"""The five BASELINE.json benchmark scenarios (SURVEY.md §6).

The real datasets (NYC Yellow Taxi 2019-01, TPC-H SF100 lineitem, Criteo
day-0) are not downloadable in a zero-egress environment, so each
scenario generates a synthetic stand-in with the same shape, dtype mix,
and distribution character, clearly labeled as such.  Scale factors let
the same script run as a seconds-long smoke or a full-size soak.

Scenario -> BASELINE.json config mapping:
  taxi      -> "NYC Yellow Taxi 2019-01 (~7M rows, 18 cols), CPU ref"
  tpch      -> "TPC-H SF100 lineitem (600M rows) numeric moments+quantiles"
  criteo    -> "Criteo day-0 (45M rows, 39 cols) mixed int/cat with HLL"
  wide1b    -> "Synthetic 1Bx200 float32 — fused moments+KLL+Pearson"
  streaming -> "Kafka→Arrow 10k-row micro-batches, running KLL/HLL merge"
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def taxi_batch(rng: np.random.Generator, rows: int) -> pd.DataFrame:
    """18 mixed columns shaped like the yellow-taxi trip records."""
    pickup = pd.Timestamp("2019-01-01") + pd.to_timedelta(
        rng.integers(0, 31 * 86400, rows), unit="s")
    trip_secs = rng.gamma(2.0, 420.0, rows)
    distance = rng.exponential(2.9, rows)
    fare = 2.5 + distance * 2.5 + rng.normal(0, 1.5, rows)
    tip = np.where(rng.random(rows) < 0.6, fare * 0.2, 0.0)
    return pd.DataFrame({
        "vendor_id": rng.choice(["CMT", "VTS"], rows),
        "pickup_datetime": pickup,
        "dropoff_datetime": pickup + pd.to_timedelta(trip_secs, unit="s"),
        "passenger_count": rng.integers(1, 7, rows).astype(np.int8),
        "trip_distance": distance.astype(np.float32),
        "rate_code": rng.choice([1, 2, 3, 4, 5, 99], rows,
                                p=[.9, .04, .02, .02, .01, .01]).astype(np.int8),
        "store_and_fwd_flag": rng.random(rows) < 0.01,
        "pu_location": rng.integers(1, 266, rows).astype(np.int16),
        "do_location": rng.integers(1, 266, rows).astype(np.int16),
        "payment_type": rng.choice(["card", "cash", "no charge", "dispute"],
                                   rows, p=[.7, .28, .01, .01]),
        "fare_amount": fare.astype(np.float32),
        "extra": rng.choice([0.0, 0.5, 1.0], rows).astype(np.float32),
        "mta_tax": np.full(rows, 0.5, dtype=np.float32),
        "tip_amount": tip.astype(np.float32),
        "tolls_amount": np.where(rng.random(rows) < 0.05, 5.76, 0.0
                                 ).astype(np.float32),
        "improvement_surcharge": np.full(rows, 0.3, dtype=np.float32),
        "total_amount": (fare + tip + 0.8).astype(np.float32),
        "congestion_surcharge": np.where(pickup.month == 1, 2.5, 0.0
                                         ).astype(np.float32),
    })


def tpch_lineitem_batch(rng: np.random.Generator, rows: int) -> pd.DataFrame:
    """Numeric-only slice of lineitem: moments+quantiles workload."""
    qty = rng.integers(1, 51, rows).astype(np.float32)
    price = (qty * rng.uniform(900, 105000 / 50, rows)).astype(np.float32)
    return pd.DataFrame({
        "l_quantity": qty,
        "l_extendedprice": price,
        "l_discount": rng.integers(0, 11, rows).astype(np.float32) / 100,
        "l_tax": rng.integers(0, 9, rows).astype(np.float32) / 100,
        "l_orderkey": rng.integers(1, 6_000_000, rows),
        "l_partkey": rng.integers(1, 200_000, rows),
        "l_suppkey": rng.integers(1, 10_000, rows),
    })


def criteo_batch(rng: np.random.Generator, rows: int) -> pd.DataFrame:
    """39 columns: 1 label + 13 ints (heavy-tailed, nullable) + 25 hashed
    categoricals (string, high cardinality — the HLL workload)."""
    data = {"label": (rng.random(rows) < 0.03).astype(np.int8)}
    for i in range(13):
        v = rng.zipf(1.7, rows).astype(np.float32)
        v[rng.random(rows) < 0.3] = np.nan           # Criteo-style missing
        data[f"i{i:02d}"] = v
    for i in range(25):
        card = [100, 1000, 10_000, 100_000][i % 4]
        codes = rng.zipf(1.3, rows) % card
        data[f"c{i:02d}"] = np.char.add("v", codes.astype(str))
    return pd.DataFrame(data)


def wide_batch(rng: np.random.Generator, rows: int,
               cols: int = 200) -> np.ndarray:
    """1B×200 float32 scan workload (in-memory batches; never a file)."""
    return rng.normal(50.0, 10.0, (rows, cols)).astype(np.float32)


def mixed23_batch(rng: np.random.Generator, rows: int) -> pd.DataFrame:
    """The 23-mixed-column host-prep fixture (PERF.md cost model): 6 f32
    + 3 nullable f64 + 4 i64 + i8 + bool + 3 low-card cats + 1 hicard
    string + 2 dates + nullable f32 + nullable cat — every decode path
    prepare_batch has (zero-copy numerics, dictionary hashing, the
    row-hash fast path, date ints, null masks) is on the clock."""
    d = {}
    for i in range(6):
        d[f"f32_{i}"] = rng.normal(50, 10, rows).astype(np.float32)
    for i in range(3):
        v = rng.normal(0, 1, rows)
        v[rng.random(rows) < 0.1] = np.nan
        d[f"f64_{i}"] = v
    for i in range(4):
        d[f"i64_{i}"] = rng.integers(0, 1_000_000, rows)
    d["i8"] = rng.integers(0, 100, rows).astype(np.int8)
    d["flag"] = rng.random(rows) < 0.5
    for i in range(3):
        d[f"cat_{i}"] = rng.choice(["a", "bb", "ccc", "dddd", "eeeee"],
                                   rows)
    d["hicard"] = np.char.add("id",
                              rng.integers(0, 10**9, rows).astype(str))
    for i in range(2):
        d[f"date_{i}"] = pd.Timestamp("2020-01-01") + pd.to_timedelta(
            rng.integers(0, 10**7, rows), unit="s")
    v = rng.normal(0, 1, rows)
    v[rng.random(rows) < 0.3] = np.nan
    d["nullable"] = v.astype(np.float32)
    d["cat_null"] = pd.Series(rng.choice(["x", "y", "z", None], rows))
    return pd.DataFrame(d)


GENERATORS = {
    "taxi": (taxi_batch, 7_000_000),
    "tpch": (tpch_lineitem_batch, 600_000_000),
    "criteo": (criteo_batch, 45_000_000),
}
