"""Benchmark harness: ``python benchmarks/run.py <scenario> [--scale S]``.

Scenarios map 1:1 to BASELINE.json's configs (see scenarios.py).  Each
prints a JSON line with rows/sec and wall-clock; ``--scale`` shrinks the
nominal row counts (default 0.01 — a smoke-sized run; use 1.0 for the
full-size soak on real hardware).

taxi/tpch/criteo write a Parquet fixture once (cached in --workdir) and
profile it end-to-end through ProfileReport (both scans + render).
wide1b streams in-memory batches through the fused pass-A step (the
scan-throughput number bench.py also reports).  streaming feeds 10k-row
micro-batches through StreamingProfiler with periodic snapshots.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fixture_path(workdir: str, name: str, rows: int) -> str:
    os.makedirs(workdir, exist_ok=True)
    return os.path.join(workdir, f"{name}_{rows}.parquet")


def _ensure_fixture(name: str, rows: int, workdir: str) -> str:
    import pyarrow as pa
    import pyarrow.parquet as pq

    from benchmarks import scenarios

    path = _fixture_path(workdir, name, rows)
    if os.path.exists(path):
        return path
    gen, _ = scenarios.GENERATORS[name]
    rng = np.random.default_rng(0)
    writer = None
    chunk = 1 << 18
    written = 0
    while written < rows:
        df = gen(rng, min(chunk, rows - written))
        table = pa.Table.from_pandas(df, preserve_index=False)
        if writer is None:
            writer = pq.ParquetWriter(path, table.schema)
        writer.write_table(table)
        written += len(df)
    writer.close()
    return path


def run_table_scenario(name: str, scale: float, workdir: str,
                       backend: str, exact_distinct: bool = False) -> dict:
    from tpuprof import ProfileReport, ProfilerConfig

    from benchmarks import scenarios

    _, nominal = scenarios.GENERATORS[name]
    rows = max(int(nominal * scale), 10_000)
    path = _ensure_fixture(name, rows, workdir)
    kw = {}
    if exact_distinct:
        kw = {"exact_distinct": True,
              "unique_spill_dir": os.path.join(workdir, "uniq_spill")}

    def _config():
        return ProfilerConfig(backend=backend, **kw)

    t0 = time.perf_counter()
    report = ProfileReport(path, config=_config())
    out = os.path.join(workdir, f"{name}_report.html")
    report.to_file(out)
    cold = time.perf_counter() - t0
    # warm runs in-process: XLA programs are compiled, so this is the
    # steady-state rate (the first run pays ~20-40s of compiles; a real
    # deployment pays them once per schema thanks to the jit cache).
    # Best of two — the tunnel occasionally stalls a single run by an
    # order of magnitude (PERF.md round-3 scenario note), which is
    # environment weather, not framework cost.
    warm = float("inf")
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        report = ProfileReport(path, config=_config())
        report.to_file(out)
        el = time.perf_counter() - t0
        if el < warm:
            warm, best = el, report     # phases must describe the SAME
    n = best.description["table"]["n"]  # run as the reported rate
    # each profile's phase timings ride its stats dict (backends reset
    # the process-global totals per collect)
    phases = {k: round(v, 2) for k, v in sorted(
        (best.description.get("_phases") or {}).items())}
    return {"scenario": name, "rows": n,
            "cols": best.description["table"]["nvar"],
            "seconds": round(warm, 3),
            "rows_per_sec": round(n / warm, 1),
            "cold_seconds": round(cold, 3),
            "cold_rows_per_sec": round(n / cold, 1),
            "phases_warm": phases}


def run_wide1b(scale: float, workdir: str, backend: str) -> dict:
    import jax

    from benchmarks import scenarios
    from tpuprof.config import ProfilerConfig
    from tpuprof.ingest.arrow import HostBatch
    from tpuprof.runtime.mesh import MeshRunner

    total_rows = max(int(1e9 * scale), 1 << 18)
    # a fake multi-device CPU mesh timeshares nproc cores; TPU-sized
    # batches then starve XLA's collective rendezvous (40s hard timeout),
    # so CPU smoke runs use a batch each core can turn around quickly
    on_cpu = jax.devices()[0].platform == "cpu"
    config = ProfilerConfig(batch_rows=1 << (12 if on_cpu else 16))
    runner = MeshRunner(config, n_num=200, n_hash=0)
    rng = np.random.default_rng(0)
    n_staged = 4 if on_cpu else 16     # TPU: amortize dispatch latency
    batches = []
    for _ in range(n_staged):
        hb = HostBatch(
            nrows=runner.rows,
            # F-order, as ingest lays batches out (its transpose is the
            # zero-copy view put_batch ships — C-order would add a 50 MB
            # host transpose copy to every timed step)
            x=np.asfortranarray(scenarios.wide_batch(rng, runner.rows)),
            row_valid=np.ones(runner.rows, dtype=bool),
            hll=np.zeros((runner.rows, 0), dtype=np.uint16),
            cat_codes={}, date_ints={})
        batches.append(hb)
    state = runner.init_pass_a(np.nanmean(batches[0].x[:4096], axis=0))
    if on_cpu:
        state = runner.step_a(state, batches[0], 0)   # compile
        jax.block_until_ready(state)
        # smoke cap: the CPU-mesh rate is flat after a few dozen steps,
        # and the regression harness only needs the round-over-round
        # DELTA — 10M rows of per-step-synced fake-device folds would
        # spend 3 minutes measuring nothing extra
        steps = min(max(total_rows // runner.rows, 4), 64)
        t0 = time.perf_counter()
        for i in range(steps):
            state = runner.step_a(state, batches[i % 4], i + 1)
            # fake devices timeshare the cores: without a sync, the first
            # device reaches finalize's all-reduce while the last still
            # has queued steps, tripping XLA's 40s rendezvous abort
            jax.block_until_ready(state)
        rows = steps * runner.rows
    else:
        # HBM-staged multi-batch scan — the bench.py methodology: measures
        # the fused pass itself, with the host->device copy amortized out
        staged = runner.stage_batches(batches)
        jax.block_until_ready(staged.xts)
        state = runner.scan_a(state, staged)          # compile
        jax.device_get(state["mom"]["n"])
        dispatches = max(total_rows // (n_staged * runner.rows), 2)
        t0 = time.perf_counter()
        for _ in range(dispatches):
            state = runner.scan_a(state, staged)
        jax.device_get(state["mom"]["n"])
        rows = dispatches * n_staged * runner.rows
    elapsed = time.perf_counter() - t0
    runner.finalize_a(state)      # once-per-profile; excluded like bench.py
    return {"scenario": "wide1b", "rows": rows, "cols": 200,
            "seconds": round(elapsed, 3),
            "rows_per_sec": round(rows / elapsed, 1),
            "devices": runner.n_dev}


def run_streaming(scale: float, workdir: str, backend: str) -> dict:
    from benchmarks import scenarios
    from tpuprof.config import ProfilerConfig
    from tpuprof.runtime.stream import StreamingProfiler

    micro = 10_000                                   # BASELINE config 5
    n_batches = max(int(1000 * scale), 10)
    rng = np.random.default_rng(0)
    example = scenarios.taxi_batch(rng, 64)
    # default batch_rows (64k): ~6 micro-batches coalesce per device
    # dispatch (StreamingProfiler buffers to a full device batch) —
    # round 2 pinned batch_rows=micro, which made every 10k micro-batch
    # pay its own padded transfer + dispatch (62k rows/s, PERF.md)
    prof = StreamingProfiler.for_example(
        example, config=ProfilerConfig())
    t0 = time.perf_counter()
    for i in range(n_batches):
        prof.update(scenarios.taxi_batch(rng, micro))
        if (i + 1) % 100 == 0:
            prof.stats()                              # periodic snapshot
    stats = prof.stats()
    elapsed = time.perf_counter() - t0
    rows = stats["table"]["n"]
    return {"scenario": "streaming", "rows": rows,
            "micro_batch": micro, "seconds": round(elapsed, 3),
            "rows_per_sec": round(rows / elapsed, 1)}


def run_hostfed(scale: float, workdir: str) -> dict:
    """Tunnel-independent host-fed end-to-end profile (PERF.md round-3
    one-off, promoted to a tracked scenario — VERDICT r3 #3): an
    8-fake-device CPU mesh in a SUBPROCESS pinned to the CPU platform,
    so tunnel weather cannot pollute the number.  Profiles a 2M×50
    parquet fixture through the full ProfileReport (ingest + both scans
    + render), then streams the same rows as 10k micro-batches through
    StreamingProfiler — the streaming:batch ratio is the regression
    canary for dispatch/coalescing glue (VERDICT r3 #4)."""
    import subprocess

    rows = max(int(2_000_000 * scale), 100_000)
    fixture = os.path.join(workdir, f"hostfed_{rows}.parquet")
    if not os.path.exists(fixture):
        import pyarrow as pa
        import pyarrow.parquet as pq

        from benchmarks import scenarios
        rng = np.random.default_rng(0)
        writer = None
        left = rows
        while left > 0:
            n = min(1 << 18, left)
            x = scenarios.wide_batch(rng, n, cols=50)
            table = pa.table({f"f{i:02d}": x[:, i] for i in range(50)})
            if writer is None:
                writer = pq.ParquetWriter(fixture, table.schema)
            writer.write_table(table)
            left -= n
        writer.close()
    worker = r"""
import json, os, sys, time
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import pyarrow.parquet as pq
from tpuprof import ProfileReport, ProfilerConfig
from tpuprof.runtime.stream import StreamingProfiler

fixture, workdir = sys.argv[1], sys.argv[2]
cfg = lambda **kw: ProfilerConfig(
    backend="tpu", compile_cache_dir=os.path.join(workdir, "jax_cache_cpu"),
    **kw)
out = os.path.join(workdir, "hostfed_report.html")
t0 = time.perf_counter()
ProfileReport(fixture, config=cfg()).to_file(out)
cold = time.perf_counter() - t0
warm, best = float("inf"), None
for _ in range(2):
    t0 = time.perf_counter()
    r = ProfileReport(fixture, config=cfg())
    r.to_file(out)
    el = time.perf_counter() - t0
    if el < warm:
        warm, best = el, r
n = best.description["table"]["n"]
phases = {k: round(v, 2) for k, v in sorted(
    (best.description.get("_phases") or {}).items())}

# streaming leg: IDENTICAL feed and denominator to the single-pass
# comparand (VERDICT r4 #9): compiles warm on a THROWAWAY profiler over
# a head slice (persistent cache carries the executables), then a fresh
# profiler streams the full table and the rate divides by the same n
# the batch leg profiles
warm_rows = min(200_000, (n // 5) // 10_000 * 10_000) or 10_000
tbl = pq.read_table(fixture)
warmer = StreamingProfiler(tbl.schema, config=cfg(exact_passes=False))
for pos in range(0, warm_rows, 10_000):
    warmer.update(tbl.slice(pos, 10_000))
warmer.stats()
prof = StreamingProfiler(tbl.schema, config=cfg(exact_passes=False))
t0 = time.perf_counter()
for pos in range(0, n, 10_000):
    prof.update(tbl.slice(pos, 10_000))
prof.stats()
stream_el = time.perf_counter() - t0
# single-pass batch profile over the SAME in-memory table = streaming's
# apples-to-apples comparand (both legs memory-fed, full n; the ratio
# isolates the micro-batch glue, not parquet decode)
ProfileReport(tbl, config=cfg(exact_passes=False))      # warm this shape
t0 = time.perf_counter()
ProfileReport(tbl, config=cfg(exact_passes=False))
single = time.perf_counter() - t0
print(json.dumps({
    "scenario": "hostfed", "rows": n, "cols": 50,
    "seconds": round(warm, 3), "rows_per_sec": round(n / warm, 1),
    "cold_seconds": round(cold, 3), "phases_warm": phases,
    "stream_rows_per_sec": round(n / stream_el, 1),
    "singlepass_rows_per_sec": round(n / single, 1),
    "stream_vs_singlepass": round((n / stream_el) / (n / single), 3)}))
"""
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", worker, fixture, workdir, repo],
        env=env, capture_output=True, text=True, timeout=3600)
    if proc.returncode != 0:
        raise RuntimeError(f"hostfed worker failed:\n{proc.stderr[-2000:]}")
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


def measure_prepare(rows: int, batch_rows: int = 1 << 16,
                    repeats: int = 3, workers: "int | None" = None) -> dict:
    """Host-prep microbenchmark (no device anywhere): serial vs parallel
    ``prepare_batch`` over the 23-mixed-col fixture (PERF.md's cost-model
    shape).  Serial = decode_threads=1, the reference path; parallel =
    the per-column/per-row-chunk task pool at ``workers`` (default
    max(8, cores)).  Also times the cross-batch ``prefetch_prepared``
    pipeline at the auto width — the figure that hides under device
    scans in production.  Both modes run over identically warmed caches
    (dictionary memo, col_stats steering converged), so the ratio
    isolates the parallel decomposition, not cache luck.

    NOTE on 1-core boxes (this build machine: PERF.md 'nproc=1'): thread
    parallelism cannot exceed 1x there — the parallel figure then mostly
    reflects the zero-copy fast paths plus scheduling overhead, and the
    >=3x target is only observable on real multi-core hosts."""
    import pyarrow as pa

    from benchmarks import scenarios
    from tpuprof.ingest.arrow import ArrowIngest, prepare_batch, \
        prefetch_prepared

    rng = np.random.default_rng(0)
    df = scenarios.mixed23_batch(rng, rows)
    table = pa.Table.from_pandas(df, preserve_index=False)
    batch_rows = min(batch_rows, rows)
    w = workers if workers is not None else max(8, os.cpu_count() or 1)

    def loop_mode(decode_threads):
        ing = ArrowIngest(table, batch_rows=batch_rows)
        rbs = [rb for _, _, rb in ing.raw_batches_positioned()]
        def one_pass():
            for rb in rbs:
                prepare_batch(rb, ing.plan, batch_rows, 11,
                              dict_cache=ing._dict_cache,
                              col_stats=ing._col_stats,
                              decode_threads=decode_threads)
        one_pass()              # warm: native build, memos, steering
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            one_pass()
            best = min(best, time.perf_counter() - t0)
        return rows / best

    def pipeline_mode():
        ing = ArrowIngest(table, batch_rows=batch_rows)
        for hb in prefetch_prepared(ing, ing.plan, batch_rows, 11):
            pass                # warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for hb in prefetch_prepared(ing, ing.plan, batch_rows, 11):
                pass
            best = min(best, time.perf_counter() - t0)
        return rows / best

    serial = loop_mode(1)
    parallel = loop_mode(w)
    pipelined = pipeline_mode()
    # ROADMAP item 3: the multi-core prepare scaling curve has never
    # been observed (every round so far ran on a 1-core box).  Record
    # it whenever a capable runner finally executes this harness, and
    # leave an EXPLICIT marker otherwise — a silent gap would read as
    # "measured, flat" instead of "never measured".
    cpus = os.cpu_count() or 1
    if cpus >= 8:
        worker_scaling = [
            {"workers": wk, "rows_per_sec": round(loop_mode(wk), 1)}
            for wk in (1, 2, 4, 8)]
    else:
        worker_scaling = f"skipped: {cpus} core" \
            + ("" if cpus == 1 else "s")
    return {
        "rows": rows, "cols": table.num_columns,
        "prepare_rows_per_sec": round(parallel, 1),
        "serial_rows_per_sec": round(serial, 1),
        "parallel_rows_per_sec": round(parallel, 1),
        "pipelined_rows_per_sec": round(pipelined, 1),
        "speedup": round(parallel / serial, 3),
        "workers": w,
        "cpus": cpus,
        "worker_scaling": worker_scaling,
    }


def run_prepare(scale: float, workdir: str) -> dict:
    rows = max(int(50_000_000 * scale), 100_000)
    out = measure_prepare(rows)
    out["scenario"] = "prepare"
    return out


def measure_wide_exact(rows: int, cols: int = 200,
                       batch_rows: int = 1 << 16) -> dict:
    """Exact-distinct cost at the wide shape, host path in isolation
    (the PERF.md round-5 methodology promoted to a tracked leg —
    ISSUE 8): near-all-distinct f32 lanes, no device anywhere.

    * sketch leg: ``prepare_batch`` without full hashes — the host cost
      of the HLL tier (the 1× comparand).
    * exact leg: ``prepare_batch`` with full hashes + the tracker feed
      + resolve, under the PRODUCTION defaults (RAM-derived "auto"
      global budget, partitioned tracker, overlapped spill writes) —
      ``exact_distinct_overhead_x`` = exact total / sketch.
    * spill leg: the tracker feed again with the global budget forced
      to a third of the stream, so the spill path (radix scatter +
      partitioned runs + overlapped tofile) stays on the clock at
      every ``--scale`` even when "auto" swallows the whole stream.

    Every stage is best-of-2 on warmed caches."""
    import tempfile

    import pyarrow as pa

    from benchmarks import scenarios
    from tpuprof.config import (resolve_spill_workers,
                                resolve_unique_budget,
                                resolve_unique_partitions)
    from tpuprof.ingest.arrow import ArrowIngest, prepare_batch
    from tpuprof.kernels.unique import UniqueTracker

    rng = np.random.default_rng(0)
    names = [f"f{i:03d}" for i in range(cols)]
    xs = scenarios.wide_batch(rng, rows, cols=cols)
    table = pa.table({nm: xs[:, i] for i, nm in enumerate(names)})
    batch_rows = min(batch_rows, rows)

    def prep_pass(full):
        ing = ArrowIngest(table, batch_rows=batch_rows)
        rbs = [rb for _, _, rb in ing.raw_batches_positioned()]

        def one():
            return [prepare_batch(rb, ing.plan, batch_rows, 11,
                                  dict_cache=ing._dict_cache,
                                  col_stats=ing._col_stats,
                                  decode_threads=1, full_hashes=full)
                    for rb in rbs]

        one()                                   # warm
        best, hbs = float("inf"), None
        for _ in range(2):
            t0 = time.perf_counter()
            out = one()
            el = time.perf_counter() - t0
            if el < best:
                best, hbs = el, out
        return best, hbs

    sketch_s, _ = prep_pass(False)
    prep_exact_s, hbs = prep_pass(True)

    partitions = resolve_unique_partitions(None)
    workers = resolve_spill_workers(None)
    auto_budget = resolve_unique_budget("auto")

    def tracker_pass(total_budget):
        best, result = float("inf"), {}
        for _ in range(2):
            with tempfile.TemporaryDirectory() as td:
                t = UniqueTracker(names, 1 << 22, total_budget,
                                  spill_dir=os.path.join(td, "sp"),
                                  count_exact=True,
                                  partitions=partitions,
                                  spill_workers=workers)
                t0 = time.perf_counter()
                for hb in hbs:
                    nh = hb.num_hashes or {}
                    for nm in names:
                        h, valid = nh[nm]
                        t.update(nm, h if valid is None else h[valid])
                t.flush_spills()
                feed = time.perf_counter() - t0
                spill_rows = sum(r for runs in t._runs.values()
                                 for _p, r in runs)
                t0 = time.perf_counter()
                counts = t.distinct_counts()
                t.resolve()
                resolve_s = time.perf_counter() - t0
                if feed + resolve_s < best:
                    best = feed + resolve_s
                    result = {"tracker_s": feed, "resolve_s": resolve_s,
                              "spill_bytes": spill_rows * 8,
                              "distinct_total": int(sum(counts.values()))}
                t.cleanup()
        return result

    exact = tracker_pass(auto_budget)
    spill_budget = min(1 << 25, rows * cols // 3)
    spilly = tracker_pass(spill_budget)

    exact_total = prep_exact_s + exact["tracker_s"] + exact["resolve_s"]
    return {
        "rows": rows, "cols": cols,
        "sketch_s": round(sketch_s, 3),
        "prep_exact_s": round(prep_exact_s, 3),
        "tracker_s": round(exact["tracker_s"], 3),
        "resolve_s": round(exact["resolve_s"], 3),
        "exact_total_s": round(exact_total, 3),
        "exact_distinct_overhead_x": round(exact_total / sketch_s, 2),
        "unique_budget_rows": int(auto_budget),
        "unique_partitions": partitions,
        "unique_spill_workers": workers,
        "spill_tracker_s": round(spilly["tracker_s"], 3),
        "spill_resolve_s": round(spilly["resolve_s"], 3),
        "spill_budget_rows": int(spill_budget),
        "spill_bytes": int(spilly["spill_bytes"]),
        "distinct_total": exact["distinct_total"],
        "rows_per_sec": round(rows / exact_total, 1),
    }


def run_wideexact(scale: float, workdir: str) -> dict:
    # nominal = the PERF.md wide shape (512k x 200); the floor keeps
    # the smoke-scale leg representative (the tracked signal is the
    # overhead RATIO, which is far less scale-sensitive than the rates)
    rows = max(int(524_288 * scale), 131_072)
    out = measure_wide_exact(rows)
    out["scenario"] = "wideexact"
    return out


def measure_guardrail(rows: int = 1 << 17, repeats: int = 3) -> dict:
    """Clean-path cost of the fault-tolerance plumbing (ISSUE 4
    acceptance: <1%): the same serial prepare loop timed (a) calling
    ``prepare_batch`` directly and (b) through the production
    ``BatchGuard.run`` wrapper (retry policy + fault hook — what every
    batch now pays), plus the v5 checkpoint CRC's share of a save.
    ``guardrail_overhead_pct`` is the prepare-loop delta; per-batch
    plumbing is nanoseconds against ~10ms of decode, so anything
    persistently >1% is a regression in the guard itself."""
    import pickle
    import time as _time
    import zlib

    import pyarrow as pa

    from benchmarks import scenarios
    from tpuprof.ingest.arrow import ArrowIngest, prepare_batch
    from tpuprof.runtime import guard

    rng = np.random.default_rng(0)
    batch_rows = min(1 << 16, rows)
    df = scenarios.mixed23_batch(rng, rows)
    table = pa.Table.from_pandas(df, preserve_index=False)
    ing = ArrowIngest(table, batch_rows=batch_rows)
    rbs = [rb for _, _, rb in ing.raw_batches_positioned()]
    bg = guard.BatchGuard(retries=2, backoff_s=0.05, capture=False)

    def body(guarded: bool) -> None:
        for k, rb in enumerate(rbs):
            if guarded:
                bg.run(lambda rb=rb: prepare_batch(
                    rb, ing.plan, batch_rows, 11,
                    dict_cache=ing._dict_cache,
                    col_stats=ing._col_stats, decode_threads=1),
                    site="prep", key=k, rows=rb.num_rows)
            else:
                prepare_batch(rb, ing.plan, batch_rows, 11,
                              dict_cache=ing._dict_cache,
                              col_stats=ing._col_stats,
                              decode_threads=1)

    # warm both modes over the same converged caches, then interleave
    # the timed passes so cache/CPU weather hits both sides equally.
    # The A/B delta is a SANITY figure only — at smoke scale it sits
    # inside this box's ±3% noise band, far above the true wrapper
    # cost, so the acceptance number comes from the isolated
    # microbench below instead.
    body(False)
    body(True)
    best = {False: float("inf"), True: float("inf")}
    for _ in range(repeats):
        for mode in (False, True):
            t0 = _time.perf_counter()
            body(mode)
            best[mode] = min(best[mode], _time.perf_counter() - t0)
    direct = rows / best[False]
    guarded = rows / best[True]
    ab_delta_pct = (direct - guarded) / direct * 100.0

    # the actual plumbing cost, measured where it is measurable: the
    # per-call price of BatchGuard.run around a no-op (lambda + fault
    # hook + try/except), against the per-batch prepare time it wraps
    def _noop():
        return None

    reps = 20000
    t0 = _time.perf_counter()
    for k in range(reps):
        bg.run(_noop, site="prep", key=k)
    guarded_call_s = (_time.perf_counter() - t0) / reps
    t0 = _time.perf_counter()
    for _ in range(reps):
        _noop()
    direct_call_s = (_time.perf_counter() - t0) / reps
    wrapper_s = max(guarded_call_s - direct_call_s, 0.0)
    prep_batch_s = best[False] / max(len(rbs), 1)
    overhead_pct = wrapper_s / prep_batch_s * 100.0

    # CRC share of a checkpoint save: the only new per-save byte work
    payload = pickle.dumps({"arrays": np.zeros(1 << 20, np.float32)},
                           protocol=pickle.HIGHEST_PROTOCOL)
    t0 = _time.perf_counter()
    for _ in range(5):
        zlib.crc32(payload)
    crc_gbps = 5 * len(payload) / (_time.perf_counter() - t0) / 1e9

    # watchdog: the unwatched path is a direct call (free); the watched
    # path spawns one thread per DRAIN, not per batch — report its
    # per-call cost so the tradeoff stays written down
    t0 = _time.perf_counter()
    for _ in range(50):
        guard.watched(lambda: None, 5.0, site="bench")
    watched_us = (_time.perf_counter() - t0) / 50 * 1e6

    # flight recorder (ISSUE 5): per-event ring-append cost against the
    # per-batch prepare it instruments.  The production rate on the
    # prepare leg is ~2 records/batch (dispatch milestone + span close,
    # both batch-granular, never per-row), so the overhead bound is
    # 2 * record cost / prepare cost — acceptance: < 0.5%.
    from tpuprof.obs.blackbox import BlackBox
    box = BlackBox(512)
    reps_bb = 20000
    t0 = _time.perf_counter()
    for k in range(reps_bb):
        box.record("dispatch", program="scan_a", key=k)
    record_s = (_time.perf_counter() - t0) / reps_bb
    blackbox_pct = 2 * record_s / prep_batch_s * 100.0

    return {
        "rows": rows, "cols": table.num_columns,
        "rows_per_sec": round(guarded, 1),      # generic delta column
        "guarded_rows_per_sec": round(guarded, 1),
        "direct_rows_per_sec": round(direct, 1),
        "ab_delta_pct": round(ab_delta_pct, 3),
        "guard_wrapper_us_per_batch": round(wrapper_s * 1e6, 3),
        "guardrail_overhead_pct": round(overhead_pct, 4),
        "checkpoint_crc_gbps": round(crc_gbps, 2),
        "watchdog_watched_call_us": round(watched_us, 1),
        "blackbox_record_us": round(record_s * 1e6, 3),
        "blackbox_overhead_pct": round(blackbox_pct, 4),
    }


def run_faults(scale: float, workdir: str) -> dict:
    rows = max(int(20_000_000 * scale), 100_000)
    out = measure_guardrail(rows)
    out["scenario"] = "faults"
    return out


def run_passb(scale: float, workdir: str) -> dict:
    """Pass-B dispatch microbenchmark (ISSUE 3): the histogram+MAD fold
    alone, A/B'd across the two binning formulations on the current
    mesh, with bounds derived on device from a folded pass-A state (the
    production recipe).  On the CPU regression mesh the absolute rates
    are smoke-scale; the tracked signals are the round-over-round DELTA
    of ``pass_b_rows_per_sec`` and the cumulative:legacy ratio."""
    import time as _time

    import jax

    from tpuprof.config import ProfilerConfig, resolve_pass_b_kernel
    from tpuprof.runtime.mesh import MeshRunner

    on_cpu = jax.devices()[0].platform == "cpu"
    batch_rows = 1 << (12 if on_cpu else 16)
    cols = 50
    total_rows = max(int(2e8 * scale), 1 << 17)
    rng = np.random.default_rng(0)

    def measure(kernel):
        runner = MeshRunner(ProfilerConfig(batch_rows=batch_rows,
                                           pass_b_kernel=kernel),
                            n_num=cols, n_hash=0)
        from tpuprof.ingest.arrow import HostBatch
        hb = HostBatch(
            nrows=runner.rows,
            x=np.asfortranarray(
                rng.normal(50, 10, (runner.rows, cols)).astype(np.float32)),
            row_valid=np.ones(runner.rows, dtype=bool),
            hll=np.zeros((runner.rows, 0), dtype=np.uint16),
            cat_codes={}, date_ints={})
        state_a = runner.init_pass_a(np.full(cols, 50.0, np.float32))
        state_a = runner.step_a(state_a, hb)
        lo_d, hi_d, mean_d = runner.bounds_b_device(state_a)
        db = runner.put_batch(hb, with_hll=False)
        state = runner.step_b(runner.init_pass_b(), db, lo_d, hi_d,
                              mean_d)                       # compile
        jax.block_until_ready(state)
        steps = min(max(total_rows // runner.rows, 4), 64)
        t0 = _time.perf_counter()
        for _ in range(steps):
            state = runner.step_b(state, db, lo_d, hi_d, mean_d)
            # fake CPU devices timeshare cores — sync per step, as the
            # wide1b leg does, so no device outruns the others
            jax.block_until_ready(state)
        elapsed = _time.perf_counter() - t0
        return steps * runner.rows / elapsed

    cum = measure("cumulative")
    leg = measure("legacy")
    return {"scenario": "passb", "rows": total_rows, "cols": cols,
            "pass_b_rows_per_sec": round(cum, 1),
            "rows_per_sec": round(cum, 1),  # the generic delta column
            "pass_b_legacy_rows_per_sec": round(leg, 1),
            "pass_b_cumulative_vs_legacy": round(cum / leg, 3),
            "default_kernel": resolve_pass_b_kernel(None)}


def measure_drift(rows: int, batch_rows: int = 1 << 12,
                  aot_dir: "str | None" = None) -> dict:
    """Artifact + incremental + diff costs (ISSUE 6): write/read seconds
    for a fold-able stats artifact, the incremental-vs-full speedup
    (resume(artifact) + profile(delta) vs re-profiling the whole
    window), and the `tpuprof diff` compute time.  Micro-batches are
    device-batch aligned so the incremental leg runs the byte-stable
    path (ARTIFACTS.md).  Shared by the `drift` scenario and bench.py."""
    import tempfile

    import pandas as pd

    from benchmarks import scenarios
    from tpuprof import ProfilerConfig
    from tpuprof.artifact import (compute_drift, read_artifact,
                                  resume_profiler, write_artifact)
    from tpuprof.runtime.stream import StreamingProfiler

    def _batches(seed, n_batches, per_batch):
        rng = np.random.default_rng(seed)
        return [scenarios.taxi_batch(rng, per_batch)
                for _ in range(n_batches)]

    cfg = ProfilerConfig(batch_rows=batch_rows, aot_cache_dir=aot_dir)
    probe = StreamingProfiler.for_example(
        scenarios.taxi_batch(np.random.default_rng(0), 64), config=cfg)
    per_batch = probe.runner.rows          # aligned micro-batches
    n_total = max(rows // per_batch, 8)
    n_base = max(n_total * 3 // 4, 1)      # window A; delta = the rest
    base_b = _batches(0, n_base, per_batch)
    delta_b = _batches(1, n_total - n_base, per_batch)

    # warm the compiled programs so neither leg pays first-compile
    for b in base_b[:2]:
        probe.update(b)
    probe.stats()

    with tempfile.TemporaryDirectory() as td:
        art_a = os.path.join(td, "a.artifact.json")
        art_b = os.path.join(td, "b.artifact.json")

        prof_a = StreamingProfiler.for_example(base_b[0], config=cfg)
        for b in base_b:
            prof_a.update(b)
        t0 = time.perf_counter()
        write_artifact(art_a, profiler=prof_a)
        write_s = time.perf_counter() - t0
        art_bytes = os.path.getsize(art_a)

        t0 = time.perf_counter()
        read_artifact(art_a)
        read_s = time.perf_counter() - t0

        # incremental: stored_state ⊕ profile(delta)
        t0 = time.perf_counter()
        inc = resume_profiler(art_a)
        for b in delta_b:
            inc.update(b)
        write_artifact(art_b, profiler=inc)
        incremental_s = time.perf_counter() - t0

        # full re-profile of the whole window
        t0 = time.perf_counter()
        full = StreamingProfiler.for_example(base_b[0], config=cfg)
        for b in base_b + delta_b:
            full.update(b)
        full.stats()
        full_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        drift = compute_drift(read_artifact(art_a), read_artifact(art_b))
        diff_s = time.perf_counter() - t0

    total_rows = n_total * per_batch
    return {
        "rows": total_rows,
        "delta_rows": len(delta_b) * per_batch,
        "artifact_bytes": art_bytes,
        "artifact_write_s": round(write_s, 4),
        "artifact_read_s": round(read_s, 4),
        "incremental_s": round(incremental_s, 3),
        "full_s": round(full_s, 3),
        "incremental_vs_full_speedup": round(full_s / incremental_s, 3),
        "drift_compute_s": round(diff_s, 4),
        "drift_verdict": drift["summary"]["verdict"],
        # generic delta column: rows the incremental path "covered"
        # (stored window + delta) per second of incremental work
        "rows_per_sec": round(total_rows / incremental_s, 1),
    }


def run_drift(scale: float, workdir: str) -> dict:
    # the ISSUE-9 runner cache removed this leg's rebuild storm (probe,
    # window A, resume and full re-profile now share ONE runner), but
    # the persistent DISK cache still has to stay off here: re-tested
    # with reuse in place, this box's jaxlib corrupts its abseil
    # mutexes ("Mutex corrupt: both reader and writer lock held") with
    # the cache enabled during this streaming+npz-shaped leg even with
    # a single build.  In-process warm starts come from the runner
    # cache anyway; CROSS-round warm starts come from the app-level
    # AOT executable store under --workdir (ISSUE 15) — which never
    # touches the jaxlib persistent-cache code path, so it restores
    # the restart warmth this leg lost without re-arming the aborts.
    from tpuprof.backends.tpu import disable_compile_cache
    disable_compile_cache()
    os.makedirs(workdir, exist_ok=True)
    rows = max(int(20_000_000 * scale), 100_000)
    out = measure_drift(rows, aot_dir=os.path.join(workdir, "aot"))
    out["scenario"] = "drift"
    return out


def measure_rebalance(rows: int, n_frags: int = 6,
                      aot_dir: "str | None" = None) -> dict:
    """Elastic fleet cost envelope (ISSUE 7).  Two figures:

    * ``steal_overhead_pct`` — clean-path cost of running the SAME
      profile through the elastic claim/contribute/finish machinery
      (one member, nobody dies) vs the static stripe, A/B'd in one
      process.  Acceptance bound <1% like ``guardrail_overhead_pct``;
      at smoke scale the noise band swallows the true cost, so the
      signal is 'persistently above 1%', not any single round.
    * ``rebalance_latency_s`` — wall time for a survivor's finish
      barrier to detect a departed member (deleted heartbeat), steal
      its claimed fragments, replay them (host-side re-read), and
      reach full coverage — the scheduler's contribution to recovery,
      excluding device folds (those are the same folds any scan pays).
    """
    import tempfile

    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    from benchmarks import scenarios
    from tpuprof import ProfilerConfig
    from tpuprof.backends.tpu import (TPUStatsBackend,
                                      disable_compile_cache)
    from tpuprof.ingest.arrow import ArrowIngest
    from tpuprof.runtime import fleet as fleetrt

    # same belt-and-suspenders as run_drift: the ISSUE-9 runner cache
    # means the warm/static/elastic collects share one runner (no more
    # rebuild storm), but this box's jaxlib has aborted with the
    # persistent DISK cache on in multi-profiler legs, and the disk
    # cache buys an in-process leg nothing the runner cache doesn't
    disable_compile_cache()
    rng = np.random.default_rng(0)
    per_frag = max(rows // n_frags, 256)
    with tempfile.TemporaryDirectory() as td:
        ds = os.path.join(td, "ds")
        os.makedirs(ds)
        for f in range(n_frags):
            pq.write_table(pa.Table.from_pandas(
                scenarios.taxi_batch(rng, per_frag),
                preserve_index=False), os.path.join(ds, f"p{f}.parquet"))

        def run(elastic: bool, tag: str) -> float:
            cfg = ProfilerConfig(
                backend="tpu", batch_rows=1 << 12, elastic=elastic,
                aot_cache_dir=aot_dir,
                fleet_dir=os.path.join(td, f"fleet_{tag}")
                if elastic else None,
                fleet_host_id="bench" if elastic else None)
            t0 = time.perf_counter()
            TPUStatsBackend().collect(ds, cfg)
            return time.perf_counter() - t0

        run(False, "warm")              # compile warm-up: neither leg
        static_s = run(False, "static")  # pays first-compile
        elastic_s = run(True, "elastic")
        overhead_pct = (elastic_s - static_s) / static_s * 100

        # rebalance latency at the scheduler level: a departed member
        # holds 2 uncontributed claims; the survivor's finish barrier
        # must notice, steal, replay (host re-read) and cover
        fdir = os.path.join(td, "fleet_lat")
        ingest = ArrowIngest(ds, 1 << 12)
        fp = ingest.fingerprint()
        dead = fleetrt.FleetMember(fdir, "dead", n_frags, fp,
                                   liveness_timeout_s=5.0)
        assert dead.claim_next("a") == 0 and dead.claim_next("a") == 1
        dead.depart()
        survivor = fleetrt.FleetMember(fdir, "live", n_frags, fp,
                                       liveness_timeout_s=5.0)
        while survivor.claim_next("a") is not None:
            pass

        def replay(frags):
            n = sum(rb.num_rows for fi in frags
                    for _f, _b, rb in ingest.read_fragment(fi))
            return {"rows": int(n)}

        t0 = time.perf_counter()
        parts = survivor.finish("a", {"rows": 0},
                                sorted(survivor.claimed("a")),
                                replay, timeout_s=60)
        latency_s = time.perf_counter() - t0
        survivor.close()
        stolen = sum(len(p["fragments"]) for p in parts
                     if p["host"] == "live" and p["seq"] > 0)

    total_rows = per_frag * n_frags
    return {
        "rows": total_rows,
        "fragments": n_frags,
        "static_s": round(static_s, 3),
        "elastic_s": round(elastic_s, 3),
        "steal_overhead_pct": round(overhead_pct, 4),
        "rebalance_latency_s": round(latency_s, 4),
        "fragments_stolen": int(stolen),
        "rows_per_sec": round(total_rows / elastic_s, 1),
    }


def run_rebalance(scale: float, workdir: str) -> dict:
    # cross-round restart warmth through the AOT store (the run_drift
    # rationale — the jaxlib disk cache stays off, the app-level store
    # replaces what it used to provide)
    os.makedirs(workdir, exist_ok=True)
    rows = max(int(5_000_000 * scale), 20_000)
    out = measure_rebalance(rows,
                            aot_dir=os.path.join(workdir, "aot"))
    out["scenario"] = "rebalance"
    return out


def measure_serve(rows: int, workdir: str, warm_jobs: int = 4,
                  concurrent: int = 4) -> dict:
    """Profile-as-a-service envelope (ISSUE 9): one ProfileScheduler
    (the `tpuprof serve` core — warm mesh + keyed compiled-program
    cache), measured on three axes:

    * cold vs warm: the FIRST job of a shape pays runner build + JIT
      compile (the 20-40 s cold start on hardware; seconds at the CPU
      smoke scale); repeat-fingerprint jobs reuse the cached runner.
      ``serve_cold_vs_warm_ratio`` is the amortization the daemon
      exists for (target >= 10x where compile dominates the wall).
    * repeat-fingerprint cache hit rate: every warm job must probe the
      cache HOT (``serve_cache_hit_rate`` = 1.0 or the keying is
      broken).
    * concurrency: ``concurrent`` mixed-shape jobs (two fixtures)
      submitted at once through one warm mesh -> requests/s and the
      p50/p99 of the scheduler's SLO view.

    The persistent DISK compile cache is disabled up front: the ratio
    must measure the daemon's in-process amortization, not a prior
    round's disk cache (and the serve leg is exactly the repeated-
    rebuild shape the per-process gate exists for)."""
    from tpuprof.backends.tpu import disable_compile_cache
    from tpuprof.serve import ProfileScheduler
    from tpuprof.serve import cache as serve_cache

    disable_compile_cache()
    fixture_a = _ensure_fixture("taxi", rows, workdir)
    fixture_b = _ensure_fixture("tpch", rows, workdir)
    out_dir = os.path.join(workdir, "serve_out")
    os.makedirs(out_dir, exist_ok=True)
    cfg = {"batch_rows": 1 << 12}

    sched = ProfileScheduler(workers=2)

    def one(src, tag):
        t0 = time.perf_counter()
        job = sched.submit(source=src,
                           output=os.path.join(out_dir, f"{tag}.html"),
                           config_kwargs=dict(cfg))
        sched.wait(job, timeout=1800)
        if job.state != "done":
            raise RuntimeError(f"serve job {tag} {job.state}: {job.error}")
        return time.perf_counter() - t0, job

    cold_s, _ = one(fixture_a, "cold_a")
    warm, hot = [], 0
    for k in range(warm_jobs):
        el, job = one(fixture_a, f"warm_{k}")
        warm.append(el)
        hot += 1 if job.cache_hit else 0
    warm_sorted = sorted(warm)
    warm_p50 = warm_sorted[(len(warm_sorted) - 1) // 2]
    cold_b_s, _ = one(fixture_b, "cold_b")     # second shape: its own cold

    # mixed-shape concurrency through the (now fully warm) mesh
    jobs = []
    t0 = time.perf_counter()
    for k in range(concurrent):
        src = fixture_a if k % 2 == 0 else fixture_b
        jobs.append(sched.submit(
            source=src, output=os.path.join(out_dir, f"conc_{k}.html"),
            config_kwargs=dict(cfg)))
    for job in jobs:
        sched.wait(job, timeout=1800)
    conc_wall = time.perf_counter() - t0
    bad = [j for j in jobs if j.state != "done"]
    if bad:
        raise RuntimeError(
            f"concurrent serve jobs failed: "
            f"{[(j.id, j.state, j.error) for j in bad]}")
    st = sched.stats()
    sched.shutdown()

    return {
        "rows": rows * 2,           # two fixtures profiled
        "serve_cold_s": round(cold_s, 3),
        "serve_cold_b_s": round(cold_b_s, 3),
        "serve_warm_p50_s": round(warm_p50, 4),
        "serve_warm_p99_s": round(warm_sorted[-1], 4),
        "serve_cold_vs_warm_ratio": round(cold_s / warm_p50, 1),
        # repeat-fingerprint jobs ONLY (acceptance: 1.0) — the overall
        # cache view (colds included) rides serve_cache below
        "serve_cache_hit_rate": round(hot / warm_jobs, 3),
        "serve_concurrent_jobs": concurrent,
        "serve_concurrent_wall_s": round(conc_wall, 3),
        "serve_requests_per_sec": round(concurrent / conc_wall, 3),
        "serve_p50_s": st["p50_s"],
        "serve_p99_s": st["p99_s"],
        "serve_cache": serve_cache.cache_stats(),
        "rows_per_sec": round(rows / warm_p50, 1),
    }


def measure_watch(rows: int, workdir: str) -> dict:
    """Continuous-drift watch envelope (ISSUE 10): 3 cycles of one
    DriftWatcher at smoke scale through a warm scheduler —

    * ``watch_cycle_s`` — steady-state cycle latency (profile +
      artifact + diff + manifest seal; cycle 2, after the cold
      compile), the figure that bounds how tight ``--every`` can go.
    * ``watch_alert_latency_s`` — wall time from a drifted delta
      landing in the source to the alert being on disk (cycle 3 runs
      against an atomically-replaced, hard-shifted fixture; the leg
      FAILS if no drift alert fires — a silent-watch regression is a
      correctness bug, not a slow round).
    * artifact rotation verified on disk (keep=2 -> exactly 2 retained
      generations after 3 cycles).

    The persistent DISK compile cache stays off (run_drift's
    rationale); the runner cache provides the in-process warmth a real
    daemon has."""
    import shutil
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from tpuprof.backends.tpu import disable_compile_cache
    from tpuprof.serve import DriftWatcher, ProfileScheduler

    disable_compile_cache()
    fixture = _ensure_fixture("taxi", rows, workdir)
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "watched.parquet")
        shutil.copyfile(fixture, src)
        spool = os.path.join(td, "spool")
        sched = ProfileScheduler(workers=1)
        watcher = DriftWatcher(spool, [src], sched, every_s=0, keep=2,
                               config_kwargs={"batch_rows": 1 << 12})
        w = watcher.watches[0]
        cold = watcher.run_cycle(w)
        warm = watcher.run_cycle(w)
        if cold["status"] != "ok" or warm["status"] != "ok":
            raise RuntimeError(f"clean watch cycles failed: "
                               f"{[cold, warm]}")
        # the drifted delta: shift every numeric column hard and
        # publish atomically, as a production pipeline would
        table = pq.read_table(src)
        import pandas as pd
        df = table.to_pandas()
        for col in df.columns:
            if df[col].dtype.kind == "f":
                df[col] = df[col] * 4.0 + 100.0
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False),
                       src + ".new")
        os.replace(src + ".new", src)
        t0 = time.perf_counter()
        drifted = watcher.run_cycle(w)
        alert_latency = time.perf_counter() - t0
        if drifted["status"] != "drift" or not w.alerts:
            raise RuntimeError(
                f"injected drift did not alert: {drifted} "
                f"(alerts: {w.alerts})")
        retained = [c for c, _ in w.chain()]
        if len(retained) != 2:
            raise RuntimeError(
                f"rotation violated keep=2 on disk: {retained}")
        sched.shutdown()
    return {
        "rows": rows,
        "watch_cold_cycle_s": round(cold["seconds"], 3),
        "watch_cycle_s": round(warm["seconds"], 4),
        "watch_alert_latency_s": round(alert_latency, 4),
        "watch_alerts": len(w.alerts),
        "watch_drift_columns": int(drifted.get("n_drift") or 0),
        "watch_retained": len(retained),
        "rows_per_sec": round(rows / warm["seconds"], 1),
    }


def measure_serve_http(rows: int, workdir: str, jobs: int = 104,
                       tenants: int = 4, daemons: int = 2,
                       kill_jobs: int = 12) -> dict:
    """Network serving plane envelope (ISSUE 11): ``daemons`` real
    `tpuprof serve --http 0` processes on ONE shared spool, driven
    over HTTP —

    * byte-identity: one HTTP-served stats export must equal the
      one-shot in-process path exactly (the leg FAILS otherwise);
    * load: ``jobs`` jobs from ``tenants`` authenticated tenants
      round-robined across both edges -> ``serve_http_rps`` and the
      p50/p99 of the per-job end-to-end latency (queue wait included
      — the SLO the submitters experience);
    * kill-one lane: a second batch accepted by BOTH edges, then one
      daemon SIGKILLed mid-load — every accepted job must still get
      exactly one result (claims go stale, the survivor steals;
      ``serve_http_killed_lost`` must be 0)."""
    import shutil
    import signal
    import subprocess

    fixture = _ensure_fixture("taxi", rows, workdir)
    spool = os.path.join(workdir, "serve_http_spool")
    shutil.rmtree(spool, ignore_errors=True)
    auth_path = os.path.join(workdir, "serve_http_tokens")
    with open(auth_path, "w") as fh:
        for k in range(tenants):
            fh.write(f"token{k} tenant{k}\n")
    cfg = {"batch_rows": 1 << 12}
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    from tpuprof.serve import (discover_edges, submit_job, wait_result,
                               wait_result_http)

    def spawn(daemon_id):
        return subprocess.Popen(
            [sys.executable, "-m", "tpuprof", "serve", spool,
             "--http", "0", "--daemon-id", daemon_id,
             "--serve-workers", "2", "--serve-queue-depth", "256",
             "--liveness-timeout", "2", "--serve-auth-file", auth_path,
             # the load lane submits IDENTICAL jobs on purpose (any
             # daemon must serve any of them) — this leg measures
             # compute throughput, so the read tier that would collapse
             # them to one compute stays off (serve_read measures it)
             "--read-cache", "off",
             "--no-compile-cache"],
            cwd=here, stderr=subprocess.DEVNULL)

    procs = {f"d{k}": spawn(f"d{k}") for k in range(daemons)}
    out: dict = {"rows": rows}
    try:
        deadline = time.monotonic() + 300
        while len(discover_edges(spool)) < daemons:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"edges never advertised: {discover_edges(spool)}")
            time.sleep(0.2)
        urls = discover_edges(spool)
        edge_list = [urls[f"d{k}"] for k in range(daemons)]

        # warm every daemon (first job pays the compile; the load
        # numbers below measure the WARM fleet, like the serve leg)
        for url in edge_list:
            _code, doc = submit_job(url, fixture, tenant="tenant0",
                                    config_kwargs=dict(cfg),
                                    token="token0")
            res = wait_result_http(url, doc["id"], timeout=1800,
                                   token="token0")
            if res["status"] != "done":
                raise RuntimeError(f"warmup failed: {res}")

        # byte-identity vs the one-shot path
        http_stats = os.path.join(workdir, "serve_http_stats.json")
        _code, doc = submit_job(edge_list[0], fixture, tenant="tenant0",
                                stats_json=http_stats,
                                config_kwargs=dict(cfg), token="token0")
        wait_result_http(edge_list[0], doc["id"], timeout=1800,
                         token="token0")
        from tpuprof import ProfileReport, ProfilerConfig
        one_shot = ProfileReport(
            fixture,
            config=ProfilerConfig(backend="tpu", **cfg)).to_json_dict()
        with open(http_stats) as fh:
            if json.load(fh) != one_shot:
                raise RuntimeError(
                    "HTTP-served stats differ from the one-shot path")

        # the load: jobs x tenants across every edge
        t0 = time.perf_counter()
        jids = []
        for k in range(jobs):
            url = edge_list[k % daemons]
            tok = f"token{k % tenants}"
            code, doc = submit_job(url, fixture,
                                   config_kwargs=dict(cfg), token=tok)
            if code != 202:
                raise RuntimeError(f"load submit {k} -> {code}: {doc}")
            jids.append(doc["id"])
        latencies = []
        for jid in jids:
            res = wait_result(spool, jid, timeout=1800)
            if res["status"] != "done":
                raise RuntimeError(f"load job {jid}: {res}")
            latencies.append(float(res["seconds"]))
        wall = time.perf_counter() - t0
        lat = sorted(latencies)
        out.update({
            "serve_http_jobs": jobs,
            "serve_http_tenants": tenants,
            "serve_http_daemons": daemons,
            "serve_http_wall_s": round(wall, 3),
            "serve_http_rps": round(jobs / wall, 2),
            "serve_http_p50_s": round(lat[(len(lat) - 1) // 2], 4),
            "serve_http_p99_s": round(
                lat[min(int(len(lat) * 0.99), len(lat) - 1)], 4),
            "rows_per_sec": round(rows * jobs / wall, 1),
        })

        # kill-one lane: accept on both edges, SIGKILL d0, count losses
        kill_jids = []
        for k in range(kill_jobs):
            url = edge_list[k % daemons]
            _code, doc = submit_job(url, fixture,
                                    config_kwargs=dict(cfg),
                                    token="token0")
            kill_jids.append(doc["id"])
        victim = procs.pop("d0")
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=60)
        t0 = time.perf_counter()
        lost = 0
        for jid in kill_jids:
            res = wait_result(spool, jid, timeout=1800)
            if res["status"] != "done":
                lost += 1
        out["serve_http_killed_lost"] = lost
        out["serve_http_kill_recovery_s"] = \
            round(time.perf_counter() - t0, 3)
        if lost:
            raise RuntimeError(
                f"kill-one lane lost {lost}/{kill_jobs} jobs")
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=120)
                except subprocess.TimeoutExpired:
                    proc.kill()
    return out


def run_serve_http(scale: float, workdir: str) -> dict:
    # small fixture on purpose (the serve-leg rationale): the tracked
    # signals are edge throughput and tail latency of a WARM fleet,
    # plus the zero-loss kill-one invariant — not scan throughput
    rows = max(int(1_000_000 * scale), 10_000)
    out = measure_serve_http(rows, workdir)
    out["scenario"] = "serve_http"
    return out


def measure_serve_read(rows: int, workdir: str, reads: int = 1600,
                       clients: int = 4, coalesce_k: int = 8) -> dict:
    """Read-path tier envelope (ISSUE 16): ONE real `tpuprof serve
    --http 0` daemon with the read tier at its product default (ON),
    driven over keep-alive HTTP —

    * miss path: the first stats export computes through the daemon
      and must be byte-identical to the one-shot in-process path (the
      leg FAILS otherwise — a cached wrong answer served fast is
      worse than no cache);
    * exactly-once: ``coalesce_k`` concurrent identical submits on a
      COLD key must compute exactly once (healthz ``computed`` delta
      == 1; the rest ride as coalesced followers or cache hits, all
      with identical answers), and a late subscriber is answered
      straight from the cache (``read_cache: "hit"``);
    * pushdown: POST /v1/query answers from the pre-fed warehouse
      generation (provenance ``warehouse``, values equal to the
      one-shot), a repeat serves from the answer cache
      (``X-Tpuprof-Provenance: cache``, same bytes), and touching the
      source past the generation recomputes (provenance ``computed``);
    * load: ``reads`` >=95%-read requests (conditional GETs + cache-
      hit submits, 95% exactly) from ``clients`` keep-alive
      connections -> ``serve_read_rps`` (must be >= 500 req/s) and
      the read-hit latency tail (p99 must be < 50 ms)."""
    import http.client
    import shutil
    import subprocess
    import threading
    from urllib.parse import urlsplit

    fixture = _ensure_fixture("taxi", rows, workdir)
    spool = os.path.join(workdir, "serve_read_spool")
    shutil.rmtree(spool, ignore_errors=True)
    cfg = {"batch_rows": 1 << 12}
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    from tpuprof import ProfileReport, ProfilerConfig
    from tpuprof.serve import discover_edges, submit_job, wait_result_http
    from tpuprof.warehouse import store

    # one-shot ground truth, profiled BEFORE the daemon spawns: it
    # seeds the warehouse generation the pushdown tier answers from
    # and is the byte-identity reference for the miss path
    report = ProfileReport(fixture,
                           config=ProfilerConfig(backend="tpu", **cfg))
    one_shot = report.to_json_dict()
    desc = report.description
    store.append_generation(os.path.join(spool, "warehouse"), fixture,
                            desc, rows=int(desc["table"]["n"]),
                            created_unix=time.time())

    proc = subprocess.Popen(
        [sys.executable, "-m", "tpuprof", "serve", spool,
         "--http", "0", "--daemon-id", "d0", "--serve-workers", "2",
         "--serve-queue-depth", "256", "--no-compile-cache"],
        cwd=here, stderr=subprocess.DEVNULL)
    out: dict = {"rows": rows}
    try:
        deadline = time.monotonic() + 300
        while "d0" not in discover_edges(spool):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"edge never advertised: {discover_edges(spool)}")
            time.sleep(0.2)
        url = discover_edges(spool)["d0"]
        parts = urlsplit(url)
        host, port = parts.hostname, parts.port

        def _req(conn, method, path, body=None, headers=None):
            payload = json.dumps(body).encode() if body is not None \
                else None
            t0 = time.perf_counter()
            conn.request(method, path, body=payload,
                         headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return (resp.status, data, dict(resp.getheaders()),
                    time.perf_counter() - t0)

        ctl = http.client.HTTPConnection(host, port, timeout=1800)

        # miss path: compute once through the daemon; the exported
        # stats must equal the one-shot in-process export exactly
        http_stats = os.path.join(workdir, "serve_read_stats.json")
        t0 = time.perf_counter()
        _code, doc = submit_job(url, fixture, stats_json=http_stats,
                                config_kwargs=dict(cfg))
        res = wait_result_http(url, doc["id"], timeout=1800)
        if res["status"] != "done":
            raise RuntimeError(f"miss-path job failed: {res}")
        out["serve_read_miss_s"] = round(time.perf_counter() - t0, 3)
        with open(http_stats) as fh:
            if json.load(fh) != one_shot:
                raise RuntimeError(
                    "read-tier miss path differs from the one-shot path")

        # seed the answer cache with one pure submit; its result is
        # the conditional-GET target for the load lane below
        _code, doc = submit_job(url, fixture, config_kwargs=dict(cfg))
        seed = wait_result_http(url, doc["id"], timeout=1800)
        if seed["status"] != "done":
            raise RuntimeError(f"seed job failed: {seed}")

        # exactly-once lane: K concurrent submits on a COLD key (a
        # config fingerprint nothing above has computed)
        cfg_cold = {"batch_rows": 1 << 11}
        _s, h0_raw, _h, _t = _req(ctl, "GET", "/v1/healthz")
        h0 = json.loads(h0_raw)
        gate = threading.Barrier(coalesce_k)
        docs: list = [None] * coalesce_k
        errs: list = []

        def _one(k):
            try:
                gate.wait(timeout=60)
                _c, d = submit_job(url, fixture,
                                   config_kwargs=dict(cfg_cold))
                docs[k] = wait_result_http(url, d["id"], timeout=1800)
            except Exception as exc:           # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=_one, args=(k,))
                   for k in range(coalesce_k)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1800)
        if errs:
            raise RuntimeError(f"coalesce lane failed: {errs[0]}")
        _s, h1_raw, _h, _t = _req(ctl, "GET", "/v1/healthz")
        h1 = json.loads(h1_raw)
        computed = h1["computed"] - h0["computed"]
        folded = ((h1["coalesced"] - h0["coalesced"])
                  + (h1["read_cache"]["hits"]
                     - h0["read_cache"]["hits"]))
        if computed != 1:
            raise RuntimeError(
                f"{coalesce_k} identical submits computed {computed}x "
                "(exactly-once violated)")
        if folded < coalesce_k - 1:
            raise RuntimeError(
                f"only {folded}/{coalesce_k - 1} submits folded onto "
                "the one compute")
        # fan-out identity: everything but the per-job lifecycle
        # fields must be byte-for-byte the one computed answer
        volatile = ("id", "seconds", "queue_seconds", "cache_hit",
                    "coalesced_with", "read_cache")
        stable = [{k: v for k, v in d.items() if k not in volatile}
                  for d in docs]
        if any(s != stable[0] for s in stable[1:]):
            raise RuntimeError("coalesced followers got different "
                               "answers")
        out["serve_read_coalesce_k"] = coalesce_k
        out["serve_read_coalesce_computed"] = computed
        out["serve_read_coalesce_folded"] = folded

        # late subscriber: answered from the cache, no recompute
        _c, d = submit_job(url, fixture, config_kwargs=dict(cfg_cold))
        late = wait_result_http(url, d["id"], timeout=1800)
        if late.get("read_cache") != "hit":
            raise RuntimeError(
                f"late subscriber was not served from cache: {late}")

        # pushdown lane: warehouse tier answers without profiling,
        # the repeat serves from the answer cache
        # numeric columns only: a categorical column has no mean to
        # push down, and the leg compares means exactly
        qcols = sorted(c for c, v in desc["variables"].items()
                       if isinstance(v, dict)
                       and v.get("mean") is not None)[:2]
        if not qcols:
            raise RuntimeError("fixture has no numeric columns")
        q = {"source": fixture, "cols": qcols, "stats": ["mean"]}
        jhdr = {"Content-Type": "application/json"}
        t0 = time.perf_counter()
        st, qraw, qh, _ = _req(ctl, "POST", "/v1/query", body=q,
                               headers=jhdr)
        out["serve_read_query_warehouse_s"] = \
            round(time.perf_counter() - t0, 4)
        qdoc = json.loads(qraw)
        if st != 200 or qdoc.get("provenance") != "warehouse":
            raise RuntimeError(f"pushdown warehouse tier: {st} {qdoc}")
        for c in qcols:
            if qdoc["columns"][c]["mean"] != \
                    desc["variables"][c]["mean"]:
                raise RuntimeError(
                    f"pushdown answer for {c!r} differs from the "
                    "one-shot description")
        st2, qraw2, qh2, _ = _req(ctl, "POST", "/v1/query", body=q,
                                  headers=jhdr)
        if st2 != 200 or qh2.get("X-Tpuprof-Provenance") != "cache" \
                or qraw2 != qraw:
            raise RuntimeError(
                "repeat query did not serve the same bytes from cache")

        # the load: >=95%-read traffic over keep-alive connections —
        # 19 conditional GETs (304 revalidations of the seed result)
        # per 1 cache-hit submit, timed per request
        rpath = "/v1/results/" + seed["id"]
        st, _b, hdrs0, _ = _req(ctl, "GET", rpath)
        if st != 200 or "ETag" not in hdrs0:
            raise RuntimeError(f"seed result fetch: {st} {hdrs0}")
        etag = hdrs0["ETag"]
        per = reads // clients
        write_every = 20                    # 1 in 20 -> exactly 95% GET
        lock = threading.Lock()
        lats: list = []
        lerrs: list = []

        def _client(_k):
            conn = http.client.HTTPConnection(host, port, timeout=120)
            my = []
            try:
                for i in range(per):
                    if i % write_every == write_every - 1:
                        st_, _p, _hh, dt = _req(
                            conn, "POST", "/v1/jobs",
                            body={"source": fixture,
                                  "config": dict(cfg)},
                            headers=jhdr)
                        if st_ != 202:
                            raise RuntimeError(
                                f"load submit -> {st_}")
                    else:
                        st_, _p, _hh, dt = _req(
                            conn, "GET", rpath,
                            headers={"If-None-Match": etag})
                        if st_ != 304:
                            raise RuntimeError(
                                f"conditional GET -> {st_}")
                    my.append(dt)
                with lock:
                    lats.extend(my)
            except Exception as exc:           # noqa: BLE001
                with lock:
                    lerrs.append(exc)
            finally:
                conn.close()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=_client, args=(k,))
                   for k in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t0
        if lerrs:
            raise RuntimeError(f"read load failed: {lerrs[0]}")
        lat = sorted(lats)
        rps = len(lat) / wall
        p50 = lat[(len(lat) - 1) // 2]
        p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]
        out.update({
            "serve_read_requests": len(lat),
            "serve_read_clients": clients,
            "serve_read_read_fraction": round(
                (write_every - 1) / write_every, 3),
            "serve_read_wall_s": round(wall, 3),
            "serve_read_rps": round(rps, 1),
            "serve_read_hit_p50_ms": round(p50 * 1000, 2),
            "serve_read_hit_p99_ms": round(p99 * 1000, 2),
            "rows_per_sec": round(rps, 1),
        })
        if rps < 500:
            raise RuntimeError(
                f"read tier sustained {rps:.0f} req/s (< 500 floor)")
        if p99 >= 0.050:
            raise RuntimeError(
                f"cache-hit p99 {p99 * 1000:.1f}ms (>= 50ms ceiling)")

        # computed pushdown tier LAST: the utime invalidates every
        # source-fingerprint key, which would wreck the lanes above
        os.utime(fixture)
        t0 = time.perf_counter()
        st3, qraw3, qh3, _ = _req(ctl, "POST", "/v1/query", body=q,
                                  headers=jhdr)
        out["serve_read_query_computed_s"] = \
            round(time.perf_counter() - t0, 3)
        qdoc3 = json.loads(qraw3)
        if st3 != 200 or qdoc3.get("provenance") != "computed":
            raise RuntimeError(f"pushdown computed tier: {st3} {qdoc3}")
        ctl.close()
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
    return out


def run_serve_read(scale: float, workdir: str) -> dict:
    # small fixture on purpose (the serve-leg rationale): the tracked
    # signals are read-tier throughput, the hit-latency tail, and the
    # exactly-once/provenance invariants — not scan throughput
    rows = max(int(1_000_000 * scale), 10_000)
    out = measure_serve_read(rows, workdir)
    out["scenario"] = "serve_read"
    return out


def measure_serve_shed(rows: int, workdir: str, burst: int = 10,
                       backlog: int = 2, reads: int = 400,
                       clients: int = 2) -> dict:
    """Overload envelope (ISSUE 19, rung 8): ONE real daemon with a
    ``--serve-backlog`` budget, its single worker saturated by a burst
    of distinct-shape compute submits —

    * shedding: once queued compute stands at the budget, further
      non-cacheable submits must answer **503** with
      ``reject_kind: "BacklogFull"`` and a positive jittered
      ``Retry-After`` (bounded by the 300 s clamp + jitter);
    * reads only, not collapse: WHILE the queue is saturated and
      shedding, conditional GETs of a cached result and a cache-hit
      submit keep answering — the read p99 must stay **< 50 ms**
      (the in-leg gate) and the leg FAILS if saturation ended before
      the read window did (a vacuous gate is no gate);
    * ledger: ``/v1/healthz`` must reconcile exactly — its ``shed``
      count equals the 503s the driver observed;
    * drain: SIGTERM mid-queue must exit **0** inside the drain
      budget (in-flight finishes, unstarted claims released)."""
    import http.client
    import shutil
    import signal
    import subprocess
    import threading
    from urllib.parse import urlsplit

    fixture = _ensure_fixture("taxi", rows, workdir)
    spool = os.path.join(workdir, "serve_shed_spool")
    shutil.rmtree(spool, ignore_errors=True)
    cfg = {"batch_rows": 1 << 12}
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    from tpuprof.serve import discover_edges, submit_job, wait_result_http

    proc = subprocess.Popen(
        [sys.executable, "-m", "tpuprof", "serve", spool,
         "--http", "0", "--daemon-id", "d0", "--serve-workers", "1",
         "--serve-queue-depth", "64", "--serve-backlog", str(backlog),
         "--serve-drain-timeout", "240", "--no-compile-cache"],
        cwd=here, stderr=subprocess.DEVNULL)
    out: dict = {"rows": rows}
    try:
        deadline = time.monotonic() + 300
        while "d0" not in discover_edges(spool):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"edge never advertised: {discover_edges(spool)}")
            time.sleep(0.2)
        url = discover_edges(spool)["d0"]
        parts = urlsplit(url)
        host, port = parts.hostname, parts.port

        def _req(conn, method, path, body=None, headers=None):
            payload = json.dumps(body).encode() if body is not None \
                else None
            t0 = time.perf_counter()
            conn.request(method, path, body=payload,
                         headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return (resp.status, data, dict(resp.getheaders()),
                    time.perf_counter() - t0)

        ctl = http.client.HTTPConnection(host, port, timeout=1800)
        jhdr = {"Content-Type": "application/json"}

        # seed the read tier: one computed answer to poll against
        _code, doc = submit_job(url, fixture, config_kwargs=dict(cfg))
        seed = wait_result_http(url, doc["id"], timeout=1800)
        if seed["status"] != "done":
            raise RuntimeError(f"seed job failed: {seed}")
        rpath = "/v1/results/" + seed["id"]
        st, _b, hdrs0, _ = _req(ctl, "GET", rpath)
        if st != 200 or "ETag" not in hdrs0:
            raise RuntimeError(f"seed result fetch: {st} {hdrs0}")
        etag = hdrs0["ETag"]

        # saturate the single worker: a burst of distinct shapes (no
        # compile cache — every one is slow, honest compute); past the
        # backlog budget the edge must shed with 503 + Retry-After
        accepted = shed = 0
        retry_afters: list = []
        for k in range(burst):
            st, raw, hh, _ = _req(
                ctl, "POST", "/v1/jobs",
                body={"source": fixture,
                      "config": {"batch_rows": (1 << 11) + 64 * k}},
                headers=jhdr)
            if st == 202:
                accepted += 1
            elif st == 503:
                rej = json.loads(raw)
                if rej.get("reject_kind") != "BacklogFull":
                    raise RuntimeError(f"503 without BacklogFull: {rej}")
                ra = float(hh["Retry-After"])
                if not 0 < ra <= 400:
                    raise RuntimeError(f"Retry-After out of range: {ra}")
                retry_afters.append(ra)
                shed += 1
            else:
                raise RuntimeError(f"burst submit -> {st} {raw!r}")
        if not shed:
            raise RuntimeError(
                f"burst of {burst} never shed (backlog {backlog})")

        # the read lane, measured WHILE compute is saturated/shedding
        per = reads // clients
        lock = threading.Lock()
        lats: list = []
        lerrs: list = []

        def _reader(_k):
            conn = http.client.HTTPConnection(host, port, timeout=120)
            my = []
            try:
                for _ in range(per):
                    st_, _p, _hh, dt = _req(
                        conn, "GET", rpath,
                        headers={"If-None-Match": etag})
                    if st_ != 304:
                        raise RuntimeError(f"conditional GET -> {st_}")
                    my.append(dt)
                with lock:
                    lats.extend(my)
            except Exception as exc:           # noqa: BLE001
                with lock:
                    lerrs.append(exc)
            finally:
                conn.close()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=_reader, args=(k,))
                   for k in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t0
        if lerrs:
            raise RuntimeError(f"read lane failed: {lerrs[0]}")

        # a cache-hit submit also rides the read tier while shedding
        st, raw, _hh, _ = _req(ctl, "POST", "/v1/jobs",
                               body={"source": fixture,
                                     "config": dict(cfg)},
                               headers=jhdr)
        if st != 202:
            raise RuntimeError(f"cache-hit submit shed: {st} {raw!r}")
        hit = wait_result_http(url, json.loads(raw)["id"], timeout=60)
        if hit["status"] != "done":
            raise RuntimeError(f"cache-hit submit failed: {hit}")

        # the gate is only honest if compute was still saturated when
        # the read window closed — and the healthz ledger must
        # reconcile with what the driver saw
        st, hraw, _hh, _ = _req(ctl, "GET", "/v1/healthz")
        h = json.loads(hraw)
        if h["queued"] < 1 or h["active"] < 1:
            raise RuntimeError(
                "compute tier drained before the read window closed "
                f"(queued={h['queued']} active={h['active']}) — "
                "shrink reads or grow the burst")
        if h["shed"] != shed:
            raise RuntimeError(
                f"healthz shed={h['shed']} != driver-observed {shed}")
        if h["serve_backlog"] != backlog:
            raise RuntimeError(
                f"healthz serve_backlog={h['serve_backlog']}")
        ctl.close()

        lat = sorted(lats)
        p50 = lat[(len(lat) - 1) // 2]
        p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]
        out.update({
            "serve_shed_burst": burst,
            "serve_shed_backlog": backlog,
            "serve_shed_accepted": accepted,
            "serve_shed_shed": shed,
            "serve_shed_retry_after_s": round(
                sum(retry_afters) / len(retry_afters), 2),
            "serve_shed_reads": len(lat),
            "serve_shed_read_rps": round(len(lat) / wall, 1),
            "serve_shed_read_p50_ms": round(p50 * 1000, 2),
            "serve_shed_read_p99_ms": round(p99 * 1000, 2),
            "rows_per_sec": round(len(lat) / wall, 1),
        })
        if p99 >= 0.050:
            raise RuntimeError(
                f"read p99 {p99 * 1000:.1f}ms under shedding load "
                "(>= 50ms ceiling)")

        # drain proof: SIGTERM mid-queue must exit 0 inside the budget
        t0 = time.perf_counter()
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=300)
        out["serve_shed_drain_s"] = round(time.perf_counter() - t0, 2)
        if rc != 0:
            raise RuntimeError(f"drain exited {rc}, not 0")
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
    return out


def run_serve_shed(scale: float, workdir: str) -> dict:
    # small fixture on purpose: the tracked signals are the shed
    # contract and the read tail under saturation, not scan throughput
    rows = max(int(1_000_000 * scale), 10_000)
    out = measure_serve_shed(rows, workdir)
    out["scenario"] = "serve_shed"
    return out


def run_watch(scale: float, workdir: str) -> dict:
    # small fixture on purpose, like serve: the tracked signals are the
    # warm cycle latency and the alert latency, not scan throughput
    rows = max(int(1_000_000 * scale), 10_000)
    out = measure_watch(rows, workdir)
    out["scenario"] = "watch"
    return out


def measure_warehouse(rows: int, workdir: str, cols: int = 400,
                      gens: int = 50) -> dict:
    """Profile-warehouse envelope (ISSUE 13) at a WIDE shape:

    * ``warehouse_write_s`` — one columnar generation append (Parquet
      encode + fsync + rename) for a ``cols``-column profile;
    * ``warehouse_pruned_read_speedup`` — answering "one stat of one
      column" from the columnar file (column-pruned read) vs from the
      full JSON artifact (whole-document parse) — the 10k-column win
      at bench scale; the leg FAILS if pruning is not faster;
    * ``history_query_s`` — a `tpuprof history` stat query over a
      ``gens``-generation chain (the acceptance fixture's shape);
      the leg FAILS if the answer is wrong.

    The profile itself is fixture prep (cpu oracle — the tracked
    signals are columnar IO, not scan throughput)."""
    import statistics
    import tempfile

    import pandas as pd

    from tpuprof import ProfileReport, ProfilerConfig
    from tpuprof import warehouse as wh
    from tpuprof.artifact import read_artifact, write_artifact

    rng = np.random.default_rng(0)
    data = {f"c{i:04d}": rng.normal(i, 1.0 + i % 7, rows)
            for i in range(cols - 1)}
    data["cat"] = rng.choice(["x", "y", "z"], rows)
    report = ProfileReport(pd.DataFrame(data), backend="cpu")

    def _median(fn, n=5):
        times = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    with tempfile.TemporaryDirectory(dir=workdir) as td:
        art_path = os.path.join(td, "wide.artifact.json")
        write_artifact(art_path, stats=report.description,
                       config=ProfilerConfig(), source="wide")
        art = read_artifact(art_path)
        probe_col = "c0007"
        truth = art.stats["variables"][probe_col]["mean"]

        pq_path = os.path.join(td, "wide.stats.parquet")

        def _write():
            wh.write_stats_parquet(
                pq_path, art.stats, art.sketches, source="wide",
                generation=1, rows=art.rows,
                artifact_crc32=art.crc32)
        write_s = _median(_write, n=3)

        def _json_read():
            a = read_artifact(art_path)
            return a.stats["variables"][probe_col]["mean"]

        def _pruned_read():
            g = wh.read_stats_parquet(pq_path, columns=[probe_col],
                                      stats=["mean"])
            return g.stats[probe_col]["mean"]

        if _pruned_read() != truth or _json_read() != truth:
            raise RuntimeError("warehouse leg: columnar/JSON answers "
                               "disagree — round-trip broken")
        json_read_s = _median(_json_read)
        pruned_read_s = _median(_pruned_read)
        speedup = json_read_s / pruned_read_s
        if speedup <= 1.0:
            raise RuntimeError(
                f"warehouse leg: column-pruned read ({pruned_read_s:.4f}s) "
                f"is not faster than the full-JSON read "
                f"({json_read_s:.4f}s) at {cols} columns — the "
                "warehouse's reason to exist regressed")

        chain_dir = os.path.join(td, "chain")
        for g in range(1, gens + 1):
            wh.append_generation(chain_dir, "wide", art.stats,
                                 art.sketches, generation=g,
                                 rows=art.rows)
        src_dir = wh.source_dir(chain_dir, "wide")

        def _history():
            return wh.query_stat(src_dir, probe_col, "mean")
        doc = _history()
        if doc["generations"] != gens or \
                any(e["value"] != truth for e in doc["series"]):
            raise RuntimeError("warehouse leg: history query answered "
                               "wrong over the generation chain")
        history_s = _median(_history, n=3)
        file_bytes = os.path.getsize(pq_path)

    return {
        "rows": rows,
        "warehouse_cols": cols,
        "warehouse_generations": gens,
        "warehouse_write_s": round(write_s, 4),
        "warehouse_bytes": file_bytes,
        "warehouse_json_read_s": round(json_read_s, 4),
        "warehouse_pruned_read_s": round(pruned_read_s, 4),
        "warehouse_pruned_read_speedup": round(speedup, 2),
        "history_query_s": round(history_s, 4),
        # the differ's generic higher-is-better key: stat cells
        # answered per second by the history query over the chain
        "rows_per_sec": round(gens / history_s, 1),
    }


def run_warehouse(scale: float, workdir: str) -> dict:
    # the wide shape is the point (column pruning); rows only size the
    # fixture-prep profile
    os.makedirs(workdir, exist_ok=True)
    rows = max(int(200_000 * scale), 2000)
    out = measure_warehouse(rows, workdir)
    out["scenario"] = "warehouse"
    return out


LINT_WALL_TARGET_S = 5.0


def measure_lint() -> dict:
    """ISSUE 12 bench guard: the invariant suite must stay cheap
    enough to live in tier-1 forever — wall target < 5 s over the real
    tree on this box (measured ~0.8 s at PR 12).  Tracked signals are
    the wall and the finding counts (unsuppressed must be 0 at HEAD;
    the leg FAILS loudly on drift rather than recording it as a
    number)."""
    from tpuprof.analysis import run_lint

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = time.perf_counter()
    report = run_lint(root)
    wall = time.perf_counter() - t0
    unsuppressed = report.unsuppressed()
    if unsuppressed:
        raise RuntimeError(
            f"lint leg: {len(unsuppressed)} unsuppressed finding(s) at "
            "HEAD — fix or justify before benching: "
            + "; ".join(f.ident for f in unsuppressed[:5]))
    return {
        "lint_wall_s": round(wall, 4),
        "lint_checkers": len(report.checkers_run),
        "lint_findings_total": len(report.findings),
        "lint_suppressed": len(report.suppressed),
        "lint_under_target": wall < LINT_WALL_TARGET_S,
        # the differ's generic key so the leg diffs round-over-round
        # (higher = better, like every other leg): full-suite runs per
        # second of wall
        "rows_per_sec": round(1.0 / wall, 4),
    }


def run_lint_leg(scale: float, workdir: str) -> dict:
    out = measure_lint()
    out["scenario"] = "lint"
    return out


def _wide_fixture(workdir: str, rows: int, cols: int) -> str:
    """Plain wide float32 parquet (the singlepass leg's second shape)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from benchmarks import scenarios

    path = os.path.join(workdir, f"wide{cols}_{rows}.parquet")
    if os.path.exists(path):
        return path
    rng = np.random.default_rng(0)
    writer = None
    left = rows
    while left > 0:
        n = min(1 << 18, left)
        x = scenarios.wide_batch(rng, n, cols=cols)
        table = pa.table({f"f{i:03d}": x[:, i] for i in range(cols)})
        if writer is None:
            writer = pq.ParquetWriter(path, table.schema)
        writer.write_table(table)
        left -= n
    writer.close()
    return path


def measure_singlepass(rows: int, workdir: str,
                       wide_cols: int = 100) -> dict:
    """Single-pass fused-vs-two-pass A/B (ISSUE 14 / ROADMAP 3(c)):

    * **tpch lane** — warm-edge fused profile (seeded from a two-pass
      run's artifact of the SAME fixture) vs the two-pass profile,
      both warm (best of two), full ProfileReport e2e.  The leg FAILS
      if the two stats exports are not byte-identical — the identity
      contract is a correctness gate, not a tracked number.
    * **wide lane** — the same A/B at a {wide_cols}-column float32
      shape (``singlepass_wide_speedup_x``).
    * **warm-watch lane** — 3 fused watch cycles over an undrifted
      source; cycles ≥ 2 must hit on EVERY numeric lane
      (``edge_hit_rate`` == 1.0 — the by-construction claim,
      enforced, not recorded).

    The persistent DISK compile cache stays off (run_drift's
    rationale); the runner cache provides in-process warmth, and the
    fused/two-pass runners occupy separate cache slots by key."""
    import shutil
    import tempfile

    from tpuprof import ProfileReport, ProfilerConfig, obs
    from tpuprof.artifact import write_artifact
    from tpuprof.backends.tpu import disable_compile_cache
    from tpuprof.obs import metrics as om
    from tpuprof.report.export import stats_to_json
    from tpuprof.serve import DriftWatcher, ProfileScheduler

    disable_compile_cache()
    obs.configure(enabled=True)

    def _ab(fixture: str, tag: str) -> dict:
        art = os.path.join(workdir, f"singlepass_{tag}.artifact.json")
        out_html = os.path.join(workdir, f"singlepass_{tag}.html")

        def _profile(**kw):
            cfg = ProfilerConfig(backend="tpu", metrics_enabled=True,
                                 **kw)
            t0 = time.perf_counter()
            rep = ProfileReport(fixture, config=cfg)
            rep.to_file(out_html)
            return time.perf_counter() - t0, rep

        _, rep0 = _profile()                    # two-pass compile
        write_artifact(art, stats=rep0.description,
                       config=ProfilerConfig(backend="tpu"))
        fused_kw = {"profile_passes": "fused", "seed_edges": art}
        _profile(**fused_kw)                    # fused compile
        # INTERLEAVED best-of-4 pairs: on a timeshared box the load
        # drifts over seconds, so alternating the arms (the PERF.md
        # same-session A/B discipline) keeps weather out of the ratio
        two_s = fused_s = float("inf")
        two_rep = fused_rep = None
        for _ in range(4):
            s, rep = _profile()
            if s < two_s:
                two_s, two_rep = s, rep
            s, rep = _profile(**fused_kw)
            if s < fused_s:
                fused_s, fused_rep = s, rep
        a = json.dumps(stats_to_json(two_rep.description),
                       sort_keys=True, default=str)
        b = json.dumps(stats_to_json(fused_rep.description),
                       sort_keys=True, default=str)
        if a != b:
            raise RuntimeError(
                f"singlepass {tag}: fused stats diverge from two-pass "
                "— the identity contract is broken")
        n = fused_rep.description["table"]["n"]
        # scan-phase-only ratio alongside e2e: (scan_a + scan_b) of
        # the best two-pass run over the fused run's single scan
        # (whose span keeps the "scan_a" name) — the pass-structure
        # lever isolated from render/finalize fixed costs, and far
        # less weather-sensitive on a 1-core box
        ph2 = two_rep.description.get("_phases") or {}
        phf = fused_rep.description.get("_phases") or {}
        scan_two = ph2.get("scan_a", 0.0) + ph2.get("scan_b", 0.0)
        scan_fused = phf.get("scan_a", 0.0)
        return {"rows": n, "two_pass_s": two_s, "fused_s": fused_s,
                "speedup": two_s / fused_s,
                "scan_speedup": scan_two / scan_fused
                if scan_fused else float("nan")}

    tpch = _ab(_ensure_fixture("tpch", rows, workdir), "tpch")
    wide = _ab(_wide_fixture(workdir, max(rows // 2, 500_000),
                             wide_cols), "wide")

    # warm-watch hit-rate lane: cycle 1 sketches cold, cycles 2..3 seed
    # from the previous cycle's artifact — every lane must hit
    def _sp_counts():
        snap = om.registry().snapshot()["counters"]
        return (sum(snap.get("tpuprof_singlepass_edge_hits_total",
                             {}).values()),
                sum(snap.get("tpuprof_singlepass_edge_misses_total",
                             {}).values()))

    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "watched.parquet")
        shutil.copyfile(_ensure_fixture("tpch", max(rows // 4, 10_000),
                                        workdir), src)
        spool = os.path.join(td, "spool")
        sched = ProfileScheduler(workers=1)
        watcher = DriftWatcher(
            spool, [src], sched, every_s=0, keep=2,
            config_kwargs={"batch_rows": 1 << 12,
                           "profile_passes": "fused",
                           "metrics_enabled": True})
        w = watcher.watches[0]
        first = watcher.run_cycle(w)
        h0, m0 = _sp_counts()
        warm_cycles = [watcher.run_cycle(w) for _ in range(2)]
        h1, m1 = _sp_counts()
        sched.shutdown()
    if first["status"] != "ok" or any(c["status"] != "ok"
                                      for c in warm_cycles):
        raise RuntimeError(
            f"singlepass watch lane: cycles failed: {[first] + warm_cycles}")
    warm_hits, warm_misses = h1 - h0, m1 - m0
    hit_rate = warm_hits / max(warm_hits + warm_misses, 1)
    if hit_rate != 1.0:
        raise RuntimeError(
            f"singlepass watch lane: warm edge hit rate {hit_rate} != "
            f"1.0 ({warm_misses} misses on an undrifted source) — the "
            "by-construction claim is broken")

    return {
        "rows": tpch["rows"],
        "seconds": round(tpch["fused_s"], 3),
        "rows_per_sec": round(tpch["rows"] / tpch["fused_s"], 1),
        "two_pass_rows_per_sec": round(tpch["rows"] / tpch["two_pass_s"],
                                       1),
        "singlepass_speedup_x": round(tpch["speedup"], 3),
        "singlepass_scan_speedup_x": round(tpch["scan_speedup"], 3),
        "singlepass_wide_speedup_x": round(wide["speedup"], 3),
        "singlepass_wide_scan_speedup_x": round(wide["scan_speedup"], 3),
        "edge_hit_rate": round(hit_rate, 4),
        "watch_warm_cycle_s": round(
            min(c["seconds"] for c in warm_cycles), 4),
    }


def run_singlepass(scale: float, workdir: str) -> dict:
    # floor high enough that the SCAN dominates the e2e wall: below
    # ~1M rows compile/render/finalize fixed costs dilute the
    # pass-structure ratio into noise (measured: 20k rows -> 1.09x,
    # 500k -> 1.20x, 1M -> 1.35x on the CPU lane) and the leg would
    # track overhead, not the lever
    rows = max(int(2_000_000 * scale), 1_000_000)
    out = measure_singlepass(rows, workdir)
    out["scenario"] = "singlepass"
    return out


def measure_aot_roundtrip(rows: int, workdir: str) -> dict:
    """AOT compile-vs-deserialize A/B (ISSUE 15), in-process: AOT-
    compile + serialize one runner's core programs into a fresh store
    (timing the compile half), then load them into a SECOND, cold
    runner through the real acquire seam and time the deserialize.
    The leg FAILS unless the load adopted the programs and ran ≥5x
    faster than the compile it replaces — the tentpole's reason to
    exist, enforced rather than recorded."""
    import dataclasses
    import shutil

    from tpuprof import ProfilerConfig
    from tpuprof.backends.tpu import disable_compile_cache
    from tpuprof.ingest.arrow import ArrowIngest
    from tpuprof.runtime import aot as aotrt
    from tpuprof.serve import cache as serve_cache

    disable_compile_cache()
    fixture = _ensure_fixture("taxi", rows, workdir)
    ab_dir = os.path.join(workdir, "restart_ab_aot")
    shutil.rmtree(ab_dir, ignore_errors=True)
    cfg = ProfilerConfig(backend="tpu", batch_rows=1 << 12)
    plan = ArrowIngest(fixture, cfg.batch_rows).plan
    runner = serve_cache.acquire_runner(cfg, plan.n_num, plan.n_hash)
    key = serve_cache.runner_key(cfg, plan.n_num, plan.n_hash)
    store = aotrt.AotStore(ab_dir)
    meta = store.save_runner(key, runner, cfg)
    store.touch_manifest(key, cfg, plan.n_num, plan.n_hash)

    # a fresh RunnerCache = a fresh process's first acquire, minus the
    # interpreter/jax import wall (the daemon lane measures that half)
    cfg_aot = dataclasses.replace(cfg, aot_cache_dir=ab_dir)
    rc = serve_cache.RunnerCache(2)
    t0 = time.perf_counter()
    warm = rc.get(cfg_aot, plan.n_num, plan.n_hash)
    load_s = time.perf_counter() - t0
    if not hasattr(warm._scan_a, "_aot_fallback"):
        raise RuntimeError(
            "restart leg: AOT load did not adopt the scan programs — "
            "the store answered nothing")
    speedup = meta["compile_s"] / load_s
    if speedup < 5.0:
        raise RuntimeError(
            f"restart leg: AOT deserialize ({load_s:.3f}s) is only "
            f"{speedup:.1f}x faster than the compile it replaces "
            f"({meta['compile_s']:.3f}s) — acceptance is >= 5x")
    return {
        "rows": rows,
        "aot_programs": meta["programs"],
        "aot_entry_bytes": meta["bytes"],
        "aot_compile_s": round(meta["compile_s"], 3),
        "aot_save_write_s": round(meta["write_s"], 4),
        "aot_load_s": round(load_s, 4),
        "aot_deserialize_speedup_x": round(speedup, 1),
    }


def measure_restart(rows: int, workdir: str) -> dict:
    """Restart-to-warm (ISSUE 15 acceptance): the in-process
    compile-vs-deserialize A/B above PLUS a real `tpuprof serve`
    daemon restart on one spool —

    * daemon 1 answers a cold job (pays the compile) and its
      background save publishes the AOT entry + manifest under
      SPOOL/aot (the CLI default);
    * daemon 2 starts on the same spool with a job already waiting;
      ``restart_to_warm_s`` is Popen -> first-job-done wall, which
      must land under the 5 s ROADMAP bar;
    * the restarted daemon's stats export must be byte-identical to
      the cold daemon's (in-leg enforcement — a wrong warm answer is
      a correctness bug, not a slow round)."""
    import shutil
    import subprocess

    from tpuprof.serve import wait_result, write_job

    out = measure_aot_roundtrip(rows, workdir)
    fixture = _ensure_fixture("taxi", rows, workdir)
    spool = os.path.join(workdir, "restart_spool")
    shutil.rmtree(spool, ignore_errors=True)
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = {"batch_rows": 1 << 12}

    def spawn():
        return subprocess.Popen(
            [sys.executable, "-m", "tpuprof", "serve", spool,
             "--daemon-id", "r0", "--serve-workers", "1",
             "--no-compile-cache"],
            cwd=here, stderr=subprocess.DEVNULL)

    from tpuprof.runtime import aot as aotrt
    cold_stats = os.path.join(workdir, "restart_cold.json")
    proc = spawn()
    try:
        jid = write_job(spool, fixture, stats_json=cold_stats,
                        config_kwargs=dict(cfg))
        res = wait_result(spool, jid, timeout=1800)
        if res["status"] != "done":
            raise RuntimeError(f"restart leg: cold job failed: {res}")
        cold_job_s = float(res["seconds"])
        # the save is a background thread — wait for the entry to
        # publish before killing the daemon
        store = aotrt.AotStore(os.path.join(spool, "aot"))
        deadline = time.monotonic() + 600
        while not (store.entries()
                   and os.path.exists(store.manifest_path)):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "restart leg: daemon never published its AOT "
                    "entry")
            time.sleep(0.2)
    finally:
        proc.terminate()
        proc.wait(timeout=300)

    # the restart: a job is already waiting when the daemon comes up,
    # so Popen -> result-landed IS the operator's restart-to-warm
    warm_stats = os.path.join(workdir, "restart_warm.json")
    jid = write_job(spool, fixture, stats_json=warm_stats,
                    config_kwargs=dict(cfg))
    t0 = time.perf_counter()
    proc = spawn()
    try:
        res = wait_result(spool, jid, timeout=1800)
        restart_to_warm_s = time.perf_counter() - t0
        if res["status"] != "done":
            raise RuntimeError(f"restart leg: warm job failed: {res}")
        warm_job_s = float(res["seconds"])
    finally:
        proc.terminate()
        proc.wait(timeout=300)

    with open(cold_stats) as fh:
        cold_doc = json.load(fh)
    with open(warm_stats) as fh:
        warm_doc = json.load(fh)
    if cold_doc != warm_doc:
        raise RuntimeError(
            "restart leg: AOT-warmed stats differ from the "
            "cold-compiled stats — the never-wrong contract is broken")
    if restart_to_warm_s >= 5.0:
        raise RuntimeError(
            f"restart leg: restart-to-warm {restart_to_warm_s:.2f}s "
            "missed the < 5 s bar (ROADMAP 3(d))")
    out.update({
        "restart_cold_job_s": round(cold_job_s, 3),
        "restart_warm_job_s": round(warm_job_s, 3),
        "restart_warm_vs_cold_x": round(cold_job_s
                                        / max(warm_job_s, 1e-9), 1),
        "restart_to_warm_s": round(restart_to_warm_s, 3),
        "rows_per_sec": round(rows / restart_to_warm_s, 1),
    })
    return out


def run_restart(scale: float, workdir: str) -> dict:
    # small fixture on purpose (the serve-leg rationale): the tracked
    # signals are the deserialize:compile ratio and the restart wall,
    # not scan throughput
    os.makedirs(workdir, exist_ok=True)
    rows = max(int(1_000_000 * scale), 10_000)
    out = measure_restart(rows, workdir)
    out["scenario"] = "restart"
    return out


def run_serve(scale: float, workdir: str) -> dict:
    # small fixtures on purpose: the tracked signal is the cold:warm
    # RATIO (compile amortization), which a big scan denominator would
    # only dilute; absolute warm rates ride rows_per_sec as usual
    rows = max(int(1_000_000 * scale), 10_000)
    out = measure_serve(rows, workdir)
    out["scenario"] = "serve"
    return out


REGRESSION_SCENARIOS = ("taxi", "tpch", "criteo", "wide1b", "streaming",
                        "hostfed", "prepare", "passb", "faults", "drift",
                        "rebalance", "serve", "watch", "serve_http",
                        "warehouse", "lint", "singlepass", "restart",
                        "serve_read", "serve_shed")


def _load_baseline(baseline: "str | None", workdir: str) -> "tuple":
    """(label, results-by-scenario) of the previous round's regression
    table: an explicit ``--baseline`` path wins; else the newest
    committed ``benchmarks/REGRESSION_r*.json``; else the workdir's
    previous ``REGRESSION.json`` (same-machine rerun).  Returns
    (None, {}) when this is the first round with nothing to diff."""
    import glob

    candidates = []
    if baseline:
        candidates.append(baseline)
    here = os.path.dirname(os.path.abspath(__file__))
    candidates.extend(sorted(glob.glob(
        os.path.join(here, "REGRESSION_r*.json")), reverse=True))
    candidates.append(os.path.join(workdir, "REGRESSION.json"))
    for path in candidates:
        try:
            with open(path) as fh:
                payload = json.load(fh)
        except (OSError, ValueError):
            continue
        by_name = {r.get("scenario"): r for r in payload.get("results", [])
                   if isinstance(r, dict)}
        if by_name:
            return os.path.basename(path), by_name
    return None, {}


_DELTA_KEYMAP = {"passb": "pass_b_rows_per_sec",
                 "prepare": "prepare_rows_per_sec",
                 "faults": "guarded_rows_per_sec",
                 "serve_read": "serve_read_rps"}


def _historical_bands() -> dict:
    """Per-leg swing bands from the COMMITTED REGRESSION_r*.json
    history (ISSUE 9 satellite): for each scenario, the largest
    |round-over-round swing| of its tracked key across the committed
    rounds, padded 1.25x, floored at the generic 25% and capped at 95%
    (a flag must still be reachable).  Legs that historically swing at
    FIXED code — passb ranged 3.2-5.2x cum:legacy across rounds and
    r11 logged a -38% false alarm with no pass-B code touched — flag
    only outside their own measured weather band, so the differ stops
    crying wolf on known-noisy legs while a new regression on a stable
    leg still trips at 25%."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in sorted(glob.glob(os.path.join(here,
                                              "REGRESSION_r*.json"))):
        try:
            with open(path) as fh:
                payload = json.load(fh)
        except (OSError, ValueError):
            continue
        rounds.append({r.get("scenario"): r
                       for r in payload.get("results", [])
                       if isinstance(r, dict)})
    bands = {}
    for name in {k for rnd in rounds for k in rnd}:
        key = _DELTA_KEYMAP.get(name, "rows_per_sec")
        series = []
        for rnd in rounds:
            ent = rnd.get(name)
            if ent and key in ent:
                try:
                    series.append(float(ent[key]))
                except (TypeError, ValueError):
                    pass
        swings = [abs(b - a) / a * 100
                  for a, b in zip(series, series[1:]) if a]
        if swings:
            bands[name] = max(25.0, min(max(swings) * 1.25, 95.0))
    return bands


def _print_deltas(results, label, baseline) -> None:
    """One delta line per scenario vs the previous round, each judged
    against ITS historical swing band (``_historical_bands``) — a
    silent pass-B regression must be visible without reading JSON by
    hand (ISSUE 3 satellite), and a known-noisy leg must not bury the
    real flags in false alarms (ISSUE 9 satellite)."""
    if not baseline:
        print("\n(no previous REGRESSION.json found — nothing to diff)")
        return
    bands = _historical_bands()
    print(f"\ndeltas vs {label} (flagged outside each leg's historical "
          "swing band; default band ±25%):")
    for r in results:
        name = r.get("scenario")
        prev = baseline.get(name)
        key = _DELTA_KEYMAP.get(name, "rows_per_sec")
        if "error" in r:
            print(f"  {name}: FAILED this round ({r['error'][:50]})")
            continue
        if not prev or key not in prev or key not in r:
            print(f"  {name}: no baseline figure")
            continue
        old, new = float(prev[key]), float(r[key])
        pct = (new - old) / old * 100 if old else float("nan")
        band = bands.get(name, 25.0)
        flag = ""
        if pct <= -band:
            flag = "  ⚠ REGRESSION?"
        elif pct >= band:
            flag = "  (improvement)"
        print(f"  {name}: {old:,.0f} → {new:,.0f} rows/s "
              f"({pct:+.1f}% vs ±{band:.0f}% band){flag}")


def run_regression(scale: float, workdir: str,
                   baseline: "str | None" = None) -> None:
    """ALL five BASELINE scenarios (+ hostfed), each in a CPU-pinned
    subprocess on an 8-fake-device mesh, one diffable table out
    (VERDICT r4 #6): small-scale rates whose round-over-round DELTAS —
    not absolute values — are the regression signal.  Tunnel weather
    cannot touch any number here.  Also measures the exact_distinct
    overhead at the criteo (mixed) shape via a second criteo leg with
    --parity-style settings, since that tier's cost lives on the host.

    Writes ``REGRESSION.json`` into --workdir and prints one JSON line
    per scenario plus a markdown table the next round can diff."""
    import subprocess

    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    here = os.path.abspath(__file__)
    # snapshot the previous round's figures BEFORE this run overwrites
    # the workdir copy
    base_label, base_results = _load_baseline(baseline, workdir)
    results = []

    def _leg(display_name, argv):
        # a failed leg must leave a diffable FAILED row, never a silent
        # omission the next round could misread as "never ran"; a child
        # that exits 0 without a JSON line is a failure too
        try:
            proc = subprocess.run(argv, env=env, capture_output=True,
                                  text=True, timeout=3600)
        except subprocess.TimeoutExpired:
            results.append({"scenario": display_name,
                            "error": "timeout after 3600s"})
            print(json.dumps(results[-1]), flush=True)
            return
        lines = [l for l in proc.stdout.splitlines()
                 if l.startswith("{")]
        if proc.returncode != 0 or not lines:
            err = (proc.stderr.strip().splitlines() or ["no output"])[-1]
            results.append({"scenario": display_name, "error": err})
            print(json.dumps(results[-1]), flush=True)
            return
        entry = json.loads(lines[-1])
        entry["scenario"] = display_name
        results.append(entry)
        print(json.dumps(entry), flush=True)

    for name in REGRESSION_SCENARIOS:
        _leg(name, [sys.executable, here, name, "--scale", str(scale),
                    "--workdir", workdir])
    # exact_distinct overhead leg at the mixed (criteo) shape
    _leg("criteo+exact",
         [sys.executable, here, "criteo", "--scale", str(scale),
          "--workdir", workdir, "--exact-distinct"])
    # exact_distinct overhead at the WIDE shape (ISSUE 8): the 5.6x ->
    # <=3x claim as a tracked round-over-round number, host path only
    _leg("wide200+exact",
         [sys.executable, here, "wideexact", "--scale", str(scale),
          "--workdir", workdir])
    out_path = os.path.join(workdir, "REGRESSION.json")
    with open(out_path, "w") as fh:
        json.dump({"scale": scale, "results": results}, fh, indent=2)
    print(f"\n| scenario | rows | warm rows/s | notes |")
    print(f"|---|---|---|---|")
    for r in results:
        if "error" in r:
            print(f"| {r['scenario']} | — | FAILED | {r['error'][:60]} |")
            continue
        notes = ""
        if "stream_vs_singlepass" in r:
            notes = f"stream:single {r['stream_vs_singlepass']}"
        if "pass_b_cumulative_vs_legacy" in r:
            notes = f"cum:legacy {r['pass_b_cumulative_vs_legacy']}"
        if "incremental_vs_full_speedup" in r:
            notes = f"inc:full {r['incremental_vs_full_speedup']}"
        if "exact_distinct_overhead_x" in r:
            notes = f"exact:sketch {r['exact_distinct_overhead_x']}x"
        if "serve_cold_vs_warm_ratio" in r:
            notes = (f"cold:warm {r['serve_cold_vs_warm_ratio']}x, "
                     f"hit {r['serve_cache_hit_rate']}")
        if "watch_alert_latency_s" in r:
            notes = (f"cycle {r['watch_cycle_s']}s, "
                     f"alert {r['watch_alert_latency_s']}s")
        if "serve_http_rps" in r:
            notes = (f"{r['serve_http_rps']} req/s, "
                     f"p99 {r['serve_http_p99_s']}s, "
                     f"lost {r['serve_http_killed_lost']}")
        if "warehouse_pruned_read_speedup" in r:
            notes = (f"write {r['warehouse_write_s']}s, pruned "
                     f"{r['warehouse_pruned_read_speedup']}x, history "
                     f"{r['history_query_s']}s")
        if "lint_wall_s" in r:
            notes = f"wall {r['lint_wall_s']}s"
        if "singlepass_speedup_x" in r:
            notes = (f"fused:two {r['singlepass_speedup_x']}x, wide "
                     f"{r['singlepass_wide_speedup_x']}x, hit "
                     f"{r['edge_hit_rate']}")
        if "restart_to_warm_s" in r:
            notes = (f"warm in {r['restart_to_warm_s']}s, "
                     f"deser {r['aot_deserialize_speedup_x']}x, "
                     f"job {r['restart_warm_vs_cold_x']}x")
        if "serve_read_rps" in r:
            notes = (f"{r['serve_read_rps']} req/s, hit p99 "
                     f"{r['serve_read_hit_p99_ms']}ms, computed "
                     f"{r['serve_read_coalesce_computed']}/"
                     f"{r['serve_read_coalesce_k']}")
        if "serve_shed_shed" in r:
            notes = (f"shed {r['serve_shed_shed']}/"
                     f"{r['serve_shed_burst']}, read p99 "
                     f"{r['serve_shed_read_p99_ms']}ms, drain "
                     f"{r['serve_shed_drain_s']}s")
        rate = r.get("rows_per_sec",
                     r.get("prepare_rows_per_sec", float("nan")))
        rows = r.get("rows")
        # rows-less legs (lint) print a dash — a string can't take the
        # thousands format that crashed the r15 table
        rows_s = f"{rows:,}" if isinstance(rows, (int, float)) else "—"
        print(f"| {r['scenario']} | {rows_s} | "
              f"{rate:,.0f} | {notes} |")
    _print_deltas(results, base_label, base_results)
    print(f"\nwritten: {out_path}")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("scenario", choices=["taxi", "tpch", "criteo",
                                             "wide1b", "streaming",
                                             "hostfed", "prepare",
                                             "passb", "faults", "drift",
                                             "rebalance", "wideexact",
                                             "serve", "watch",
                                             "serve_http", "warehouse",
                                             "lint", "singlepass",
                                             "restart", "serve_read",
                                             "serve_shed",
                                             "regression", "all"])
    parser.add_argument("--scale", type=float, default=0.01)
    parser.add_argument("--workdir", default="/tmp/tpuprof_bench")
    parser.add_argument("--backend", default="tpu")
    parser.add_argument("--baseline", default=None, metavar="PATH",
                        help="previous round's REGRESSION.json to diff "
                             "against (default: newest committed "
                             "benchmarks/REGRESSION_r*.json, else the "
                             "workdir's previous run)")
    parser.add_argument("--exact-distinct", action="store_true",
                        help="profile with exact distinct counting "
                             "(spill dir under --workdir) — the "
                             "regression harness uses this to track the "
                             "exact tier's host cost")
    args = parser.parse_args()

    if args.scenario == "regression":
        run_regression(args.scale, args.workdir, baseline=args.baseline)
        return

    # Persistent compilation cache: each ProfileReport builds a fresh
    # MeshRunner whose jit wrappers are new instances, so without this
    # the "warm" second profile re-pays every XLA compile on a stock
    # JAX install (the in-memory jit cache is per-wrapper).
    import jax
    os.makedirs(args.workdir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(args.workdir, "jax_cache"))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass                      # older jaxlibs: warm == cold, still valid

    names = (["taxi", "tpch", "criteo", "wide1b", "streaming", "hostfed",
              "prepare", "passb", "faults", "drift", "rebalance",
              "wideexact", "serve", "watch", "serve_http", "warehouse",
              "lint", "singlepass", "restart", "serve_read",
              "serve_shed"]
             if args.scenario == "all" else [args.scenario])
    for name in names:
        if name in ("taxi", "tpch", "criteo"):
            result = run_table_scenario(name, args.scale, args.workdir,
                                        args.backend,
                                        exact_distinct=args.exact_distinct)
        elif name == "wide1b":
            result = run_wide1b(args.scale, args.workdir, args.backend)
        elif name == "hostfed":
            result = run_hostfed(args.scale, args.workdir)
        elif name == "prepare":
            result = run_prepare(args.scale, args.workdir)
        elif name == "passb":
            result = run_passb(args.scale, args.workdir)
        elif name == "faults":
            result = run_faults(args.scale, args.workdir)
        elif name == "drift":
            result = run_drift(args.scale, args.workdir)
        elif name == "rebalance":
            result = run_rebalance(args.scale, args.workdir)
        elif name == "wideexact":
            result = run_wideexact(args.scale, args.workdir)
        elif name == "serve":
            result = run_serve(args.scale, args.workdir)
        elif name == "watch":
            result = run_watch(args.scale, args.workdir)
        elif name == "serve_http":
            result = run_serve_http(args.scale, args.workdir)
        elif name == "warehouse":
            result = run_warehouse(args.scale, args.workdir)
        elif name == "lint":
            result = run_lint_leg(args.scale, args.workdir)
        elif name == "singlepass":
            result = run_singlepass(args.scale, args.workdir)
        elif name == "restart":
            result = run_restart(args.scale, args.workdir)
        elif name == "serve_read":
            result = run_serve_read(args.scale, args.workdir)
        elif name == "serve_shed":
            result = run_serve_shed(args.scale, args.workdir)
        else:
            result = run_streaming(args.scale, args.workdir, args.backend)
        print(json.dumps(result))


if __name__ == "__main__":
    main()
