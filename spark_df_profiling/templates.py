"""Reference-layout alias: ``spark_df_profiling.templates.template(name)``
returned a compiled Jinja2 template in the upstream package (SURVEY.md
§2.1 Templates row — ``templates.py`` + ``templates/*.html``).  tpuprof
keeps the same per-section template names (``base.html``, ``report.html``,
``row_num.html``, ``row_cat.html``, ...) in its own environment, so the
loader maps straight through."""

from tpuprof.report.render import _get_env


def template(template_name: str):
    """Return the compiled Jinja2 template for ``template_name``
    (``.html`` appended when omitted, matching the upstream's loader
    convenience)."""
    name = template_name if template_name.endswith(".html") \
        else template_name + ".html"
    return _get_env().get_template(name)


__all__ = ["template"]
