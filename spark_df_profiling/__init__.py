"""Drop-in import surface for users migrating from spark-df-profiling.

The reference library's whole public API (SURVEY.md §1: ``ProfileReport``
with ``bins``/``corr_reject`` kwargs, ``.to_file``/``.html``/
``.get_rejected_variables``/``_repr_html_``, ``base.describe``, and the
``formatters`` helpers) is re-exported from tpuprof, so

    import spark_df_profiling
    report = spark_df_profiling.ProfileReport(df, bins=10, corr_reject=0.9)
    report.to_file("report.html")

keeps working verbatim — now backed by the fused TPU scan instead of
per-column Spark jobs.  Accepts pandas DataFrames, pyarrow Tables, and
Parquet paths (there is no SparkSession here to accept Spark DataFrames;
convert with ``df.toPandas()`` or point at the Parquet the Spark job
wrote).
"""

from tpuprof import ProfileReport, ProfilerConfig, describe
from tpuprof.report import formatters

from spark_df_profiling import base, templates

__all__ = ["ProfileReport", "ProfilerConfig", "describe", "formatters",
           "base", "templates"]
