"""Reference-layout alias: ``spark_df_profiling.base.describe`` was the
stats entry point in the upstream package (SURVEY.md §1 L2); tpuprof's
``describe`` has the same contract (stats dict out, renderer-ready)."""

from tpuprof.api import describe

__all__ = ["describe"]
