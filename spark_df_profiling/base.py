"""Reference-layout alias: ``spark_df_profiling.base`` held both halves
of the pipeline in the upstream package (SURVEY.md §1 L2/L3) —
``describe`` (stats collection) and ``to_html`` (rendering).  tpuprof's
equivalents keep the same contracts."""

from typing import Any, Dict, Optional

from tpuprof.api import describe


def to_html(sample, stats_object: Dict[str, Any],
            config: Optional[Any] = None) -> str:
    """Reference: ``base.to_html(sample, stats_object)`` — render the
    report fragment from a stats dict (SURVEY §3.1).  ``sample`` is the
    head-rows DataFrame shown in the report's sample section; tpuprof's
    stats dicts already carry one, so pass ``None`` to keep it."""
    from tpuprof.config import ProfilerConfig
    from tpuprof.report.render import to_html as _render
    stats = dict(stats_object)
    if sample is not None:
        stats["sample"] = sample
    return _render(stats, config or ProfilerConfig())


__all__ = ["describe", "to_html"]
