"""Reference-layout alias for ``spark_df_profiling.formatters``
(SURVEY.md §2.1: fmt_percent / fmt_bytesize and friends)."""

from tpuprof.report.formatters import (VALUE_FORMATTERS, alert_class,
                                       fmt_bytesize, fmt_number,
                                       fmt_percent, fmt_stat,
                                       fmt_timedelta, fmt_timestamp,
                                       fmt_value)

__all__ = ["fmt_percent", "fmt_bytesize", "fmt_number", "fmt_timestamp",
           "fmt_timedelta", "fmt_value", "fmt_stat", "alert_class",
           "VALUE_FORMATTERS"]
