"""Command-line interface: ``tpuprof profile data.parquet -o report.html``,
``tpuprof diff A.json B.json -o drift.html``, and the profile-as-a-service
pair — ``tpuprof serve SPOOL`` (resident daemon holding the warm mesh +
compiled-program cache) / ``tpuprof submit SPOOL source -o out.html``
(SURVEY.md §7.1 stage 7; the reference has no CLI — notebook-only — so
these are capabilities the TPU framework adds for batch/cluster/fleet
use).  Job lifecycle itself lives in tpuprof/serve — the CLI is one
client of that scheduler, not its owner."""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpuprof",
        description="TPU-native data profiling: one fused scan, full HTML "
                    "report.")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("profile", help="profile a table and write the report")
    p.add_argument("source", help="Parquet file/directory path")
    p.add_argument("-o", "--output", default="report.html",
                   help="output HTML path (default: report.html)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "cpu", "tpu"])
    p.add_argument("--bins", type=int, default=10)
    p.add_argument("--corr-reject", type=float, default=0.9)
    p.add_argument("--batch-rows", type=int, default=1 << 16)
    p.add_argument("--scan-batches", type=int, default=8, metavar="S",
                   help="prepared batches staged per device dispatch "
                        "(multi-batch scan; 1 disables staging)")
    p.add_argument("--prepare-workers", type=int, default=None,
                   metavar="W",
                   help="host-prep pipeline width: decode/hash/pack of W "
                        "batches in parallel (default: half the cores, "
                        "capped at 4)")
    p.add_argument("--prep-workers", type=int, default=None, metavar="W",
                   help="intra-batch prep parallelism: per-column (and "
                        "per-row-chunk) decode/hash/pack tasks of one "
                        "batch on W shared threads (default: "
                        "TPUPROF_PREP_WORKERS env, else all cores; 1 = "
                        "the serial reference path, byte-identical "
                        "output at any width)")
    p.add_argument("--pass-b-kernel", default=None,
                   choices=("cumulative", "legacy"),
                   help="pass-B binning formulation (default: "
                        "TPUPROF_PASS_B_KERNEL env, else cumulative). "
                        "Both are bit-for-bin identical; legacy is the "
                        "rollback if the cumulative kernel regresses on "
                        "a given chip")
    p.add_argument("--profile-passes", default=None,
                   choices=("two_pass", "fused"),
                   help="profile pass structure (default: "
                        "TPUPROF_PROFILE_PASSES env, else two_pass). "
                        "fused folds moments AND histogram counts in "
                        "one read of every batch on provisional seeded "
                        "bin edges (--seed-edges / watch artifacts; "
                        "first-batch sketch cold) — edge misses re-bin "
                        "in a targeted column-subset pass, so results "
                        "are identical either way; warm edges skip the "
                        "second scan entirely")
    p.add_argument("--seed-edges", metavar="ARTIFACT", default=None,
                   help="seed fused-profile provisional bin edges from "
                        "this tpuprof-stats-v1 artifact of the same "
                        "source (default: TPUPROF_SEED_EDGES env, else "
                        "first-batch sketch).  Advisory: a torn or "
                        "mismatched artifact degrades to the sketch "
                        "with a warning")
    p.add_argument("--sketch-size", type=int, default=4096,
                   help="quantile sample-sketch size K")
    p.add_argument("--hll-precision", type=int, default=11)
    p.add_argument("--single-pass", action="store_true",
                   help="one scan only (sketch-derived histograms/top-k)")
    p.add_argument("--spearman", action="store_true",
                   help="also compute Spearman rank correlations (with "
                        "--single-pass: estimated from the row sample, "
                        "~1/sqrt(K) rank error)")
    p.add_argument("--columns", metavar="A,B,C",
                   help="profile only these columns, in this order (the "
                        "reference's df.select idiom).  Parquet reads "
                        "skip the excluded columns entirely — also the "
                        "escape hatch for nested (list/struct/map) "
                        "columns, whose stringified ingest is ~200x "
                        "slower.  Unknown names error.")
    p.add_argument("--nested", default="stringify",
                   choices=["stringify", "opaque"],
                   help="nested (list/struct/map) column policy: "
                        "'stringify' profiles the str() form (exact, "
                        "but ~200x slower ingest for that column); "
                        "'opaque' reports count/missing/memory only "
                        "with no decode at all")
    p.add_argument("--stats-json", metavar="PATH",
                   help="also dump the FULL stats dict as JSON (table, "
                        "variables, freq, correlations, messages, sample; "
                        "tpuprof-stats-v1: raw numbers, human formatting "
                        "under the parallel 'display' section)")
    p.add_argument("--artifact", metavar="PATH",
                   help="also persist the profile as a CRC-sealed "
                        "tpuprof-stats-v1 stats artifact: the raw-number "
                        "export plus the histogram/top-k sketches "
                        "`tpuprof diff` compares (ARTIFACTS.md).  "
                        "One-shot profiles write stats-only artifacts; "
                        "fold-able (incremental-resumable) ones come "
                        "from the StreamingProfiler API")
    p.add_argument("--warehouse-dir", metavar="DIR",
                   help="with --artifact: ALSO append a columnar "
                        "tpuprof-stats-parquet-v1 generation under "
                        "DIR/<source-key>/ (the profile warehouse — "
                        "one row per column, stats as typed Parquet "
                        "columns, column-pruned reads; ARTIFACTS.md).  "
                        "Default: TPUPROF_WAREHOUSE_DIR, else off")
    p.add_argument("--warehouse-format", default=None,
                   choices=("parquet", "off"),
                   help="columnar warehouse encoding, or 'off' to "
                        "never write one even with a warehouse dir "
                        "configured (the pyarrow-free opt-out; "
                        "default: TPUPROF_WAREHOUSE_FORMAT, else "
                        "parquet)")
    p.add_argument("--trace", metavar="DIR",
                   help="capture a jax.profiler trace into DIR")
    p.add_argument("--metrics-json", metavar="PATH",
                   help="enable pipeline telemetry (tpuprof/obs) and "
                        "stream JSONL events here: span timings as they "
                        "close, checkpoint saves, and metric snapshots "
                        "(see OBSERVABILITY.md).  Also dumps the final "
                        "Prometheus text exposition next to PATH "
                        "(PATH + '.prom'); host 0 additionally writes "
                        "the fleet-merged view (PATH + '.fleet.prom' — "
                        "counters summed across hosts, gauges labelled "
                        "host=N)")
    p.add_argument("--metrics-interval", type=float, default=0.0,
                   metavar="SEC",
                   help="with --metrics-json: emit a metrics snapshot "
                        "every SEC seconds while the profile runs "
                        "(default: one final snapshot only)")
    p.add_argument("--metrics-max-bytes", type=int, default=None,
                   metavar="N",
                   help="JSONL sink growth cap: rotate PATH -> PATH.1 "
                        "once at N bytes so long streams stay disk-"
                        "bounded (~2xN; default: TPUPROF_METRICS_MAX_"
                        "BYTES env, else unlimited)")
    p.add_argument("--progress", action="store_true",
                   help="print a one-line pipeline status (rows, "
                        "batches, dispatches, recent rows/s) to stderr "
                        "every few seconds (implies metrics; interval = "
                        "--metrics-interval, default 5s)")
    p.add_argument("--unique-spill-dir", metavar="DIR",
                   help="spill sorted hash runs here so exact UNIQUE "
                        "classification never falls back to an estimate "
                        "(disk cost: 8 bytes/row per high-cardinality "
                        "column)")
    p.add_argument("--unique-track-rows", type=int, default=None,
                   metavar="N",
                   help="per-column RAM budget (rows) for exact "
                        "UNIQUE/distinct tracking before spilling "
                        "(default: 4M rows = ~32 MB/column)")
    p.add_argument("--unique-track-total-rows", default=None,
                   metavar="N|auto",
                   help="global RAM budget (rows across all columns) "
                        "for exact tracking; 'auto' derives it from "
                        "available RAM (quarter of MemAvailable at "
                        "8 B/row, capped at 2 GB) — the measured "
                        "RAM/speed lever for wide exact-distinct "
                        "shapes (default: "
                        "TPUPROF_UNIQUE_TRACK_TOTAL_ROWS, else 32M "
                        "rows = ~256 MB)")
    p.add_argument("--unique-partitions", type=int, default=None,
                   metavar="P",
                   help="hash partitions of the exact tracker (power "
                        "of two in [1, 256]; results identical at "
                        "every count — this sizes sort/resolve working "
                        "sets, default: TPUPROF_UNIQUE_PARTITIONS, "
                        "else 16)")
    p.add_argument("--unique-spill-workers", type=int, default=None,
                   metavar="W",
                   help="unique-spill run writes in flight on the "
                        "shared io pool while the scan keeps folding "
                        "(0 = synchronous writes; byte-identical "
                        "output at any width; default: "
                        "TPUPROF_UNIQUE_SPILL_WORKERS, else 2)")
    p.add_argument("--exact-distinct", action="store_true",
                   help="count distincts exactly for every column at any "
                        "size (needs --unique-spill-dir; 8 bytes per "
                        "distinct value per column of disk)")
    p.add_argument("--parity", action="store_true",
                   help="reference semantics, exactly, in one switch: "
                        "exact distinct counts for every column (no HLL "
                        "estimate anywhere), exact histograms/top-k "
                        "(second pass), and Spearman.  Auto-derives a "
                        "spill dir under TMPDIR when --unique-spill-dir "
                        "is not given (8 bytes per distinct value per "
                        "column; removed after the profile).  Multi-host "
                        "runs should still pass --unique-spill-dir on "
                        "shared storage.")
    p.add_argument("--checkpoint", metavar="PATH",
                   help="persist the scan every N batches and resume "
                        "from PATH after a crash (multi-host: each host "
                        "writes its own PATH.h<i>of<N> artifact)")
    p.add_argument("--checkpoint-every", type=int, default=64,
                   metavar="N", help="batches between checkpoints")
    ft = p.add_argument_group(
        "fault tolerance", "the degradation ladder (ROBUSTNESS.md): "
        "retry transient batch failures, optionally quarantine poison "
        "batches instead of dying, keep fallback checkpoint "
        "generations, and bound the blocking legs with watchdogs")
    ft.add_argument("--checkpoint-keep", type=int, default=None,
                    metavar="N",
                    help="checkpoint generations retained (PATH + "
                         "PATH.1 ...); restore walks back past a "
                         "corrupt head to the newest good one "
                         "(default: TPUPROF_CHECKPOINT_KEEP, else 2)")
    ft.add_argument("--ingest-retries", type=int, default=None,
                    metavar="N",
                    help="transient per-batch prep failures retried "
                         "with exponential backoff before escalating "
                         "(default: TPUPROF_INGEST_RETRIES, else 2)")
    ft.add_argument("--retry-backoff", type=float, default=None,
                    metavar="SEC",
                    help="first retry's sleep; each further attempt "
                         "doubles it (default: TPUPROF_RETRY_BACKOFF_S, "
                         "else 0.05; 0 retries back-to-back)")
    ft.add_argument("--max-quarantined", type=int, default=None,
                    metavar="N",
                    help="poison-batch budget: skip (and report) up to "
                         "N permanently-failing batches instead of "
                         "dying; the report gains a degraded-run "
                         "banner (default: TPUPROF_MAX_QUARANTINED, "
                         "else 0 = fail fast)")
    ft.add_argument("--quarantine-log", metavar="PATH",
                    help="also append quarantined-batch records to "
                         "PATH as JSONL")
    ft.add_argument("--drain-timeout", type=float, default=None,
                    metavar="SEC",
                    help="watchdog deadline on the device drain; "
                         "expiry exits with a heartbeat snapshot "
                         "instead of hanging (default: "
                         "TPUPROF_DRAIN_TIMEOUT_S, else off)")
    ft.add_argument("--barrier-timeout", type=float, default=None,
                    metavar="SEC",
                    help="watchdog deadline on the multi-host resume "
                         "barrier (default: TPUPROF_BARRIER_TIMEOUT_S, "
                         "else off)")
    fleet = p.add_argument_group(
        "elastic fleet", "work-stealing membership (ROBUSTNESS.md rung "
        "5): launch N independent processes sharing --fleet-dir; each "
        "pulls fragments from the shared manifest, survivors steal a "
        "dead member's fragments and finish with correct stats, and a "
        "restarted member presenting the same --fleet-host-id adopts "
        "its predecessor's claims + checkpoint.  Mutually exclusive "
        "with the --coordinator collective runtime")
    fleet.add_argument("--elastic", action="store_true", default=None,
                       help="enable elastic membership (default: "
                            "TPUPROF_ELASTIC, else off — the "
                            "fixed-membership byte-paths stay "
                            "untouched)")
    fleet.add_argument("--fleet-dir", metavar="DIR",
                       help="shared coordination directory (manifest, "
                            "claims, heartbeats, contributions) on "
                            "storage every member sees (default: "
                            "TPUPROF_FLEET_DIR)")
    fleet.add_argument("--fleet-host-id", metavar="ID",
                       help="stable member identity — pin per slot so "
                            "a restart adopts its predecessor's work "
                            "(default: TPUPROF_FLEET_HOST_ID, else "
                            "hostname-pid)")
    fleet.add_argument("--liveness-timeout", type=float, default=None,
                       metavar="SEC",
                       help="heartbeat staleness before a member is "
                            "declared dead and its fragments stolen "
                            "(default: TPUPROF_LIVENESS_TIMEOUT_S, "
                            "else 10)")
    dist = p.add_argument_group(
        "multi-host", "launch the same command on every host (the "
        "framework owns its launch — no spark-submit analogue needed); "
        "each host scans its own fragment stripe and host 0 writes the "
        "complete merged report")
    dist.add_argument("--coordinator", metavar="HOST:PORT",
                      help="jax.distributed coordinator address "
                           "(e.g. 10.0.0.1:8476)")
    dist.add_argument("--num-processes", type=int, metavar="N",
                      help="total number of participating processes")
    dist.add_argument("--process-id", type=int, metavar="I",
                      help="this process's rank in [0, N)")
    p.add_argument("--aot-cache-dir", metavar="DIR", default=None,
                   help="AOT executable cache: serialize this "
                        "profile's compiled programs under DIR (keyed "
                        "by runner key + environment fingerprint) and "
                        "deserialize on the next same-shape run — "
                        "restart-to-warm in seconds where the jaxlib "
                        "disk cache cannot go (default: "
                        "TPUPROF_AOT_CACHE_DIR, else off for one-shot "
                        "profiles)")
    p.add_argument("--aot-cache", default=None, choices=("on", "off"),
                   help="AOT executable-cache switch: 'off' never "
                        "reads or writes serialized executables even "
                        "with a dir configured (default: "
                        "TPUPROF_AOT_CACHE, else on)")
    cache_group = p.add_mutually_exclusive_group()
    cache_group.add_argument(
        "--compile-cache", metavar="DIR", default=None,
        help="persist XLA executables here (default: "
             "~/.cache/tpuprof/xla — repeat runs skip the one-time "
             "~15-35s compile)")
    cache_group.add_argument(
        "--no-compile-cache", action="store_true",
        help="disable the persistent compilation cache")

    s = sub.add_parser(
        "serve", help="resident profile daemon: hold the mesh + the "
                      "compiled-program cache warm and answer `tpuprof "
                      "submit` jobs from a spool directory in "
                      "sub-seconds instead of a 20-40s cold start each")
    s.add_argument("spool", help="spool directory (jobs/ + results/); "
                                 "clients on this host — or shared "
                                 "storage — drop requests here")
    s.add_argument("--serve-workers", type=int, default=None, metavar="N",
                   help="concurrent jobs on the one warm mesh (host "
                        "prep of job B overlaps job A's device folds; "
                        "default: TPUPROF_SERVE_WORKERS, else 2)")
    s.add_argument("--serve-queue-depth", type=int, default=None,
                   metavar="N",
                   help="admission bound: jobs queued beyond the "
                        "running set before submits REJECT (default: "
                        "TPUPROF_SERVE_QUEUE_DEPTH, else 32)")
    s.add_argument("--serve-tenant-quota", type=int, default=None,
                   metavar="N",
                   help="per-tenant queued+running cap; 0 = unlimited "
                        "(default: TPUPROF_SERVE_TENANT_QUOTA, else 0)")
    s.add_argument("--job-timeout", type=float, default=None,
                   dest="job_timeout_s", metavar="SEC",
                   help="per-job watchdog: a profile running past SEC "
                        "fails with exit-code-4 semantics and frees its "
                        "worker instead of wedging the daemon "
                        "(ROBUSTNESS.md rung 6; default: "
                        "TPUPROF_JOB_TIMEOUT_S, else off)")
    edge = s.add_argument_group(
        "network edge + serve fleet", "HTTP front door on the same "
        "scheduler (POST /v1/jobs, GET /v1/results/<id>, /metrics — "
        "serve/http.py), and multi-daemon membership: N daemons with "
        "--http (or --claim-jobs) sharing ONE spool claim jobs "
        "atomically, heartbeat, and steal a SIGKILLed peer's "
        "unanswered jobs")
    edge.add_argument("--http", type=int, default=None,
                      dest="serve_http_port", metavar="PORT",
                      help="listen for HTTP jobs on PORT (0 = "
                           "ephemeral, advertised under "
                           "SPOOL/daemons/; default: "
                           "TPUPROF_SERVE_HTTP_PORT, else no HTTP "
                           "edge).  Implies --claim-jobs")
    edge.add_argument("--serve-auth-file", metavar="PATH",
                      help="bearer-token file ('<token> <tenant>' "
                           "lines): /v1/* requests must present a "
                           "listed token (401 otherwise) and bill the "
                           "token's tenant quota (default: "
                           "TPUPROF_SERVE_AUTH_FILE, else open edge)")
    edge.add_argument("--claim-jobs", action="store_true",
                      help="fleet mode without HTTP: claim spool jobs "
                           "atomically so N file-spool daemons can "
                           "share one spool")
    edge.add_argument("--daemon-id", metavar="ID",
                      help="stable daemon identity for claims/"
                           "heartbeats — pin per slot so a restart "
                           "adopts its predecessor's unanswered "
                           "claims (default: TPUPROF_FLEET_HOST_ID, "
                           "else hostname-pid)")
    edge.add_argument("--liveness-timeout", type=float, default=None,
                      metavar="SEC",
                      help="heartbeat staleness before a fleet daemon "
                           "is declared dead and its claimed jobs "
                           "stolen (default: "
                           "TPUPROF_LIVENESS_TIMEOUT_S, else 10)")
    overload = s.add_argument_group(
        "overload + drain (ISSUE 19)", "admission shed past a backlog "
        "budget (503 + jittered Retry-After; reads keep serving), "
        "per-connection abuse caps on the HTTP edge, a circuit "
        "breaker on warehouse pushdown, and the SIGTERM graceful-"
        "drain budget")
    overload.add_argument(
        "--serve-backlog", type=int, default=None, metavar="N",
        help="shed budget: non-cacheable submits answer 503 + "
             "Retry-After once N compute jobs are queued; 0 = off — "
             "only the hard --serve-queue-depth 429 bound applies "
             "(default: TPUPROF_SERVE_BACKLOG, else 0)")
    overload.add_argument(
        "--serve-drain-timeout", type=float, default=None,
        dest="serve_drain_timeout_s", metavar="SEC",
        help="graceful-drain budget on SIGTERM: in-flight jobs get "
             "SEC to finish and flush before the daemon exits; "
             "unstarted claimed jobs are released to fleet peers "
             "immediately (default: TPUPROF_SERVE_DRAIN_TIMEOUT_S, "
             "else 30)")
    overload.add_argument(
        "--breaker-threshold", type=int, default=None, metavar="N",
        help="consecutive corrupt/failed warehouse generation reads "
             "per source before /v1/query skips the warehouse tier "
             "for that source (default: TPUPROF_BREAKER_THRESHOLD, "
             "else 3)")
    overload.add_argument(
        "--breaker-cooldown", type=float, default=None,
        dest="breaker_cooldown_s", metavar="SEC",
        help="open-breaker cooldown before one half-open probe is "
             "allowed back through the warehouse (default: "
             "TPUPROF_BREAKER_COOLDOWN_S, else 30)")
    overload.add_argument(
        "--serve-max-connections", type=int, default=None, metavar="N",
        help="open-socket ceiling on the HTTP edge; newcomers past it "
             "get a terse 503 (default: "
             "TPUPROF_SERVE_MAX_CONNECTIONS, else 512)")
    overload.add_argument(
        "--serve-conn-timeout", type=float, default=None,
        dest="serve_conn_timeout_s", metavar="SEC",
        help="per-connection I/O deadline: a client must finish "
             "sending its request (and drain its response) within "
             "SEC — trickling bytes does not extend it (slow-loris "
             "defense; default: TPUPROF_SERVE_CONN_TIMEOUT_S, else "
             "30)")
    overload.add_argument(
        "--serve-max-header-bytes", type=int, default=None, metavar="B",
        help="request-line + header byte cap per request (default: "
             "TPUPROF_SERVE_MAX_HEADER_BYTES, else 64 KiB)")
    overload.add_argument(
        "--serve-max-body-bytes", type=int, default=None, metavar="B",
        help="request body byte cap (default: "
             "TPUPROF_SERVE_MAX_BODY_BYTES, else 1 MiB)")
    aot = s.add_argument_group(
        "restart-to-warm (AOT executable cache)", "after a runner "
        "compiles, its executables serialize into SPOOL/aot keyed by "
        "runner key + environment fingerprint; a RESTARTED daemon "
        "deserializes them in seconds (and prewarms its hottest keys "
        "in the background) instead of re-paying the 20-40 s compile "
        "— GET /v1/healthz reports readiness + prewarm progress")
    aot.add_argument("--aot-cache-dir", metavar="DIR", default=None,
                     help="AOT store root (default: "
                          "TPUPROF_AOT_CACHE_DIR, else SPOOL/aot)")
    aot.add_argument("--aot-cache", default=None, choices=("on", "off"),
                     help="'off' disables the store entirely "
                          "(default: TPUPROF_AOT_CACHE, else on)")
    aot.add_argument("--aot-prewarm", type=int, default=None,
                     metavar="K",
                     help="deserialize the manifest's K hottest "
                          "runner keys at startup, in the background "
                          "(0 = lazy loads only; default: "
                          "TPUPROF_AOT_PREWARM, else 4)")
    read = s.add_argument_group(
        "read-path tier (edge result cache + coalescing)", "terminal "
        "answers keyed by (source fingerprint, config fingerprint): a "
        "repeat submit of an unchanged source serves byte-identical "
        "bytes in microseconds, N concurrent identical submits "
        "collapse onto ONE compute, and POST /v1/query answers column "
        "stats from the warehouse before scheduling anything")
    read.add_argument("--read-cache", default=None,
                      choices=("on", "off"),
                      help="'off' disables the result cache AND "
                           "coalescing — every submit computes "
                           "(default: TPUPROF_READ_CACHE, else on)")
    read.add_argument("--read-cache-entries", type=int, default=None,
                      metavar="N",
                      help="LRU entry cap on the result cache "
                           "(default: TPUPROF_READ_CACHE_ENTRIES, "
                           "else 512)")
    read.add_argument("--read-cache-bytes", type=int, default=None,
                      metavar="B",
                      help="LRU byte cap on cached answer payloads "
                           "(default: TPUPROF_READ_CACHE_BYTES, else "
                           "64 MiB)")
    s.add_argument("--once", action="store_true",
                   help="answer the spool's current jobs, then exit "
                        "(CI / cron mode; default: serve forever)")
    s.add_argument("--poll-interval", type=float, default=0.2,
                   metavar="SEC", help="spool scan cadence")
    s.add_argument("--metrics-json", metavar="PATH",
                   help="stream serve + pipeline JSONL events here and "
                        "dump PATH.prom on exit (OBSERVABILITY.md "
                        "'Profile-as-a-service')")
    s.add_argument("--metrics-interval", type=float, default=0.0,
                   metavar="SEC",
                   help="with --metrics-json: periodic snapshot cadence")
    s.add_argument("--progress", action="store_true",
                   help="one-line pipeline/queue status to stderr every "
                        "few seconds")
    serve_cache = s.add_mutually_exclusive_group()
    serve_cache.add_argument(
        "--compile-cache", metavar="DIR", default=None,
        help="persistent XLA cache for the daemon's FIRST program "
             "build, so a restarted daemon re-warms from disk "
             "(default: ~/.cache/tpuprof/xla; later builds are gated "
             "per-process — see serve/cache.py)")
    serve_cache.add_argument("--no-compile-cache", action="store_true",
                             help="disable the persistent cache")

    w = sub.add_parser(
        "watch", help="continuous drift watch: a serve daemon that "
                      "re-profiles each SOURCE every --every seconds "
                      "through the warm mesh, persists cycle artifacts "
                      "(--keep generations), diffs consecutive cycles "
                      "and raises drift alerts (ROBUSTNESS.md rung 6); "
                      "the spool still answers `tpuprof submit` jobs")
    w.add_argument("spool", help="spool directory — watch state lives "
                                 "under SPOOL/watch/<source-key>/")
    w.add_argument("sources", nargs="+", metavar="SOURCE",
                   help="Parquet file/directory path(s) to watch")
    w.add_argument("--every", type=float, default=None,
                   dest="watch_every_s", metavar="SEC",
                   help="seconds between re-profile cycles per source "
                        "(default: TPUPROF_WATCH_EVERY_S, else 300; "
                        "0 = back-to-back, the CI mode)")
    w.add_argument("--keep", type=int, default=None,
                   dest="artifact_keep", metavar="N",
                   help="cycle artifacts retained per source; the "
                        "drift baseline walks past a corrupt head to "
                        "the newest good generation (default: "
                        "TPUPROF_ARTIFACT_KEEP, else 3)")
    w.add_argument("--cycles", type=int, default=None, metavar="N",
                   help="stop after N cycles over every source "
                        "(CI/cron mode; default: watch forever)")
    w.add_argument("--psi-threshold", type=float, default=None,
                   metavar="X",
                   help="PSI at or above X alerts at drift severity "
                        "(default 0.25; warn band at half)")
    w.add_argument("--ks-threshold", type=float, default=None,
                   metavar="X",
                   help="KS distance at or above X alerts at drift "
                        "severity (default 0.2; warn band at half)")
    w.add_argument("--profile-passes", default=None,
                   choices=("two_pass", "fused"),
                   help="pass structure for the watch's profile jobs "
                        "(default: TPUPROF_PROFILE_PASSES env, else "
                        "two_pass).  fused: each cycle seeds bin edges "
                        "from the previous cycle's artifact and an "
                        "undrifted source profiles in ONE scan")
    w.add_argument("--job-timeout", type=float, default=None,
                   dest="job_timeout_s", metavar="SEC",
                   help="per-job watchdog: a hung cycle profile fails "
                        "(exit-code-4 semantics) and the watch "
                        "continues (default: TPUPROF_JOB_TIMEOUT_S, "
                        "else off)")
    w.add_argument("--serve-workers", type=int, default=None,
                   metavar="N",
                   help="concurrent jobs on the one warm mesh "
                        "(default: TPUPROF_SERVE_WORKERS, else 2)")
    w.add_argument("--poll-interval", type=float, default=0.2,
                   metavar="SEC", help="spool scan cadence")
    w.add_argument("--http", type=int, default=None,
                   dest="serve_http_port", metavar="PORT",
                   help="also serve the HTTP edge (submit + "
                        "GET /v1/watch/<key>/alerts, so watch "
                        "consumers poll the edge instead of the spool "
                        "filesystem; 0 = ephemeral; default: "
                        "TPUPROF_SERVE_HTTP_PORT, else off)")
    w.add_argument("--serve-auth-file", metavar="PATH",
                   help="bearer-token file for the HTTP edge "
                        "(default: TPUPROF_SERVE_AUTH_FILE, else open)")
    w.add_argument("--warehouse-dir", metavar="DIR",
                   help="columnar profile-warehouse root the watch "
                        "loop appends one tpuprof-stats-parquet-v1 "
                        "generation per cycle into (default: "
                        "TPUPROF_WAREHOUSE_DIR, else SPOOL/warehouse "
                        "— the history `tpuprof history` and "
                        "GET /v1/history/<key> answer from)")
    w.add_argument("--warehouse-format", default=None,
                   choices=("parquet", "off"),
                   help="'off' disables the columnar twin (cycles are "
                        "unaffected; default: "
                        "TPUPROF_WAREHOUSE_FORMAT, else parquet)")
    w.add_argument("--aot-cache-dir", metavar="DIR", default=None,
                   help="AOT executable-cache root: a restarted watch "
                        "daemon deserializes its compiled programs "
                        "from here in seconds instead of recompiling "
                        "(default: TPUPROF_AOT_CACHE_DIR, else "
                        "SPOOL/aot)")
    w.add_argument("--aot-cache", default=None, choices=("on", "off"),
                   help="'off' disables the AOT store (default: "
                        "TPUPROF_AOT_CACHE, else on)")
    w.add_argument("--aot-prewarm", type=int, default=None, metavar="K",
                   help="runner keys prewarmed at startup (default: "
                        "TPUPROF_AOT_PREWARM, else 4; 0 = lazy only)")
    w.add_argument("--config-json", metavar="JSON|@FILE",
                   help="ProfilerConfig kwargs applied to every watch "
                        "cycle's profile job, as inline JSON or "
                        "@path-to-file (unknown keys fail the cycle)")
    w.add_argument("--metrics-json", metavar="PATH",
                   help="stream watch_cycle/drift_alert + serve JSONL "
                        "events here and dump PATH.prom on exit")
    w.add_argument("--metrics-interval", type=float, default=0.0,
                   metavar="SEC",
                   help="with --metrics-json: periodic snapshot cadence")
    watch_cache = w.add_mutually_exclusive_group()
    watch_cache.add_argument(
        "--compile-cache", metavar="DIR", default=None,
        help="persistent XLA cache for the daemon's first program "
             "build (default: ~/.cache/tpuprof/xla)")
    watch_cache.add_argument("--no-compile-cache", action="store_true",
                             help="disable the persistent cache")

    u = sub.add_parser(
        "submit", help="hand one profile job to a running `tpuprof "
                       "serve` daemon — through its spool directory or "
                       "its HTTP edge (--url) — and (by default) wait "
                       "for the result")
    u.add_argument("spool", nargs="?", default=None,
                   help="the daemon's spool directory (omit with "
                        "--url)")
    u.add_argument("source", nargs="?", default=None,
                   help="Parquet file/directory path")
    u.add_argument("--url", metavar="http://HOST:PORT",
                   help="submit over the daemon's HTTP edge instead of "
                        "a spool directory (`tpuprof serve --http`); "
                        "an unreachable edge exits 9 "
                        "(ServeUnavailableError)")
    u.add_argument("--token", default=None,
                   help="bearer token for an auth-enabled edge "
                        "(default: TPUPROF_SERVE_TOKEN env); the "
                        "token's tenant is billed, overriding "
                        "--tenant")
    u.add_argument("-o", "--output", default=None,
                   help="output HTML path (default: none — submit "
                        "--stats-json or --artifact instead for "
                        "machine consumers)")
    u.add_argument("--tenant", default="default",
                   help="quota bucket this job bills against")
    u.add_argument("--bins", type=int, default=None)
    u.add_argument("--batch-rows", type=int, default=None)
    u.add_argument("--columns", metavar="A,B,C",
                   help="profile only these columns (the profile "
                        "subcommand's idiom)")
    u.add_argument("--single-pass", action="store_true",
                   help="one scan only (sketch-derived histograms)")
    u.add_argument("--stats-json", metavar="PATH",
                   help="dump the tpuprof-stats-v1 JSON here")
    u.add_argument("--artifact", metavar="PATH",
                   help="persist a CRC-sealed stats artifact here")
    u.add_argument("--config-json", metavar="JSON|@FILE",
                   help="extra ProfilerConfig kwargs as inline JSON or "
                        "@path-to-file — the escape hatch for options "
                        "without a submit flag (unknown keys REJECT)")
    u.add_argument("--no-wait", action="store_true",
                   help="enqueue and print the job id without waiting")
    u.add_argument("--timeout", type=float, default=None, metavar="SEC",
                   help="give up waiting after SEC (the job keeps "
                        "running server-side)")
    u.add_argument("--deadline-ms", type=int, default=None, metavar="MS",
                   help="answer-within budget the DAEMON enforces "
                        "(X-Tpuprof-Deadline-Ms): a job still queued "
                        "MS milliseconds after submit is never "
                        "started — it fails typed "
                        "(DeadlineExceededError, exit code 11) "
                        "instead of running for a client that stopped "
                        "caring")

    d = sub.add_parser(
        "diff", help="compare two stats artifacts and report per-column "
                     "drift (PSI/KS from stored histograms, distinct/"
                     "top-k churn, schema changes — ARTIFACTS.md)")
    d.add_argument("baseline", help="baseline artifact (A) path")
    d.add_argument("current", help="current artifact (B) path")
    d.add_argument("-o", "--output", default="drift.html",
                   help="drift report HTML path (default: drift.html)")
    d.add_argument("--json", metavar="PATH", dest="drift_json",
                   help="also write the machine-readable "
                        "tpuprof-drift-v1 report here")
    d.add_argument("--psi-threshold", type=float, default=None,
                   metavar="X",
                   help="PSI at or above X flags a column as drifting "
                        "(default 0.25; warn band at half)")
    d.add_argument("--ks-threshold", type=float, default=None,
                   metavar="X",
                   help="KS distance at or above X flags a column as "
                        "drifting (default 0.2; warn band at half)")
    d.add_argument("--fail-on-drift", action="store_true",
                   help="exit 1 when any column reaches drift severity "
                        "(CI gate); corrupt artifacts exit 6 either way")

    hi = sub.add_parser(
        "history", help="query the columnar profile warehouse: one "
                        "column's stat across every profiled "
                        "generation (`--stat mean --col price`), or "
                        "the PSI/KS drift trend between consecutive "
                        "generations (`--trend`) — column-pruned "
                        "Parquet reads, corrupt generations walked "
                        "past (ARTIFACTS.md 'Profile warehouse')")
    hi.add_argument("source",
                    help="the watched/profiled source path (resolved "
                         "to its warehouse key), a warehouse key, or "
                         "a per-source warehouse directory")
    hi.add_argument("--warehouse-dir", metavar="DIR", default=None,
                    help="warehouse root (default: "
                         "TPUPROF_WAREHOUSE_DIR; see also --spool)")
    hi.add_argument("--spool", metavar="DIR", default=None,
                    help="a watch daemon's spool — shorthand for "
                         "--warehouse-dir SPOOL/warehouse")
    hi.add_argument("--col", metavar="NAME", default=None,
                    help="the profiled column to query (required "
                         "unless --trend, where it is an optional "
                         "filter)")
    hi.add_argument("--stat", metavar="STAT", default="mean",
                    help="which stat column to read (default: mean; "
                         "any tpuprof-stats-v1 numeric stat — std, "
                         "p_missing, distinct_count, p95, ...)")
    hi.add_argument("--trend", action="store_true",
                    help="PSI/KS between every consecutive pair of "
                         "generations instead of a stat series "
                         "(computed from the stored histogram "
                         "sketches by the tpuprof-drift-v1 engine)")
    hi.add_argument("--json", action="store_true", dest="as_json",
                    help="print the machine-readable "
                         "tpuprof-history-v1 document to stdout "
                         "instead of the human table")

    b = sub.add_parser(
        "backtest", help="replay changed alert thresholds against a "
                         "watched source's retained artifact chain: "
                         "which cycles WOULD have alerted under "
                         "--psi-threshold X?  Uses the live watch "
                         "loop's own drift/dedup rules, so the replay "
                         "at the live thresholds reproduces the live "
                         "alert set exactly")
    b.add_argument("source",
                   help="the watched source path (resolved to its "
                        "chain under SPOOL/watch/<key>/), or a "
                        "directory of cycle_*.artifact.json files")
    b.add_argument("--spool", metavar="DIR", default=None,
                   help="the watch daemon's spool directory holding "
                        "the retained chain")
    b.add_argument("--psi-threshold", type=float, default=None,
                   metavar="X",
                   help="PSI at or above X alerts at drift severity "
                        "(default 0.25; warn band at half)")
    b.add_argument("--ks-threshold", type=float, default=None,
                   metavar="X",
                   help="KS distance at or above X alerts at drift "
                        "severity (default 0.2; warn band at half)")
    b.add_argument("--json", action="store_true", dest="as_json",
                   help="print the machine-readable "
                        "tpuprof-backtest-v1 document to stdout")

    l = sub.add_parser(
        "lint", help="run the AST-enforced invariant suite over the "
                     "source tree (tpuprof/analysis; ANALYSIS.md): "
                     "durability seams, config surface, obs contracts, "
                     "error taxonomy, runtime discipline")
    l.add_argument("root", nargs="?", default=None,
                   help="repo root holding tpuprof/ + the docs "
                        "(default: the checkout this tpuprof package "
                        "was imported from)")
    l.add_argument("--json", metavar="PATH", dest="lint_json",
                   help="also write the machine-readable "
                        "tpuprof-lint-v1 report here")
    l.add_argument("--strict", action="store_true",
                   help="ignore the suppression file: report every "
                        "finding, absorb none")
    l.add_argument("--suppressions", metavar="PATH", default=None,
                   help="suppression file (default: LINT_SUPPRESSIONS "
                        "at the root; '<checker> <ident-glob> "
                        "<reason>' lines)")
    l.add_argument("--only", metavar="ID[,ID...]", default=None,
                   help="run only these checker ids (comma-separated)")
    l.add_argument("--list", action="store_true", dest="lint_list",
                   help="list checker ids + one-line docs and exit")
    return parser


def cmd_diff(args: argparse.Namespace) -> int:
    from tpuprof.artifact import (DriftThresholds, compute_drift,
                                  drift_to_html, read_artifact)
    from tpuprof.errors import CorruptArtifactError, exit_code
    try:
        base = read_artifact(args.baseline)
        current = read_artifact(args.current)
    except FileNotFoundError as exc:
        print(f"tpuprof: error: {exc}", file=sys.stderr)
        return 2
    except CorruptArtifactError as exc:
        # the integrity rung (ROBUSTNESS.md): a torn artifact is a
        # one-line typed failure with its own exit code — it must never
        # silently become a wrong drift report
        print(f"tpuprof: error: {exc}", file=sys.stderr)
        return exit_code(exc)
    thresholds = DriftThresholds.from_cli(psi=args.psi_threshold,
                                          ks=args.ks_threshold)
    drift = compute_drift(base, current, thresholds)
    with open(args.output, "w", encoding="utf-8") as fh:
        fh.write(drift_to_html(drift))
    if args.drift_json:
        with open(args.drift_json, "w") as fh:
            json.dump(drift, fh, indent=2)
    s = drift["summary"]
    print(f"tpuprof: diff {args.baseline} -> {args.current}: "
          f"{s['verdict'].upper()} — {s['n_drift']} drifting, "
          f"{s['n_warn']} warning, {s['n_ok']} stable of "
          f"{s['columns_compared']} columns -> {args.output}",
          file=sys.stderr)
    if args.fail_on_drift and s["n_drift"]:
        return 1
    return 0


def _resolve_history_dir(args: argparse.Namespace) -> str:
    """The per-source warehouse directory a history query reads:
    ``--warehouse-dir``/env (or ``--spool``'s SPOOL/warehouse) plus the
    source key — or the source itself when it already IS a per-source
    warehouse directory."""
    from tpuprof.config import resolve_warehouse_dir
    from tpuprof.errors import InputError
    from tpuprof.warehouse import source_dir
    root = resolve_warehouse_dir(args.warehouse_dir) \
        or (os.path.join(args.spool, "warehouse") if args.spool else None)
    if root is None:
        from tpuprof.warehouse.store import _has_generations
        if os.path.isdir(args.source) and _has_generations(args.source):
            return args.source
        raise InputError(
            "history needs the warehouse root: pass --warehouse-dir "
            "(or TPUPROF_WAREHOUSE_DIR), --spool SPOOL for a watch "
            "daemon's SPOOL/warehouse, or point SOURCE at a "
            "per-source warehouse directory directly")
    return source_dir(root, args.source)


def cmd_history(args: argparse.Namespace) -> int:
    from tpuprof.errors import TYPED_ERRORS, exit_code
    from tpuprof.warehouse import query_stat, query_trend
    try:
        dirpath = _resolve_history_dir(args)
        if args.trend:
            doc = query_trend(dirpath, col=args.col)
        else:
            if not args.col:
                print("tpuprof: error: history needs --col NAME (or "
                      "--trend for the drift series)", file=sys.stderr)
                return 2
            doc = query_stat(dirpath, args.col, args.stat)
    except TYPED_ERRORS as exc:
        print(f"tpuprof: error: {exc}", file=sys.stderr)
        return exit_code(exc)
    if args.as_json:
        json.dump(doc, sys.stdout, indent=1)
        print()
    elif args.trend:
        print(f"# trend over {doc['generations']} generation pair(s) "
              f"in {doc['warehouse']}"
              + (f" (skipped corrupt: {doc['skipped_corrupt']})"
                 if doc["skipped_corrupt"] else ""))
        print("generation  baseline  column  psi  ks")
        for entry in doc["series"]:
            for name, m in sorted(entry["columns"].items()):
                print(f"{entry['generation']:>10}  "
                      f"{entry['baseline_generation']:>8}  {name}  "
                      f"{m['psi']}  {m['ks']}")
    else:
        print(f"# {args.stat}({args.col}) over {doc['generations']} "
              f"generation(s) in {doc['warehouse']}"
              + (f" (skipped corrupt: {doc['skipped_corrupt']})"
                 if doc["skipped_corrupt"] else ""))
        print("generation  rows  value")
        for entry in doc["series"]:
            print(f"{entry['generation']:>10}  "
                  f"{entry['rows'] if entry['rows'] is not None else '?':>4}"
                  f"  {entry['value']}")
    return 0


def cmd_backtest(args: argparse.Namespace) -> int:
    from tpuprof.artifact import DriftThresholds
    from tpuprof.errors import TYPED_ERRORS, exit_code
    from tpuprof.warehouse import backtest as _backtest
    from tpuprof.warehouse import chain_dir
    thresholds = DriftThresholds.from_cli(psi=args.psi_threshold,
                                          ks=args.ks_threshold)
    try:
        dirpath = chain_dir(args.spool, args.source)
        doc = _backtest(dirpath, thresholds)
    except TYPED_ERRORS as exc:
        print(f"tpuprof: error: {exc}", file=sys.stderr)
        return exit_code(exc)
    if args.as_json:
        json.dump(doc, sys.stdout, indent=1)
        print()
        return 0
    s = doc["summary"]
    print(f"tpuprof: backtest {doc['chain']}: {s['alerts']} alert(s) "
          f"over {s['cycles']} retained cycle(s) "
          f"({s['drift_cycles']} drift, {s['warn_cycles']} warn"
          + (f", {s['unreadable']} unreadable" if s["unreadable"]
             else "") + ")", file=sys.stderr)
    for a in doc["alerts"]:
        cols = ",".join(a["columns"][:6]) + \
            ("…" if len(a["columns"]) > 6 else "")
        print(f"cycle {a['cycle']:>6}  {a['severity']:<6} "
              f"{a['n_drift']} drifting / {a['n_warn']} warning  "
              f"[{cols}]")
    return 0


def _default_lint_root() -> str:
    """The checkout this package was imported from: the directory
    holding the ``tpuprof/`` package dir (which is where the docs the
    checkers parse live in a source tree)."""
    import tpuprof
    return os.path.dirname(os.path.dirname(os.path.abspath(
        tpuprof.__file__)))


def cmd_lint(args: argparse.Namespace) -> int:
    from tpuprof import analysis
    from tpuprof.errors import LintFindingsError, exit_code
    if args.lint_list:
        for cid in analysis.checker_ids():
            print(f"{cid}: {analysis.checker_doc(cid)}")
        return 0
    root = args.root or _default_lint_root()
    only = [c.strip() for c in args.only.split(",")] if args.only \
        else None
    try:
        report = analysis.run_lint(root, only=only,
                                   suppressions=args.suppressions,
                                   strict=args.strict)
    except ValueError as exc:           # unknown checker id
        print(f"tpuprof: error: {exc}", file=sys.stderr)
        return 2
    analysis.observe(report)
    if args.lint_json:
        with open(args.lint_json, "w") as fh:
            fh.write(report.to_json())
    unsuppressed = report.unsuppressed()
    for f in unsuppressed:
        print(f.format())
    n_sup = len(report.suppressed)
    if unsuppressed:
        exc = LintFindingsError(
            f"{len(unsuppressed)} finding(s) across "
            f"{len(report.counts_by_checker())} checker(s)"
            + (f" ({n_sup} suppressed)" if n_sup else ""))
        print(f"tpuprof lint: {exc}", file=sys.stderr)
        return exit_code(exc)
    print(f"tpuprof lint: clean — {len(report.checkers_run)} checkers"
          + (f", {n_sup} suppressed finding(s)" if n_sup else "")
          + f" in {report.wall_s:.2f}s", file=sys.stderr)
    return 0


def _resolve_cache_dir(args: argparse.Namespace):
    """Shared by ``profile`` and ``serve``: --no-compile-cache actively
    disables, an explicit --compile-cache wins, else the XDG default."""
    if args.no_compile_cache:
        # actively clear: a prior in-process run (or wrapper) may have
        # pointed jax at a directory, and "disabled" must mean no writes
        from tpuprof.backends.tpu import disable_compile_cache
        disable_compile_cache()
        return None
    if args.compile_cache:
        return args.compile_cache
    import os
    # `or` (not a .get default): the XDG spec treats an EMPTY
    # XDG_CACHE_HOME as unset, and '' would yield a cwd-relative dir
    return os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.expanduser("~/.cache"),
        "tpuprof", "xla")


def cmd_serve(args: argparse.Namespace) -> int:
    from tpuprof import obs
    from tpuprof.obs import blackbox
    from tpuprof.serve import ServeDaemon

    # idempotent by contract (ISSUE 9 satellite): a daemon re-invoking
    # install per job/config reload wraps the handlers exactly once,
    # and SIGUSR1 postmortems carry the live job-queue snapshot via the
    # scheduler's dump-time context provider
    blackbox.install_signal_handlers()
    cache_dir = _resolve_cache_dir(args)
    if cache_dir:
        from tpuprof.backends.tpu import _enable_compile_cache
        _enable_compile_cache(cache_dir)
    ticker = None
    if args.metrics_json or args.progress:
        obs.configure(enabled=True, jsonl_path=args.metrics_json)
        interval = args.metrics_interval or (5.0 if args.progress else 0.0)
        if interval > 0:
            from tpuprof.obs.progress import Ticker
            ticker = Ticker(interval, progress=args.progress,
                            snapshots=bool(args.metrics_json)).start()
    from tpuprof.config import (resolve_aot_cache,
                                resolve_aot_cache_dir,
                                resolve_read_cache,
                                resolve_read_cache_bytes,
                                resolve_read_cache_entries,
                                resolve_serve_auth_file,
                                resolve_serve_http_port)
    http_port = resolve_serve_http_port(args.serve_http_port)
    # restart-to-warm (ISSUE 15): the daemon's AOT store defaults to
    # SPOOL/aot — a restarted daemon deserializes its compiled
    # programs instead of re-paying the mesh+compile cost
    aot_dir = None
    if resolve_aot_cache(args.aot_cache) == "on":
        aot_dir = resolve_aot_cache_dir(args.aot_cache_dir) \
            or os.path.join(args.spool, "aot")
    # the HTTP edge implies fleet claims: N `--http` daemons on one
    # spool is the deployment shape the edge exists for, and claims
    # are what keep them from double-running each other's jobs
    daemon = ServeDaemon(args.spool, poll_interval=args.poll_interval,
                         claim_jobs=bool(args.claim_jobs
                                         or http_port is not None),
                         daemon_id=args.daemon_id,
                         liveness_timeout_s=args.liveness_timeout,
                         drain_timeout_s=args.serve_drain_timeout_s,
                         workers=args.serve_workers,
                         queue_depth=args.serve_queue_depth,
                         tenant_quota=args.serve_tenant_quota,
                         job_timeout_s=args.job_timeout_s,
                         aot_cache_dir=aot_dir,
                         aot_cache=args.aot_cache,
                         aot_prewarm=args.aot_prewarm,
                         read_cache=resolve_read_cache(args.read_cache),
                         read_cache_entries=resolve_read_cache_entries(
                             args.read_cache_entries),
                         read_cache_bytes=resolve_read_cache_bytes(
                             args.read_cache_bytes),
                         serve_backlog=args.serve_backlog)
    sched = daemon.scheduler
    if aot_dir:
        print(f"tpuprof: aot executable cache at {aot_dir} "
              f"(prewarming "
              f"{daemon.prewarmer.top_k if daemon.prewarmer else 0} "
              "hottest keys)", file=sys.stderr)
    edge = None
    if http_port is not None:
        from tpuprof.config import (resolve_breaker_cooldown,
                                    resolve_breaker_threshold)
        from tpuprof.errors import InputError
        from tpuprof.serve.breaker import CircuitBreaker
        from tpuprof.serve.http import HttpEdge
        try:
            edge = HttpEdge(
                daemon, port=http_port,
                auth_file=resolve_serve_auth_file(
                    args.serve_auth_file),
                max_connections=args.serve_max_connections,
                conn_timeout_s=args.serve_conn_timeout_s,
                max_header_bytes=args.serve_max_header_bytes,
                max_body_bytes=args.serve_max_body_bytes,
                breaker=CircuitBreaker(
                    threshold=resolve_breaker_threshold(
                        args.breaker_threshold),
                    cooldown_s=resolve_breaker_cooldown(
                        args.breaker_cooldown_s))).start()
        except (InputError, OSError) as exc:
            # bad auth file / port in use: refuse to start, one line
            print(f"tpuprof: error: http edge: {exc}", file=sys.stderr)
            daemon.close(timeout=5)
            return 2
        print(f"tpuprof: http edge on {edge.url}"
              + (" (auth required)" if edge.tokens else " (open)"),
              file=sys.stderr)
    # a daemon drains on SIGTERM (finish running jobs, flush results +
    # the .prom dump, exit 0) — overriding the flight recorder's
    # dump-and-die-by-signal disposition, which is right for a crashed
    # PROFILE but turns a routine daemon stop into a signal death with
    # a postmortem.  SIGUSR1 keeps the recorder's dump-and-continue
    # (now carrying the live queue snapshot).
    import signal as _signal

    def _graceful(signum, frame):
        blackbox.record("signal", name="SIGTERM", action="drain")
        daemon.stop_event.set()     # /v1/healthz flips to "draining"
        if edge is not None:
            # stop accepting new sockets NOW; established connections
            # keep draining and in-flight answers are delivered
            edge.stop_accepting()

    try:
        _signal.signal(_signal.SIGTERM, _graceful)
    except (ValueError, OSError):
        pass                    # non-main thread: rely on stop_event
    print(f"tpuprof: serving {args.spool} — {sched.workers} workers, "
          f"queue depth {sched._queue.depth}, tenant quota "
          f"{sched._queue.tenant_quota or 'unlimited'}"
          + (" (once)" if args.once else ""), file=sys.stderr)
    try:
        daemon.run(once=args.once)
    except KeyboardInterrupt:
        pass
    finally:
        if edge is not None:
            edge.close()            # stop accepting before draining
        daemon.close()
        if ticker is not None:
            ticker.stop()
        if args.metrics_json:
            obs.finalize(reason="serve")
            with open(args.metrics_json + ".prom", "w") as fh:
                fh.write(obs.registry().render_text())
    st = sched.stats()
    print(f"tpuprof: served {st['requests']} jobs "
          f"({st['done']} done, {st['failed']} failed, "
          f"{st['rejected']} rejected) · p50 {st['p50_s']}s "
          f"p99 {st['p99_s']}s · compile cache "
          f"{st['cache']['hits']}/{st['cache']['hits'] + st['cache']['misses']} hits",
          file=sys.stderr)
    return 0


def _parse_config_json(raw) -> dict:
    """``--config-json JSON|@FILE`` (submit and watch): a dict of extra
    ProfilerConfig kwargs.  Raises ValueError in the CLI's bad-request
    convention."""
    if not raw:
        return {}
    try:
        if raw.startswith("@"):
            with open(raw[1:]) as fh:
                extra = json.load(fh)
        else:
            extra = json.loads(raw)
    except OSError as exc:
        raise ValueError(str(exc)) from exc
    if not isinstance(extra, dict):
        raise ValueError("must be a JSON object")
    return extra


def cmd_watch(args: argparse.Namespace) -> int:
    from tpuprof import obs
    from tpuprof.artifact import DriftThresholds
    from tpuprof.obs import blackbox
    from tpuprof.serve import DriftWatcher, ServeDaemon

    try:
        config_kwargs = _parse_config_json(args.config_json)
    except ValueError as exc:
        print(f"tpuprof: error: --config-json: {exc}", file=sys.stderr)
        return 2
    if getattr(args, "profile_passes", None):
        config_kwargs.setdefault("profile_passes", args.profile_passes)
    blackbox.install_signal_handlers()
    cache_dir = _resolve_cache_dir(args)
    if cache_dir:
        from tpuprof.backends.tpu import _enable_compile_cache
        _enable_compile_cache(cache_dir)
    ticker = None
    if args.metrics_json:
        obs.configure(enabled=True, jsonl_path=args.metrics_json)
        if args.metrics_interval > 0:
            from tpuprof.obs.progress import Ticker
            ticker = Ticker(args.metrics_interval,
                            snapshots=True).start()
    from tpuprof.config import (resolve_aot_cache,
                                resolve_aot_cache_dir,
                                resolve_serve_auth_file,
                                resolve_serve_http_port)
    http_port = resolve_serve_http_port(args.serve_http_port)
    # restart-to-warm (ISSUE 15): the watch daemon's cycles share the
    # serve AOT store default, so a restarted watch is profiling at
    # warm latency in seconds
    aot_dir = None
    if resolve_aot_cache(args.aot_cache) == "on":
        aot_dir = resolve_aot_cache_dir(args.aot_cache_dir) \
            or os.path.join(args.spool, "aot")
    daemon = ServeDaemon(args.spool, poll_interval=args.poll_interval,
                         claim_jobs=http_port is not None,
                         workers=args.serve_workers,
                         job_timeout_s=args.job_timeout_s,
                         aot_cache_dir=aot_dir,
                         aot_cache=args.aot_cache,
                         aot_prewarm=args.aot_prewarm)
    edge = None
    if http_port is not None:
        from tpuprof.errors import InputError
        from tpuprof.serve.http import HttpEdge
        try:
            edge = HttpEdge(
                daemon, port=http_port,
                auth_file=resolve_serve_auth_file(
                    args.serve_auth_file)).start()
        except (InputError, OSError) as exc:
            print(f"tpuprof: error: http edge: {exc}", file=sys.stderr)
            daemon.close(timeout=5)
            return 2
        print(f"tpuprof: http edge on {edge.url} (alert feeds at "
              f"/v1/watch/<key>/alerts)", file=sys.stderr)
    watcher = DriftWatcher(
        args.spool, args.sources, daemon.scheduler,
        every_s=args.watch_every_s, keep=args.artifact_keep,
        thresholds=DriftThresholds.from_cli(psi=args.psi_threshold,
                                            ks=args.ks_threshold),
        job_timeout_s=args.job_timeout_s, config_kwargs=config_kwargs,
        warehouse_dir=args.warehouse_dir,
        warehouse_format=args.warehouse_format)
    blackbox.set_context(watch_sources=[w.source
                                        for w in watcher.watches])

    import signal as _signal
    import threading as _threading

    def _graceful(signum, frame):
        blackbox.record("signal", name="SIGTERM", action="drain")
        watcher.stop_event.set()
        daemon.stop_event.set()

    try:
        _signal.signal(_signal.SIGTERM, _graceful)
    except (ValueError, OSError):
        pass                    # non-main thread: rely on stop_event
    print(f"tpuprof: watching {len(watcher.watches)} source(s) every "
          f"{watcher.every_s:g}s (keep {watcher.keep}"
          + (f", job timeout {watcher.job_timeout_s:g}s"
             if watcher.job_timeout_s else "")
          + f") — spool {args.spool}"
          + (f" ({args.cycles} cycles)" if args.cycles else ""),
          file=sys.stderr)
    # the spool keeps answering `tpuprof submit` while the watch runs:
    # the daemon's poll loop rides a background thread, the watch loop
    # owns the foreground
    spool_thread = _threading.Thread(target=daemon.run, daemon=True,
                                     name="tpuprof-watch-spool")
    spool_thread.start()
    try:
        watcher.run(cycles=args.cycles)
    except KeyboardInterrupt:
        pass
    finally:
        watcher.stop_event.set()
        daemon.stop_event.set()
        if edge is not None:
            edge.close()
        spool_thread.join(timeout=30)
        daemon.close()
        if ticker is not None:
            ticker.stop()
        if args.metrics_json:
            obs.finalize(reason="watch")
            with open(args.metrics_json + ".prom", "w") as fh:
                fh.write(obs.registry().render_text())
    st = watcher.stats()
    c = st["cycles"]
    print(f"tpuprof: watched {st['sources']} source(s): "
          f"{c['ok']} ok, {c['warn']} warn, {c['drift']} drift, "
          f"{c['failed']} failed cycles · {st['alerts']} alerts on "
          f"file", file=sys.stderr)
    return 0


def cmd_submit(args: argparse.Namespace) -> int:
    import os

    from tpuprof.errors import CorruptResultError, exit_code
    from tpuprof.serve import wait_result, write_job

    # `submit SPOOL SOURCE` or `submit --url URL SOURCE`: with --url
    # the single positional is the source (argparse fills
    # left-to-right, so it lands in `spool`)
    if args.url:
        if args.source is None:
            args.spool, args.source = None, args.spool
        if args.spool is not None:
            print("tpuprof: error: pass either a spool directory or "
                  "--url, not both", file=sys.stderr)
            return 2
    if args.source is None:
        print("tpuprof: error: submit needs a source path (and a "
              "spool directory or --url)", file=sys.stderr)
        return 2
    if args.spool is None and not args.url:
        print("tpuprof: error: submit needs the daemon's spool "
              "directory (or --url for its HTTP edge)",
              file=sys.stderr)
        return 2

    config = {}
    if args.bins is not None:
        config["bins"] = args.bins
    if args.batch_rows is not None:
        config["batch_rows"] = args.batch_rows
    if args.columns is not None:
        cols = tuple(c.strip() for c in args.columns.split(",")
                     if c.strip())
        config["columns"] = cols
    if args.single_pass:
        config["exact_passes"] = False
    try:
        config.update(_parse_config_json(args.config_json))
    except ValueError as exc:
        print(f"tpuprof: error: --config-json: {exc}",
              file=sys.stderr)
        return 2
    if args.url:
        from tpuprof.errors import ServeUnavailableError
        from tpuprof.serve import submit_job, wait_result_http
        token = args.token or os.environ.get("TPUPROF_SERVE_TOKEN")
        try:
            code, doc = submit_job(
                args.url, args.source, output=args.output,
                tenant=args.tenant, stats_json=args.stats_json,
                artifact=args.artifact, config_kwargs=config,
                token=token, deadline_ms=args.deadline_ms)
        except ServeUnavailableError as exc:
            # the edge itself is down: ITS typed exit code (9), so a
            # retry wrapper can tell "edge unreachable" from "the job
            # was rejected" without parsing prose
            print(f"tpuprof: error: {exc}", file=sys.stderr)
            return exit_code(exc)
        if code == 401:
            print(f"tpuprof: error: {doc.get('error', 'unauthorized')}"
                  " (pass --token or set TPUPROF_SERVE_TOKEN)",
                  file=sys.stderr)
            return 2
        if code == 503 and doc.get("reject_kind") == "BacklogFull":
            # overload shed (ISSUE 19): the daemon is deliberately
            # degrading to reads-only — the typed serve-plane exit
            # code (9), with the server's Retry-After hint, so a
            # retry wrapper backs off instead of hammering
            print(f"tpuprof: error: job shed (HTTP 503): "
                  f"{doc.get('error', doc)}", file=sys.stderr)
            from tpuprof.errors import ServeUnavailableError as _SUE
            return exit_code(_SUE(""))
        if code not in (200, 202):
            # the daemon answered and said no: 429 carries the
            # scheduler's reject reason, 400 the request's own fault
            print(f"tpuprof: error: job rejected (HTTP {code}): "
                  f"{doc.get('error', doc)}", file=sys.stderr)
            return 2
        job_id = doc["id"]
        if args.no_wait:
            print(job_id)
            return 0
        try:
            result = wait_result_http(args.url, job_id,
                                      timeout=args.timeout, token=token)
        except ServeUnavailableError as exc:
            print(f"tpuprof: error: {exc}", file=sys.stderr)
            return exit_code(exc)
        except CorruptResultError as exc:
            print(f"tpuprof: error: {exc}", file=sys.stderr)
            return exit_code(exc)
        except TimeoutError as exc:
            print(f"tpuprof: error: {exc}", file=sys.stderr)
            return 4                # the watchdog-shaped failure
    else:
        # a relative --deadline-ms budget resolves to an absolute wall
        # clock HERE, at submit time — the spool file may sit unclaimed
        # for a while, and that wait is exactly what the deadline bounds
        deadline_unix_ms = (int((time.time() + args.deadline_ms / 1000.0)
                                * 1000)
                            if args.deadline_ms is not None else None)
        job_id = write_job(args.spool, args.source, output=args.output,
                           tenant=args.tenant,
                           stats_json=args.stats_json,
                           artifact=args.artifact, config_kwargs=config,
                           deadline_unix_ms=deadline_unix_ms)
        if args.no_wait:
            print(job_id)
            return 0
        try:
            result = wait_result(args.spool, job_id,
                                 timeout=args.timeout)
        except CorruptResultError as exc:
            # the result landed but rotted (non-atomic fs crash, disk
            # rot): the integrity rung's exit code, not a "daemon
            # down" timeout
            print(f"tpuprof: error: {exc}", file=sys.stderr)
            return exit_code(exc)
        except TimeoutError as exc:
            print(f"tpuprof: error: {exc}", file=sys.stderr)
            return 4                # the watchdog-shaped failure
    status = result.get("status")
    if status == "done":
        rows = result.get("rows")
        rows_s = f"{rows:,}" if isinstance(rows, int) else "?"
        print(f"tpuprof: job {job_id}: {rows_s} rows "
              f"x {result.get('cols', '?')} cols -> "
              f"{result.get('output') or args.stats_json or '(no output)'}"
              f" in {result.get('seconds', 0)}s "
              f"(queued {result.get('queue_seconds', 0)}s)",
              file=sys.stderr)
        return 0
    print(f"tpuprof: error: job {job_id} {status}: "
          f"{result.get('error', 'unknown')}", file=sys.stderr)
    if status == "rejected":
        return 2                    # the CLI's bad-request convention
    return int(result.get("exit_code") or 1)


def cmd_profile(args: argparse.Namespace) -> int:
    from tpuprof import ProfileReport, ProfilerConfig
    from tpuprof.errors import (CorruptCheckpointError,
                                CorruptManifestError, HostDeathError,
                                InputError, PoisonBatchError,
                                WatchdogTimeout, exit_code)
    from tpuprof.obs import blackbox
    from tpuprof.utils.trace import phase_timer, trace_to

    # crash flight recorder (obs/blackbox.py): always on unless
    # TPUPROF_BLACKBOX=0 — SIGTERM/SIGUSR1 dump the ring, and every
    # typed error below leaves a tpuprof-postmortem-<pid>.json
    blackbox.install_signal_handlers()

    # flag-interaction constraints (--exact-distinct without a spill
    # dir, --parity with --single-pass, ...) are enforced ONCE, by
    # ProfilerConfig.__post_init__; its ValueError is reported through
    # the config try/except below in the CLI's error convention

    multi_host = args.coordinator is not None \
        or args.num_processes is not None or args.process_id is not None
    if multi_host:
        if args.coordinator is None or args.num_processes is None \
                or args.process_id is None:
            print("tpuprof: error: multi-host needs all three of "
                  "--coordinator, --num-processes and --process-id",
                  file=sys.stderr)
            return 2
        if args.backend == "cpu":
            print("tpuprof: error: --backend cpu has no fragment "
                  "striping — every process would profile the whole "
                  "dataset; multi-host requires the tpu engine (which "
                  "also runs on CPU devices)", file=sys.stderr)
            return 2
        if args.parity and not args.unique_spill_dir:
            # config's auto-derived dir is HOST-LOCAL; the cross-host
            # merge could not adopt peers' spill runs and exact distinct
            # counts would silently degrade to estimates — the opposite
            # of what --parity promises.  This is a cross-flag constraint
            # config cannot see (it has no notion of multi-host).
            print("tpuprof: error: multi-host --parity needs "
                  "--unique-spill-dir on storage SHARED by all hosts "
                  "(the auto-derived TMPDIR dir is host-local)",
                  file=sys.stderr)
            return 2
        # 'auto' could resolve to the pandas oracle on a CPU-only
        # cluster, which ignores process striping — the tpu engine is
        # the multi-host engine on every platform
        args.backend = "tpu"
        # must run before ANY other jax usage in this process
        from tpuprof.runtime.distributed import initialize
        initialize(args.coordinator, args.num_processes, args.process_id)

    cache_dir = _resolve_cache_dir(args)

    columns = None
    if args.columns is not None:
        # "" (an unset shell variable) parses to an EMPTY tuple, which
        # ProfilerConfig rejects below — same outcome as "," or " ",
        # never a silent full profile
        columns = tuple(c.strip() for c in args.columns.split(",")
                        if c.strip())

    try:
        config = ProfilerConfig(
            backend=args.backend, columns=columns, nested=args.nested,
            bins=args.bins, corr_reject=args.corr_reject,
            batch_rows=args.batch_rows, scan_batches=args.scan_batches,
            prepare_workers=args.prepare_workers,
            prep_workers=args.prep_workers,
            pass_b_kernel=args.pass_b_kernel,
            profile_passes=args.profile_passes,
            seed_edges=args.seed_edges,
            quantile_sketch_size=args.sketch_size,
            hll_precision=args.hll_precision,
            exact_passes=not args.single_pass,
            spearman=args.spearman, unique_spill_dir=args.unique_spill_dir,
            exact_distinct=args.exact_distinct, parity=args.parity,
            **({"unique_track_rows": args.unique_track_rows}
               if args.unique_track_rows is not None else {}),
            unique_track_total_rows=args.unique_track_total_rows,
            unique_partitions=args.unique_partitions,
            unique_spill_workers=args.unique_spill_workers,
            checkpoint_path=args.checkpoint,
            checkpoint_every_batches=args.checkpoint_every,
            checkpoint_keep=args.checkpoint_keep,
            ingest_retries=args.ingest_retries,
            retry_backoff_s=args.retry_backoff,
            elastic=args.elastic,
            fleet_dir=args.fleet_dir,
            fleet_host_id=args.fleet_host_id,
            liveness_timeout_s=args.liveness_timeout,
            max_quarantined=args.max_quarantined,
            quarantine_log=args.quarantine_log,
            drain_timeout_s=args.drain_timeout,
            barrier_timeout_s=args.barrier_timeout,
            metrics_enabled=True if (args.metrics_json or args.progress)
            else None,
            metrics_path=args.metrics_json,
            metrics_interval=args.metrics_interval,
            metrics_max_bytes=args.metrics_max_bytes,
            artifact_path=args.artifact,
            warehouse_dir=args.warehouse_dir,
            warehouse_format=args.warehouse_format,
            aot_cache_dir=args.aot_cache_dir,
            aot_cache=args.aot_cache,
            compile_cache_dir=cache_dir)
    except ValueError as exc:
        # config validation (duplicate --columns, bad thresholds, ...)
        # speaks the CLI's error convention, not a traceback
        print(f"tpuprof: error: {exc}", file=sys.stderr)
        return 2

    # observability: configure up front so the ticker (and any code
    # before collect) records; the backend's configure is then a no-op
    ticker = None
    if config.metrics_enabled or args.metrics_json or args.progress:
        from tpuprof import obs
        obs.configure_from_config(config)
        interval = args.metrics_interval \
            or (5.0 if args.progress else 0.0)
        if interval > 0:
            from tpuprof.obs.progress import Ticker
            ticker = Ticker(interval, progress=args.progress,
                            snapshots=bool(args.metrics_json)).start()

    t0 = time.perf_counter()
    with trace_to(args.trace):
        with phase_timer("profile"):
            try:
                report = ProfileReport(args.source, config=config)
            except InputError as exc:
                # user-input errors ONLY (unknown --columns names,
                # checkpoint/source mismatch) speak the CLI convention;
                # internal ValueErrors keep their traceback so real
                # bugs stay diagnosable.  No postmortem: nothing
                # crashed, the request itself was malformed.
                print(f"tpuprof: error: {exc}", file=sys.stderr)
                return 2
            except (CorruptCheckpointError, CorruptManifestError,
                    PoisonBatchError, WatchdogTimeout,
                    HostDeathError) as exc:
                # the degradation ladder ran out (ROBUSTNESS.md): one
                # line + a distinct exit code per failure shape
                # (errors.exit_code), and the flight recorder dumps a
                # postmortem bundle whose last ring entries name the
                # failing site
                print(f"tpuprof: error: {exc}", file=sys.stderr)
                dump = blackbox.dump_postmortem(error=exc)
                if dump:
                    print(f"tpuprof: postmortem: {dump}",
                          file=sys.stderr)
                return exit_code(exc)
            except Exception as exc:
                # unexpected failure: keep the traceback (it is the
                # diagnosis), but leave the flight-recorder bundle too —
                # the ring holds the batch/dispatch context a traceback
                # cannot show
                blackbox.dump_postmortem(error=exc)
                raise
        # every host computes the complete merged stats (the cross-host
        # merges are allgathers), but only host 0 renders + writes —
        # N processes racing one output path helps nobody
        write_output = True
        if multi_host:
            import jax
            write_output = jax.process_index() == 0
        if write_output:
            with phase_timer("render"):
                report.to_file(args.output)
            if config.artifact_path:
                # one-shot profiles persist a stats-only artifact
                # (diffable by `tpuprof diff`; fold-able artifacts come
                # from the StreamingProfiler API — ARTIFACTS.md)
                from tpuprof.artifact import write_artifact
                write_artifact(config.artifact_path,
                               stats=report.description, config=config,
                               source=str(args.source))
                from tpuprof.config import (resolve_warehouse_dir,
                                            resolve_warehouse_format)
                whd = resolve_warehouse_dir(config.warehouse_dir)
                if whd and resolve_warehouse_format(
                        config.warehouse_format) == "parquet":
                    # the columnar twin appends a generation derived
                    # from the artifact JUST sealed, so the Parquet
                    # rows and the JSON document carry the same bits
                    # (and the file metadata the document's CRC).  The
                    # user asked for the warehouse explicitly here, so
                    # its typed failures (exit 10 without pyarrow) are
                    # the command's failures.
                    from tpuprof.artifact import read_artifact
                    from tpuprof.errors import TYPED_ERRORS, exit_code
                    from tpuprof.warehouse import append_artifact
                    try:
                        append_artifact(whd,
                                        read_artifact(
                                            config.artifact_path),
                                        source=str(args.source))
                    except TYPED_ERRORS as exc:
                        print(f"tpuprof: error: {exc}",
                              file=sys.stderr)
                        return exit_code(exc)
    elapsed = time.perf_counter() - t0

    if ticker is not None:
        ticker.stop()
    if args.metrics_json:
        # final snapshot includes the render span the collect-time one
        # could not see; the .prom twin is the same registry in the
        # text exposition format (OBSERVABILITY.md "reading the dump")
        from tpuprof import obs
        obs.finalize(reason="cli")
        with open(args.metrics_json + ".prom", "w") as fh:
            fh.write(obs.registry().render_text())

    table = report.description["table"]
    rate = table["n"] / elapsed if elapsed > 0 else float("nan")
    wrote = args.output if write_output else "(report written by host 0)"
    print(f"tpuprof: {table['n']:,} rows x {table['nvar']} cols -> "
          f"{wrote} in {elapsed:.2f}s ({rate:,.0f} rows/s)",
          file=sys.stderr)
    if args.stats_json and write_output:
        with open(args.stats_json, "w") as fh:
            json.dump(report.to_json_dict(), fh, indent=2)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "profile":
        return cmd_profile(args)
    if args.command == "serve":
        return cmd_serve(args)
    if args.command == "watch":
        return cmd_watch(args)
    if args.command == "submit":
        return cmd_submit(args)
    if args.command == "diff":
        return cmd_diff(args)
    if args.command == "history":
        return cmd_history(args)
    if args.command == "backtest":
        return cmd_backtest(args)
    if args.command == "lint":
        return cmd_lint(args)
    raise AssertionError(args.command)


if __name__ == "__main__":
    sys.exit(main())
