"""The stats-dict contract — the single most important compatibility seam.

The reference's renderer consumes a plain nested dict produced by
``base.describe()`` (SURVEY.md §1: "Interface between L2 and L3"):

    {'table': {...}, 'variables': <per-column stats DataFrame>,
     'freq': <value counts per CAT column>, 'correlations': {...},
     'messages': [...], 'sample': <head rows>}

Everything in tpuprof — CPU oracle, TPU backend, streaming — produces this
exact shape, so the report layer and ``get_rejected_variables`` never care
which engine ran.

Column kind taxonomy and dispatch order follow the reference
(spark_df_profiling/base.py describe() [U], SURVEY.md §2.1):

    distinct <= 1              -> CONST
    boolean dtype              -> BOOL
    numeric dtype              -> NUM
    datetime dtype             -> DATE
    distinct == non-null count -> UNIQUE   (non-numeric only)
    otherwise                  -> CAT

plus CORR assigned later to NUM columns whose |Pearson| vs an earlier kept
column exceeds ``corr_reject``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np
import pandas as pd

# ---------------------------------------------------------------------------
# Column kinds (reference row types; each maps to a renderer template)
# ---------------------------------------------------------------------------

NUM = "NUM"
CAT = "CAT"
DATE = "DATE"
BOOL = "BOOL"
CONST = "CONST"
UNIQUE = "UNIQUE"
CORR = "CORR"

ALL_KINDS = (NUM, CAT, DATE, BOOL, CONST, UNIQUE, CORR)

# Message (warning/alert) ids — reference: messages derivation, SURVEY §2.1.
MSG_HIGH_CARDINALITY = "HIGH_CARDINALITY"
MSG_HIGH_MISSING = "HIGH_MISSING"
MSG_HIGH_ZEROS = "HIGH_ZEROS"
MSG_SKEWED = "SKEWED"
MSG_CONST = "CONST"
MSG_UNIQUE = "UNIQUE"
MSG_CORR = "CORR"
# a CAT column's distinct count fell back to the HLL estimate (its
# Misra-Gries summary and exact duplicate tracker both overflowed)
MSG_APPROX_DISTINCT = "APPROX_DISTINCT"


@dataclasses.dataclass
class Message:
    """One alert row in the report's messages block."""

    kind: str            # one of the MSG_* ids
    column: str
    value: Any = None    # the offending value (p_missing, correlation, ...)

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "column": self.column, "value": self.value}


# ---------------------------------------------------------------------------
# Per-kind stat field lists (the §2.1 feature checklist).  The renderer and
# the contract test both key off these, so a backend that forgets a field
# fails loudly.
# ---------------------------------------------------------------------------

COMMON_FIELDS = [
    "type", "count", "n_missing", "p_missing", "distinct_count", "p_unique",
    "is_unique", "distinct_approx", "memorysize",
]

NUM_FIELDS = COMMON_FIELDS + [
    "mean", "std", "variance", "min", "max", "range", "sum",
    "p5", "p25", "p50", "p75", "p95", "iqr", "cv", "mad",
    "skewness", "kurtosis", "n_zeros", "p_zeros", "n_infinite", "p_infinite",
    "mode", "mode_approx", "histogram", "mini_histogram",
]

CAT_FIELDS = COMMON_FIELDS + ["mode", "top", "freq"]
BOOL_FIELDS = COMMON_FIELDS + ["mean", "mode", "mode_approx", "top", "freq"]
DATE_FIELDS = COMMON_FIELDS + ["min", "max", "range"]
CONST_FIELDS = COMMON_FIELDS + ["mode"]
UNIQUE_FIELDS = COMMON_FIELDS + ["first_rows"]
CORR_FIELDS = COMMON_FIELDS + ["correlation_var", "correlation"]

FIELDS_BY_KIND = {
    NUM: NUM_FIELDS,
    CAT: CAT_FIELDS,
    BOOL: BOOL_FIELDS,
    DATE: DATE_FIELDS,
    CONST: CONST_FIELDS,
    UNIQUE: UNIQUE_FIELDS,
    CORR: CORR_FIELDS,
}

# Quantile probe -> variables-frame field name.
QUANTILE_FIELDS = {0.05: "p5", 0.25: "p25", 0.5: "p50", 0.75: "p75", 0.95: "p95"}


def classify_dtype(series: pd.Series) -> str:
    """Coarse dtype family before distinct-count refinement."""
    if pd.api.types.is_bool_dtype(series):
        return BOOL
    if pd.api.types.is_numeric_dtype(series):
        return NUM
    if pd.api.types.is_datetime64_any_dtype(series):
        return DATE
    return CAT


def classify(base_kind: str, distinct_count: int, count: int) -> str:
    """Reference dispatch order (SURVEY §2.1): CONST first, UNIQUE only for
    non-numeric, else the dtype family."""
    if distinct_count <= 1:
        return CONST
    if base_kind in (NUM, BOOL, DATE):
        return base_kind
    if count > 0 and distinct_count == count:
        return UNIQUE
    return CAT


def make_table_stats(
    n: int,
    variables: Dict[str, Dict[str, Any]],
    memorysize: float = float("nan"),
) -> Dict[str, Any]:
    """Table-level block: row/var counts, total missing %, var-type census
    (reference: base.describe() table assembly [U])."""
    nvar = len(variables)
    cells = n * nvar
    total_missing = (
        sum(v.get("n_missing", 0) for v in variables.values()) / cells
        if cells else 0.0
    )
    census = {k: 0 for k in ALL_KINDS}
    for v in variables.values():
        census[v["type"]] = census.get(v["type"], 0) + 1
    table = {
        "n": n,
        "nvar": nvar,
        "total_missing": total_missing,
        "memorysize": memorysize,
        "n_duplicates": None,  # not computed by the reference's Spark fork
    }
    table.update(census)
    return table


def derive_messages(
    variables: Dict[str, Dict[str, Any]],
    config,
) -> List[Message]:
    """Warnings block (reference: messages derivation, SURVEY §2.1):
    high cardinality, high missing, high zeros, skewness, constant, unique,
    correlation-rejected."""
    msgs: List[Message] = []
    for name, v in variables.items():
        kind = v["type"]
        if kind == CONST:
            msgs.append(Message(MSG_CONST, name, v.get("mode")))
        elif kind == UNIQUE:
            msgs.append(Message(MSG_UNIQUE, name))
        elif kind == CORR:
            msgs.append(Message(MSG_CORR, name,
                                (v.get("correlation_var"), v.get("correlation"))))
        elif kind == CAT:
            # distinct_count None = nested="opaque" declared it unknown
            # (a policy, not an estimator overflow) — neither message
            distinct = v.get("distinct_count")
            if distinct is not None \
                    and distinct > config.high_cardinality_threshold:
                msgs.append(Message(MSG_HIGH_CARDINALITY, name, distinct))
            if v.get("distinct_approx") and distinct is not None:
                # only CAT warns: approximate distincts can change the
                # UNIQUE/CAT call there, and only past both exact tiers
                msgs.append(Message(MSG_APPROX_DISTINCT, name, distinct))
        elif kind == NUM:
            skew = v.get("skewness")
            if skew is not None and np.isfinite(skew) and \
                    abs(skew) > config.skewness_threshold:
                msgs.append(Message(MSG_SKEWED, name, skew))
            if v.get("p_zeros", 0.0) > config.zeros_threshold:
                msgs.append(Message(MSG_HIGH_ZEROS, name, v["p_zeros"]))
        if v.get("p_missing", 0.0) > config.missing_threshold:
            msgs.append(Message(MSG_HIGH_MISSING, name, v["p_missing"]))
    return msgs


def variables_frame(variables: Dict[str, Dict[str, Any]]) -> pd.DataFrame:
    """The reference keeps per-column stats as a pandas DataFrame indexed by
    column name (base.describe() [U]); provide the same view."""
    if not variables:
        return pd.DataFrame()
    frame = pd.DataFrame.from_dict(variables, orient="index")
    frame.index.name = "variable"
    return frame


class VariablesView(Dict[str, Dict[str, Any]]):
    """``description['variables']`` serving BOTH access idioms.

    The reference kept per-column stats as a pandas DataFrame indexed by
    column name (SURVEY §1 L2→L3 seam), so migrating code does
    ``.loc[col, 'mean']`` / ``.index`` / ``.T``; tpuprof's native
    contract is a dict of per-column dicts (``variables['col']['mean']``).
    This dict subclass adds the DataFrame accessors, built lazily from
    the dict and cached (the stats dict is frozen once assembled)."""

    def _frame(self) -> pd.DataFrame:
        cached = getattr(self, "_cached_frame", None)
        if cached is None:
            cached = variables_frame(self)
            self._cached_frame = cached
        return cached

    @property
    def loc(self):
        return self._frame().loc

    @property
    def iloc(self):
        return self._frame().iloc

    @property
    def at(self):
        return self._frame().at

    @property
    def index(self):
        return self._frame().index

    @property
    def columns(self):
        return self._frame().columns

    @property
    def T(self):
        return self._frame().T

    def iterrows(self):
        return self._frame().iterrows()

    def to_frame(self) -> pd.DataFrame:
        """Explicit DataFrame copy of the per-column stats."""
        return self._frame().copy()


def validate_stats(stats: Dict[str, Any]) -> List[str]:
    """Contract check: return a list of problems (empty == valid).  Used by
    the dict-contract snapshot test (SURVEY §4.4) and debug asserts."""
    problems: List[str] = []
    for key in ("table", "variables", "freq", "correlations", "messages",
                "sample"):
        if key not in stats:
            problems.append(f"missing top-level key {key!r}")
    if problems:
        return problems
    for name, v in stats["variables"].items():
        kind = v.get("type")
        if kind not in FIELDS_BY_KIND:
            problems.append(f"{name}: unknown type {kind!r}")
            continue
        for field in FIELDS_BY_KIND[kind]:
            if field not in v:
                problems.append(f"{name} ({kind}): missing field {field!r}")
    for msg in stats["messages"]:
        if not isinstance(msg, Message):
            problems.append(f"message {msg!r} is not a Message")
    return problems


def reject_by_correlation(corr, ordered_cols, config) -> Dict[str, tuple]:
    """The reference's rejection rule (SURVEY §2.1), backend-agnostic:
    scanning numeric columns in order, reject a column whose |ρ| vs an
    *earlier kept* column exceeds ``corr_reject``; returns
    {rejected_col: (earlier_col, rho)}.  ``corr`` is a pandas DataFrame."""
    overrides = set(config.correlation_overrides or ())
    kept = []
    rejected: Dict[str, tuple] = {}
    for col in ordered_cols:
        if col in overrides:
            kept.append(col)
            continue
        hit = None
        for earlier in kept:
            rho = corr.loc[col, earlier] if len(corr) else np.nan
            if np.isfinite(rho) and abs(rho) > config.corr_reject:
                hit = (earlier, float(rho))
                break
        if hit:
            rejected[col] = hit
        else:
            kept.append(col)
    return rejected


def rejected_variables(stats: Dict[str, Any],
                       threshold: Optional[float] = None) -> List[str]:
    """Reference: ProfileReport.get_rejected_variables(corr_threshold) scans
    the cached variables dict for CORR rows above the threshold (SURVEY
    §3.4) — no recomputation."""
    out = []
    for name, v in stats["variables"].items():
        if v["type"] == CORR:
            if threshold is None or abs(v.get("correlation") or 0) > threshold:
                out.append(name)
    return out
