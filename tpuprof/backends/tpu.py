"""TPUStatsBackend — the fused-scan engine (the north star's seam).

Where the reference issues O(columns) blocking Spark jobs per profile —
``agg``/``approxQuantile``/``countDistinct``/``groupBy().count()`` per
column plus ``df.corr`` per pair (SURVEY.md §3.1 hot loop) — this backend
streams Arrow record batches ONCE through a single jit-compiled sharded
step updating every statistic for every column (SURVEY §3.5), then runs
one collective merge.  With ``exact_passes`` (the default for rescannable
sources) a second scan computes exact histograms (needing pass-A min/max),
exact MAD (needing pass-A means) and exact top-k recounts — still O(2)
scans total versus the reference's O(columns).

Division of labor (SURVEY §7.2 "Strings on TPU"):
* device — moments, min/max, zeros/inf/missing, pairwise Pearson Gram,
  quantile sample sketch, HLL registers, histograms, MAD;
* host  — string dictionary decode + hashing (Arrow/pandas vectorized),
  Misra-Gries frequent values, date min/max (int64 ns exactness),
  first-rows capture, final assembly of the stats dict.

Accuracy contract vs the CPU oracle (tests/test_tpu_backend.py):
exact — count, missing, zeros, inf, min/max, histograms, top-k counts
(with exact_passes), bool stats, date min/max; float32-tolerance — mean,
std, variance, skewness, kurtosis, sum, MAD, Pearson; sketch-bounded —
quantiles (~1/sqrt(K) rank error; exact when n <= K), distinct counts
(~1.04/sqrt(2^p), exact-in-practice small range via linear counting).
Numeric values are profiled in float32 (TPU-native width): integers
above 2^24 lose ULPs in moments — distinct counts are unaffected (hashes
are computed on the original 64-bit values host-side).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from tpuprof import schema
from tpuprof.config import ProfilerConfig
from tpuprof.ingest.arrow import (ArrowIngest, ColumnPlan, HostBatch,
                                  prefetch_prepared, prepare_batch)
from tpuprof.ingest.sample import RowSampler
from tpuprof.kernels import corr as kcorr
from tpuprof.kernels import hll as khll
from tpuprof.kernels import moments as kmoments
from tpuprof.kernels import histogram as khistogram
from tpuprof.kernels import unique as kunique
from tpuprof.kernels.topk import MisraGries
from tpuprof.kernels.unique import UniqueTracker
from tpuprof import obs
from tpuprof.obs.spans import span
from tpuprof.runtime.mesh import MeshRunner
from tpuprof.utils.trace import log_event, phase_timer  # noqa: F401 — phase_timer kept for any external caller; new code uses span


def estimate_shift(hb: HostBatch) -> np.ndarray:
    """Per-column centering values from a prefix of the first batch (the
    fused kernel's shift input — see kernels/fused.py).  Exactness does
    not matter, only scale; all-missing columns center at 0."""
    prefix = hb.x[: min(hb.nrows, 4096)]
    if prefix.shape[0] == 0:
        return np.zeros(prefix.shape[1], dtype=np.float32)
    finite = np.isfinite(prefix)
    cnt = finite.sum(axis=0)
    sums = np.where(finite, prefix, 0.0).sum(axis=0)
    return (sums / np.maximum(cnt, 1)).astype(np.float32)


class HostAgg:
    """Host-side accumulators folded during pass A."""

    def __init__(self, plan: ColumnPlan, config: ProfilerConfig):
        self.config = config
        self.n_rows = 0
        self.col_nbytes: Dict[str, int] = {}        # summed buffer bytes
        self.col_dict_nbytes: Dict[str, int] = {}   # shared dicts: max
        self.mg: Dict[str, MisraGries] = {
            s.name: MisraGries(config.topk_capacity)
            for s in plan.by_role("cat")}
        # exact "duplicate seen" flags: restores the reference's exact
        # UNIQUE classification for columns whose MG summary overflows
        # exact_distinct extends the tracker to EVERY column: num/date
        # lanes feed their full 64-bit hash streams (HostBatch.num_hashes)
        # so the reference's countDistinct exactness holds with no HLL
        # estimate anywhere, not just for string/categorical columns
        # opaque nested columns have no hash stream — nothing to track
        from tpuprof.config import (resolve_spill_workers,
                                    resolve_unique_budget,
                                    resolve_unique_partitions)
        self.unique = UniqueTracker(
            (s.name for s in (plan.specs if config.exact_distinct
                              else plan.by_role("cat"))
             if not s.opaque),
            config.unique_track_rows,
            # int / "auto" (RAM-derived) / None (env, else the
            # historical 1<<25) — resolved once, here, so the tracker
            # and every budget check agree on one number
            resolve_unique_budget(
                getattr(config, "unique_track_total_rows", None)),
            spill_dir=config.unique_spill_dir,
            count_exact=config.exact_distinct,
            own_spill_dir=getattr(config, "spill_dir_auto", False),
            partitions=resolve_unique_partitions(
                getattr(config, "unique_partitions", None)),
            spill_workers=resolve_spill_workers(
                getattr(config, "unique_spill_workers", None)))
        # num/date columns whose exact counting expects full hashes on
        # every batch (coverage gap => honest deactivation)
        self._numdate_tracked = [s.name for s in plan.specs
                                 if s.role != "cat"] \
            if config.exact_distinct else []
        from tpuprof import native
        self._numkind = "native" if native.available() else "pandas"
        self.cat_null: Dict[str, int] = {s.name: 0 for s in plan.by_role("cat")}
        self.date_min: Dict[str, int] = {}
        self.date_max: Dict[str, int] = {}
        self.date_null: Dict[str, int] = {s.name: 0 for s in plan.by_role("date")}
        self.first_values: Dict[str, list] = {}

    def update(self, hb: HostBatch) -> None:
        first = self.n_rows == 0
        self.n_rows += hb.nrows
        for name, nb in (hb.col_nbytes or {}).items():
            self.col_nbytes[name] = self.col_nbytes.get(name, 0) + nb
        for name, nb in (hb.col_dict_nbytes or {}).items():
            self.col_dict_nbytes[name] = max(
                self.col_dict_nbytes.get(name, 0), nb)
        for name, (codes, dvals) in hb.cat_codes.items():
            codes = codes[: hb.nrows]
            valid = codes >= 0
            self.cat_null[name] += int((~valid).sum())
            if valid.any() and len(dvals):
                cnt = np.bincount(codes[valid], minlength=len(dvals))
                nz = np.nonzero(cnt)[0]
                dh = (hb.cat_hashes or {}).get(name)
                self.mg[name].update_batch(
                    dvals[nz], cnt[nz],
                    hashes=dh[nz] if dh is not None else None)
                if self.unique.active(name):
                    if dh is None:
                        # batch prepared without hashes: coverage broken,
                        # an exact "no duplicate" claim is no longer safe
                        self.unique.deactivate(name)
                    else:
                        kind = (hb.cat_hash_kind or {}).get(name, "")
                        self.unique.update(name, dh[codes[valid]],
                                           hash_kind=kind)
            if first:
                self.first_values[name] = [
                    dvals[c] if c >= 0 else None for c in codes[:5]]
        for name, payload in (hb.cat_hashed or {}).items():
            # plain-string fast path: per-batch hash aggregation with NO
            # dictionary (ingest/arrow.py) — values materialize only for
            # Misra-Gries survivors and the first report rows
            uniq, cnts, first_row, row_hashes, valid, arr = payload
            self.cat_null[name] += 0 if valid is None \
                else int(hb.nrows - valid.sum())
            if uniq.size:
                def resolver(src, arr=arr, first_row=first_row):
                    import pyarrow as pa
                    taken = arr.take(pa.array(first_row[src]))
                    return np.asarray(taken.to_pandas(), dtype=object)
                self.mg[name].update_hashed(uniq, cnts, resolver)
                if self.unique.active(name):
                    # same xxh64-of-bytes values as the dictionary path's
                    # native hashes, so streams may mix representations
                    self.unique.update(
                        name,
                        row_hashes if valid is None else row_hashes[valid],
                        hash_kind="native")
            if first:
                self.first_values[name] = arr[:5].to_pylist()
        for name, nulls in (hb.opaque_nulls or {}).items():
            # opaque nested columns (config.nested): the null count is
            # their only per-batch statistic
            self.cat_null[name] += int(nulls)
        for name, (ints, valid) in hb.date_ints.items():
            ints, valid = ints[: hb.nrows], valid[: hb.nrows]
            self.date_null[name] += int((~valid).sum())
            if valid.any():
                lo, hi = int(ints[valid].min()), int(ints[valid].max())
                self.date_min[name] = min(self.date_min.get(name, lo), lo)
                self.date_max[name] = max(self.date_max.get(name, hi), hi)
        # getattr: pre-exact-distinct artifacts unpickle a HostAgg
        # without this attribute, and BOTH resume paths let them reach
        # update(): StreamingProfiler.restore()'s meta never versioned
        # it, and _CollectCheckpoint.load() deliberately defaults the
        # absent exact_distinct meta key to False (old artifacts must
        # keep resuming) — the guard is load-bearing for both
        nh = hb.num_hashes or {}
        for name in getattr(self, "_numdate_tracked", ()):
            if not self.unique.active(name):
                continue
            pair = nh.get(name)
            if pair is None:
                # batch prepared without full hashes: coverage broken,
                # the exact count is no longer sound
                self.unique.deactivate(name)
                continue
            h, valid = pair
            if valid is None:
                # prepare_batch pre-masked the stream on the prep pool
                # (ingest/arrow.py): the array is owned and valid-only —
                # the fold thread hands it to the tracker with no mask
                # pass and no copy (the all-valid wide-numeric case);
                # never re-slice: rows below nrows mean nulls were
                # already dropped
                hv = h
            else:
                h, valid = h[: hb.nrows], valid[: hb.nrows]
                hv = h if valid.all() else h[valid]
            self.unique.update(name, hv, hash_kind=self._numkind)

    def memorysize(self, name: str) -> float:
        """Arrow buffer bytes for one column (NaN if never observed)."""
        if name not in self.col_nbytes:
            return float("nan")
        return float(self.col_nbytes[name]
                     + self.col_dict_nbytes.get(name, 0))


class Recounter:
    """Pass-B exact recount of the Misra-Gries candidates — restores the
    reference's exact ``groupBy().count()`` semantics for the reported
    top-k rows (SURVEY §7.2 "Top-k exactness")."""

    def __init__(self, hostagg: HostAgg):
        self.indexes: Dict[str, pd.Index] = {}
        self.counts: Dict[str, np.ndarray] = {}
        # dictionary->candidate indexers memoized on the dvals OBJECT:
        # dictionary-page batches share one dvals array per row group
        # (ingest's _DICT_CACHE), so the O(cardinality) get_indexer probe
        # runs once per dictionary, not once per batch.  Holding the
        # array reference makes the identity check safe.
        self._dv_cache: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for name, mg in hostagg.mg.items():
            cands = pd.Index(list(mg.candidates()))
            self.indexes[name] = cands
            self.counts[name] = np.zeros(len(cands), dtype=np.int64)

    def update(self, hb: HostBatch) -> None:
        for name, (codes, dvals) in hb.cat_codes.items():
            codes = codes[: hb.nrows]
            valid = codes >= 0
            if not valid.any() or not len(dvals):
                continue
            cnt = np.bincount(codes[valid], minlength=len(dvals))
            ent = self._dv_cache.get(name)
            if ent is None or ent[0] is not dvals:
                ent = (dvals, self.indexes[name].get_indexer(dvals))
                self._dv_cache[name] = ent
            cand_idx = ent[1]
            hit = cand_idx >= 0
            np.add.at(self.counts[name], cand_idx[hit], cnt[hit])

    def value_counts(self, name: str) -> pd.Series:
        return pd.Series(self.counts[name], index=self.indexes[name]
                         ).sort_values(ascending=False)


class _CollectCheckpoint:
    """Batch-granular resumability for the pass-A scan (SURVEY §5):
    persist (device state, host sketches, batch cursor) every N batches;
    resume = load + skip the already-folded prefix of the deterministic
    batch stream.  Resume skips the prefix without re-decoding it:
    file-backed sources skip whole fragments' I/O via (fragment, batch)
    positions, and in-memory tables skip zero-copy ``to_batches`` slices
    (positions on the single pseudo-fragment).  Only artifacts saved
    without a position (older layouts) fall back to decode-and-skip.

    Multi-host: each host persists its OWN stripe's scan to a per-host
    artifact (``<path>.h<i>of<N>``) — host cursors are independent by
    design (stripes have different batch counts and no collective runs
    during pass A), so no coordinated global cursor exists or is needed;
    the meta pins (process_id, process_count) so an artifact can never
    resume a different stripe assignment, and collect runs a resume
    barrier (runtime/distributed.allgather) so every host agrees on who
    restored before any scanning starts."""

    # batch_enum versions the batch-boundary ENUMERATION (how a source
    # splits into cursor-counted batches): "window-v2" = fixed-size
    # combined windows for in-memory tables.  An artifact whose cursors
    # counted a different enumeration must be rejected, not mis-skipped.
    _META_KEYS = ("n_num", "n_hash", "batch_rows", "hll_precision",
                  "native_hash", "source_fp", "quantile_sketch_size",
                  "topk_capacity", "seed", "process_id", "process_count",
                  "batch_enum", "exact_distinct", "nested",
                  "profile_passes")

    def __init__(self, config: ProfilerConfig, plan, runner, pshard,
                 source_fp: str, table_source: bool = False,
                 fused: bool = False):
        from tpuprof.config import resolve_checkpoint_keep
        self.pshard = pshard
        self.table_source = bool(table_source)
        # single-pass artifacts carry the fused histogram state AND the
        # provisional edges it was binned with (runtime/singlepass.py):
        # a resume folding with different edges would mix bin layouts,
        # so the edges ride the blob and profile_passes rides the meta
        # (a fused artifact never resumes a two-pass run or vice versa)
        self.fused = bool(fused)
        self.extras = lambda: (None, None)      # () -> (hist_state, edges)
        path = config.checkpoint_path
        if pshard[1] > 1:
            path = f"{path}.h{pshard[0]}of{pshard[1]}"
        self.path = path
        self.every = max(int(config.checkpoint_every_batches), 1)
        self.keep = resolve_checkpoint_keep(config.checkpoint_keep)
        self.config = config
        self.plan = plan
        self.runner = runner
        self.source_fp = source_fp
        self.last_saved = -1            # cursor of the newest artifact

    def exists(self) -> bool:
        import os
        from tpuprof.runtime import checkpoint as ckpt
        return any(os.path.exists(p)
                   for p in ckpt.candidate_paths(self.path))

    def due(self, cursor: int) -> bool:
        return cursor % self.every == 0

    def _meta(self) -> Dict[str, Any]:
        from tpuprof import native
        return {"n_num": self.plan.n_num, "n_hash": self.plan.n_hash,
                "batch_rows": self.config.batch_rows,
                "hll_precision": self.config.hll_precision,
                "native_hash": native.available(),
                "source_fp": self.source_fp,
                "quantile_sketch_size": self.config.quantile_sketch_size,
                "topk_capacity": self.config.topk_capacity,
                "seed": self.config.seed,
                "process_id": self.pshard[0],
                "process_count": self.pshard[1],
                # scoped to table sources: only THEIR enumeration changed
                # in v2 (fixed combined windows); file-backed fragment
                # cursors are unchanged and stamp None, so pre-existing
                # parquet artifacts keep resuming
                "batch_enum": "window-v2" if self.table_source else None,
                # the tracker's column set and hash coverage differ by
                # mode — resuming across a flip would silently drop or
                # hollow the exact counts
                "exact_distinct": self.config.exact_distinct,
                # the batch stream's CONTENT differs per policy (opaque
                # columns carry no value stream) — no cross-policy resume
                "nested": self.config.nested,
                # fused artifacts carry a histogram state keyed to
                # provisional edges; the pass structure must match
                "profile_passes": "fused" if self.fused else "two_pass"}

    def save(self, state, sampler, hostagg, host_hll, cursor,
             frag_pos=None, quarantine=None, fleet_done=None) -> None:
        from tpuprof.runtime import checkpoint as ckpt
        # this artifact will reference the tracker's spill runs by path:
        # from now on a crash must leave them on disk for resume (GC
        # cleanup off — the flag pickles into the artifact too).  Before
        # the FIRST save, __del__ may still reap them: nothing
        # references the files yet
        hostagg.unique.persistent = True
        blob = {"sampler": sampler, "hostagg": hostagg,
                "host_hll": host_hll, "frag_pos": frag_pos}
        if quarantine is not None and quarantine.entries:
            # only degraded runs carry the key: clean-run payloads stay
            # byte-identical to the pre-quarantine layout
            blob["quarantine"] = list(quarantine.entries)
        if fleet_done is not None:
            # elastic members persist the completed-fragment claims
            # with the fold state that covers them (runtime/fleet.py):
            # the durable half of the work-stealing manifest, riding
            # the same CRC envelope as everything else here.  Absent
            # for fixed-membership runs — payload bytes unchanged.
            blob["fleet_done"] = sorted(int(k) for k in fleet_done)
        hist_state, edges = self.extras()
        if hist_state is not None:
            # the fused histogram fold rides the same npz archive as
            # the pass-A state; the provisional edges ride the blob so
            # resume folds the remaining stream onto the SAME bins
            state = {"a": state, "hist": hist_state}
            blob["singlepass_edges"] = edges.as_blob()
        ckpt.save(self.path, state, blob, cursor, meta=self._meta(),
                  keep=self.keep)
        # the new artifact no longer references runs demoted since the
        # previous save — only now is their physical deletion safe
        hostagg.unique.reap_retired()
        self.last_saved = cursor
        log_event("collect_checkpoint", cursor=cursor, path=self.path,
                  frag_pos=frag_pos)

    def load(self):
        """(state, sampler, hostagg, host_hll, cursor, frag_pos,
        quarantine_entries) from the newest INTEGRAL artifact in the
        retention chain (a corrupt head falls back to ``path.N`` —
        checkpoint.restore_payload), after refusing any config/source
        divergence from the saved prefix.  ``frag_pos`` is the
        (fragment, batch) position of the last folded batch — resume
        skips whole fragments' I/O when it is present."""
        from tpuprof.runtime import checkpoint as ckpt
        # integrity walk first (CRC/version/length — template-free so a
        # config mismatch below still speaks the meta-key language, not
        # a shape error); the CRC already guarantees the device-state
        # archive decodes
        payload, _, used = ckpt.restore_payload(self.path)
        meta = payload["meta"]
        mine = self._meta()
        # keys added after an artifact was written are absent from its
        # meta; absence means the writer ran the then-only behavior, so
        # compare against that default instead of None (which would
        # hard-fail every pre-existing artifact on upgrade).  batch_enum
        # is deliberately NOT defaulted: for table sources the old
        # enumeration really did differ (window-v2), so absent != "v2"
        # must reject; for parquet sources both sides stamp None anyway.
        absent_defaults = {"process_id": 0, "process_count": 1,
                           "exact_distinct": False, "nested": "stringify",
                           "profile_passes": "two_pass"}
        from tpuprof.errors import InputError
        for key in self._META_KEYS:
            if meta.get(key, absent_defaults.get(key)) != mine[key]:
                raise InputError(
                    f"checkpoint {key}={meta.get(key)!r} does not match "
                    f"this run's {mine[key]!r} — the batch stream or "
                    "sketch shapes would diverge from the saved prefix")
        blob = payload["host_blob"]
        sp_blob = blob.get("singlepass_edges")
        hist_state = None
        edges = None
        if sp_blob is not None:
            from tpuprof.runtime import singlepass as _sp
            combined = ckpt.materialize(
                payload, {"a": self.runner.init_pass_a(),
                          "hist": self.runner.init_pass_b()})
            state, hist_state = combined["a"], combined["hist"]
            edges = _sp.ProvisionalEdges.from_blob(sp_blob)
        else:
            state = ckpt.materialize(payload, self.runner.init_pass_a())
        self.last_saved = payload["cursor"]
        log_event("collect_resume", cursor=payload["cursor"], path=used)
        return (state, blob["sampler"], blob["hostagg"],
                blob["host_hll"], payload["cursor"],
                blob.get("frag_pos"), blob.get("quarantine") or [],
                blob.get("fleet_done"), hist_state, edges)

    def clear(self) -> None:
        from tpuprof.runtime import checkpoint as ckpt
        ckpt.clear(self.path)


# ---------------------------------------------------------------------------
# Elastic fleet plumbing (runtime/fleet.py; ROBUSTNESS.md rung 5)
# ---------------------------------------------------------------------------

def _fleet_stream(member, phase, ingest, resume_frag=None, replay=()):
    """Claim-driven raw-batch stream: first the adopted checkpoint's
    partial fragment (resumed at the saved batch boundary), then the
    adopted claims whose fold state died with the predecessor (replayed
    from scratch), then fresh pulls from the shared manifest until it
    is exhausted.  Fragments are read one at a time so a slow member
    naturally claims less — the whole scheduler is this loop."""
    if resume_frag is not None:
        fi, bi = resume_frag
        yield from ingest.read_fragment(fi, skip_batches=bi + 1)
    for fi in replay:
        yield from ingest.read_fragment(fi)
    while True:
        fi = member.claim_next(phase)
        if fi is None:
            return
        yield from ingest.read_fragment(fi)


def _scan_fragments_pass_a(frags, ingest, plan, pad, config, runner,
                           batch_guard, use_host_hll):
    """Replay a stolen fragment set from scratch into a fresh finalized
    pass-A part (the ``steal_scan`` contract of
    runtime/fleet.FleetMember.finish).  The dead owner's partial folds
    died with it, so a clean re-scan plus the merge laws is exactly
    what makes the survivor's totals equal an uninterrupted run."""
    from tpuprof.runtime import guard as _guard
    hostagg = HostAgg(plan, config)
    sampler = RowSampler(config.quantile_sketch_size, plan.n_num,
                         seed=config.seed)
    host_hll = khll.HostRegisters(plan.n_hash, config.hll_precision) \
        if use_host_hll else None
    state = None
    q_entries = []

    def _stream():
        for fi in frags:
            yield from ingest.read_fragment(fi)

    for hb in prefetch_prepared(ingest, plan, pad, config.hll_precision,
                                workers=config.prepare_workers,
                                prep_workers=config.prep_workers,
                                full_hashes=config.exact_distinct,
                                batch_guard=batch_guard,
                                raw_stream=_stream()):
        if isinstance(hb, _guard.PoisonBatch):
            # the skip is recorded on the part (it rides to every
            # survivor's report); the thief's own budget already
            # admitted comparable skips on its primary scan
            q_entries.append({"site": hb.site + "_stolen",
                              "cursor": None, "rows": hb.rows,
                              "frag_pos": list(hb.frag_pos)
                              if hb.frag_pos else None,
                              "error": hb.error})
            continue
        if state is None:
            state = runner.init_pass_a(estimate_shift(hb))
        db = runner.put_batch(hb, with_hll=host_hll is None)
        state = runner.step_a(state, db)
        sampler.update(hb.x, hb.nrows)
        if host_hll is not None:
            host_hll.update(hb.hll, hb.nrows)
        hostagg.update(hb)
    if state is None:
        state = runner.init_pass_a()
    hostagg.unique.persistent = True     # the part references the runs
    return {"kind": "pass_a", "res_a": runner.finalize_a(state),
            "hostagg": hostagg, "sampler": sampler,
            "host_hll": host_hll, "quarantine": q_entries,
            "rows": int(hostagg.n_rows)}


def _part_regs(part):
    """A part's effective HLL registers: host registers where the
    member folded them host-side, its device plane otherwise — the two
    formats are bit-identical (kernels/hll.HostRegisters)."""
    hh = part.get("host_hll")
    return hh.regs if hh is not None else part["res_a"]["hll"]


def _elastic_merge_a(fleet_member, res_a, hostagg, sampler, host_hll,
                     quarantine, steal_scan, timeout_s):
    """Contribute this member's finalized pass-A part (fenced: a
    fragment stolen by a peer to whom we merely LOOKED dead taints the
    monolithic fold, so runtime/fleet re-scans the surviving fragments
    via ``steal_scan`` instead of double-counting), hold the elastic
    resume barrier (stealing dead members' fragments the same way),
    and fold every contribution with the same merge laws the
    fixed-membership collectives apply
    (runtime/distributed.merge_*_parts).  Returns
    ``(res_a, hostagg, sampler, hll_regs, q_entries, q_mark)`` — the
    merged whole-fleet accumulators, the max-folded effective HLL
    registers, the deterministic concatenation of every part's
    quarantine manifest, and the index into this member's local
    manifest where post-contribution (pass-B) entries start."""
    from tpuprof.runtime.distributed import (merge_host_agg_parts,
                                             merge_pass_a_parts,
                                             merge_sampler_parts)
    hostagg.unique.persistent = True     # the part references the runs
    q_mark = len(quarantine.entries)
    mine = {"kind": "pass_a", "res_a": res_a, "hostagg": hostagg,
            "sampler": sampler, "host_hll": host_hll,
            "quarantine": list(quarantine.entries),
            "rows": int(hostagg.n_rows)}
    parts = fleet_member.finish("a", mine,
                                sorted(fleet_member.claimed("a")),
                                steal_scan, timeout_s=timeout_s)
    regs = _part_regs(parts[0]).copy()
    for part in parts[1:]:
        regs = np.maximum(regs, _part_regs(part))
    q_entries = [e for p in parts for e in (p.get("quarantine") or [])]
    res_a = merge_pass_a_parts([p["res_a"] for p in parts])
    hostagg = merge_host_agg_parts([p["hostagg"] for p in parts])
    sampler = merge_sampler_parts([p["sampler"] for p in parts])
    log_event("fleet_merge_a", parts=len(parts),
              rows=int(hostagg.n_rows))
    return res_a, hostagg, sampler, regs, q_entries, q_mark


def _elastic_merge_b(fleet_member, my_part, steal_scan, timeout_s):
    """The pass-B twin: contribute, barrier (phase ``b`` claims), fold.
    Returns ``(res_b, counts, rho_spear)``; ``res_b``/``rho_spear``
    are None for recount-only parts."""
    from tpuprof.runtime.distributed import (merge_corr_parts,
                                             merge_pass_b_parts,
                                             merge_recount_parts)
    parts = fleet_member.finish("b", my_part,
                                sorted(fleet_member.claimed("b")),
                                steal_scan, timeout_s=timeout_s)
    res_bs = [p["res_b"] for p in parts if p.get("res_b") is not None]
    res_b = merge_pass_b_parts(res_bs) if res_bs else None
    counts = merge_recount_parts([p["counts"] for p in parts])
    spears = [p["spear"] for p in parts if p.get("spear") is not None]
    rho_spear = kcorr.finalize(merge_corr_parts(spears)) \
        if spears else None
    log_event("fleet_merge_b", parts=len(parts))
    return res_b, counts, rho_spear


_UNSET = object()
_last_cache_dir = [_UNSET]      # last dir THIS function enabled


def _reset_cache_singleton() -> None:
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as cc)
        cc.reset_cache()
    except Exception:
        pass


def disable_compile_cache() -> None:
    """Explicitly stop persistent-cache writes for this process.  Both
    steps matter: the config stops re-initialization, and the reset
    drops the already-pinned singleton (which otherwise KEEPS writing to
    its original directory regardless of the config — observed)."""
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass
    _reset_cache_singleton()
    _last_cache_dir[0] = None


def _enable_compile_cache(cache_dir: str) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir`` (all
    thresholds zeroed so the profile's small programs qualify).  Safe to
    call repeatedly; older jaxlibs without the knobs are a no-op —
    compiles then simply happen per process, which is correct, just
    slower."""
    import os

    import jax
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        # unwritable cache location (read-only HOME, locked-down service
        # account): degrade to uncached compiles, never fail the profile
        from tpuprof.utils.trace import logger
        logger.warning("compile cache dir %r is not writable; compiling "
                       "without a persistent cache", cache_dir)
        return
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    # each knob independently: a jax that knows the cache dir but not a
    # threshold should still get the thresholds it does support (one
    # shared try would silently leave defaults that filter out the
    # profile's sub-second compiles)
    for knob, value in (("jax_compilation_cache_dir", cache_dir),
                        ("jax_persistent_cache_min_entry_size_bytes", 0),
                        ("jax_persistent_cache_min_compile_time_secs", 0)):
        try:
            jax.config.update(knob, value)
        except Exception:
            pass
    # jax pins its cache singleton to the directory active at first use;
    # switching dirs mid-process needs an explicit reset or the new dir
    # silently never receives entries.  The config value alone cannot
    # detect this (a --no-compile-cache interlude sets it to None while
    # the singleton stays pinned), so track the last dir we enabled too.
    switched = (_last_cache_dir[0] is not _UNSET
                and _last_cache_dir[0] != cache_dir) \
        or prev not in (None, "", cache_dir)
    if switched:
        _reset_cache_singleton()
    _last_cache_dir[0] = cache_dir


class TPUStatsBackend:
    """Profile Arrow-readable sources with the fused sharded scan."""

    name = "tpu"

    def __init__(self, devices=None):
        self._devices = devices

    def collect(self, source: Any, config: ProfilerConfig) -> Dict[str, Any]:
        import jax

        from tpuprof.utils.trace import get_phase_report
        get_phase_report(reset=True)    # drop earlier profiles' phases —
        # this profile's timings are snapshotted onto ITS stats dict at
        # the end of collect, so a report's footer can never describe a
        # different profile's scan
        obs.configure_from_config(config)   # metrics/JSONL sink, if asked
        if config.compile_cache_dir:
            _enable_compile_cache(config.compile_cache_dir)
        from tpuprof.runtime.distributed import (merge_corr_states,
                                                 merge_host_aggs,
                                                 merge_pass_a_states,
                                                 merge_pass_b_states,
                                                 merge_recount_arrays,
                                                 merge_samplers,
                                                 merge_shift_estimates)
        pshard = (jax.process_index(), jax.process_count())
        # crash flight recorder context: a postmortem from this process
        # must name its rank (obs/blackbox.py; fingerprint is stamped by
        # configure_from_config above)
        obs.blackbox.set_context(process_index=pshard[0],
                                 process_count=pshard[1])
        # ---- elastic fleet membership (runtime/fleet.py; ROBUSTNESS.md
        # rung 5): fragments are PULLED from a shared manifest instead
        # of striped, merges fold contribution parts off shared storage
        # instead of collectives, and a dead member's fragments are
        # stolen + replayed by survivors.  Off by default — every
        # fixed-membership byte-path below is untouched then.
        from tpuprof.config import resolve_elastic, resolve_fleet_dir
        from tpuprof.errors import HostDeathError, InputError
        elastic = resolve_elastic(config.elastic)
        fleet_member = None
        if elastic:
            if pshard[1] > 1:
                raise InputError(
                    "elastic fleet mode replaces the jax.distributed "
                    "collective runtime (collectives cannot survive "
                    "membership change) — launch independent processes "
                    "sharing --fleet-dir instead of --coordinator/"
                    "--num-processes")
            if not resolve_fleet_dir(config.fleet_dir):
                raise InputError(
                    "elastic mode needs fleet_dir (--fleet-dir / "
                    "TPUPROF_FLEET_DIR) on storage shared by every "
                    "member")
        # multi-host spill works when unique_spill_dir is SHARED storage
        # (each host's runs validate present everywhere and the merge
        # adopts them — kernels/unique.py merge law); host-local dirs
        # degrade honestly to OVERFLOW at merge time, not up front
        ingest = ArrowIngest(source, config.batch_rows, process_shard=pshard,
                             columns=config.columns, nested=config.nested)
        plan = ingest.plan
        if not plan.specs:
            return _empty_stats(config)
        # ---- single-pass profiles (ROADMAP 3(c); runtime/singlepass):
        # fused mode folds moments AND histogram counts in one read of
        # every batch, on provisional seeded edges; edge misses re-bin
        # in a targeted column-subset pass B.  Multi-host and elastic
        # topologies keep two passes: bin edges must come from the
        # GLOBALLY merged moments, and cold-start provisional edges
        # have no cross-member agreement seam — demote loudly.
        from tpuprof.config import resolve_profile_passes
        from tpuprof.runtime import singlepass as _sp
        fused_scan = resolve_profile_passes(
            getattr(config, "profile_passes", None)) == "fused" \
            and plan.n_num > 0
        if fused_scan and (pshard[1] > 1 or elastic):
            from tpuprof.utils.trace import logger
            logger.warning(
                "profile_passes=fused is single-host only (multi-host/"
                "elastic merges need globally agreed bin edges) — "
                "running the two-pass structure; results are identical")
            fused_scan = False
        sp_seeds = _sp.resolve_seeds(config, plan) if fused_scan else None
        devices = self._devices
        if devices is None and pshard[1] > 1:
            # multi-process: a LOCAL mesh per host — each host scans its
            # own fragment stripe on its own chips (ICI merge), and the
            # finalized states merge across hosts over DCN
            # (runtime/distributed.merge_pass_a_states; a global mesh
            # would demand identical inputs and dispatch counts on every
            # process, which striped ingest cannot provide)
            devices = jax.local_devices()
        # runner construction is a cache lookup (tpuprof/serve/cache.py):
        # a repeat-fingerprint profile in this process reuses the SAME
        # runner object, whose jit wrappers already hold their compiled
        # executables — the warm-mesh half of `tpuprof serve`, and the
        # fix for the PR-6 drift-leg jaxlib aborts (repeated rebuilds
        # with the persistent compile cache on).  TPUPROF_RUNNER_CACHE=0
        # restores a fresh build per collect.
        from tpuprof.serve.cache import acquire_runner
        runner = acquire_runner(config, plan.n_num, plan.n_hash,
                                devices=devices)
        # host batches are padded to the runner's device-divisible row
        # count (chunks are <= batch_rows <= runner.rows by construction)
        pad = runner.rows

        hostagg = HostAgg(plan, config)
        sampler = RowSampler(config.quantile_sketch_size, plan.n_num,
                             seed=config.seed, process_index=pshard[0])
        # HLL registers fold on host when the native extension is usable
        # on EVERY process (register merges must mix like with like);
        # otherwise the packed plane ships to the device scatter path
        from tpuprof import native
        from tpuprof.runtime.distributed import allgather_objects
        use_host_hll = plan.n_hash > 0 and all(
            allgather_objects(native.available()))
        host_hll = khll.HostRegisters(plan.n_hash, config.hll_precision) \
            if use_host_hll else None
        # ---- fault-tolerance rungs (ROBUSTNESS.md): transient prep
        # retries always; poison-batch quarantine when budgeted; watchdog
        # deadlines on the blocking legs when configured.  All default
        # to the historical fail-fast behavior.
        from tpuprof.config import (resolve_ingest_retries,
                                    resolve_max_quarantined,
                                    resolve_quarantine_log,
                                    resolve_retry_backoff,
                                    resolve_watchdog_timeout)
        from tpuprof.runtime import guard as _guard
        from tpuprof.testing import faults as _faults
        quarantine = _guard.Quarantine(
            resolve_max_quarantined(config.max_quarantined),
            log_path=resolve_quarantine_log(config.quarantine_log))
        batch_guard = _guard.BatchGuard(
            resolve_ingest_retries(config.ingest_retries),
            resolve_retry_backoff(config.retry_backoff_s),
            capture=quarantine.enabled)
        drain_timeout = resolve_watchdog_timeout(
            config.drain_timeout_s, "TPUPROF_DRAIN_TIMEOUT_S")
        barrier_timeout = resolve_watchdog_timeout(
            config.barrier_timeout_s, "TPUPROF_BARRIER_TIMEOUT_S")
        # ---- batch-granular resumability (SURVEY §5 checkpoint/resume):
        # the pass-A scan persists (device state, host sketches, batch
        # cursor) every N batches; a crashed profile resumes by skipping
        # the already-folded prefix of the (deterministic) batch stream.
        if elastic:
            from tpuprof.config import (resolve_fleet_host_id,
                                        resolve_liveness_timeout)
            from tpuprof.runtime import fleet as _fleetrt
            # the manifest fingerprint pins source content + the knobs
            # that change batch enumeration — members with a divergent
            # view must be rejected, not merged
            fleet_member = _fleetrt.FleetMember(
                resolve_fleet_dir(config.fleet_dir),
                resolve_fleet_host_id(config.fleet_host_id),
                ingest.fragment_count(),
                f"{ingest.fingerprint()}:{config.batch_rows}"
                f":{config.nested}",
                liveness_timeout_s=resolve_liveness_timeout(
                    config.liveness_timeout_s))
        resume = _CollectCheckpoint(config, plan, runner, pshard,
                                    ingest.fingerprint(),
                                    table_source=ingest._table is not None,
                                    fused=fused_scan) \
            if config.checkpoint_path else None
        skip = 0
        resume_frag = None
        fleet_ck_done = None
        restored = resume is not None and resume.exists()
        state = None
        state_h = None          # fused histogram fold (singlepass.py)
        sp_edges = None         # the provisional edges it bins on
        if resume is not None:
            # checkpoint saves snapshot whatever the fused fold holds
            # at flush time (None before the first real batch)
            resume.extras = lambda: ((state_h, sp_edges) if fused_scan
                                     else (None, None))
        if restored:
            try:
                (state, sampler, hostagg, host_hll, skip,
                 resume_frag, prior_q, fleet_ck_done,
                 state_h, sp_edges) = resume.load()
                # a degraded prefix stays degraded: the restored
                # manifest keeps riding checkpoints and the final report
                quarantine.seed(prior_q)
                # the artifact references the tracker's spill runs;
                # assert crash protection on the resumed object too
                # (artifacts pickled before the flag existed restore
                # without it)
                hostagg.unique.persistent = True
            except Exception as exc:
                if pshard[1] == 1:
                    raise       # single host: fail fast and say why
                # multi-host: one host's unreadable artifact (older
                # format, torn write) must not exit this process while
                # its peers block in the resume-barrier collective —
                # fall back to a fresh stripe scan, loudly.  EVERY
                # restored accumulator resets: a failure after the
                # unpack (e.g. a pre-spill-era HostAgg) would otherwise
                # leave restored sketches under a zeroed cursor and
                # double-count the prefix
                from tpuprof.utils.trace import logger
                logger.warning(
                    "host %d: checkpoint artifact %r failed to load "
                    "(%s); rescanning this host's stripe from zero",
                    pshard[0], resume.path, exc)
                restored = False
                state, skip, resume_frag = None, 0, None
                state_h, sp_edges = None, None
                fleet_ck_done = None
                quarantine.seed([])
                hostagg = HostAgg(plan, config)
                sampler = RowSampler(config.quantile_sketch_size,
                                     plan.n_num, seed=config.seed,
                                     process_index=pshard[0])
                host_hll = khll.HostRegisters(
                    plan.n_hash, config.hll_precision) \
                    if use_host_hll else None
        if resume is not None and pshard[1] > 1:
            # resume barrier: every host reports (rank, restored?,
            # cursor) before any scanning starts — each host's meta has
            # already pinned its artifact to this (stripe, source,
            # config), so a mixed fleet is CORRECT (a fresh host just
            # rescans its own stripe) but worth saying out loud
            with span("resume_barrier", rank=pshard[0],
                      restored=restored):
                # a peer that died before its artifact loaded would
                # otherwise hang this collective forever; the watchdog
                # converts the hang into a typed, heartbeat-stamped
                # failure (off unless barrier_timeout_s is set)
                from tpuprof.runtime.distributed import (
                    allgather_with_watchdog)
                peers = allgather_with_watchdog(
                    (pshard[0], restored, skip), barrier_timeout,
                    site="resume_barrier",
                    heartbeat=lambda: {"rank": pshard[0],
                                       "restored": restored,
                                       "cursor": int(skip)})
            log_event("multihost_resume_barrier", peers=peers)
            # fleet view at the barrier: a resumed fleet's first shared
            # artifact says who restored, who fell back, and what the
            # restore legs cost — before any scanning starts.
            # Symmetric: every host in this block calls it.
            from tpuprof.runtime.distributed import publish_fleet
            publish_fleet("resume_barrier",
                          metrics_path=obs.resolve_metrics_path(config),
                          quarantined=len(quarantine.entries))
            flags = {r for _, r, _ in peers}
            if flags == {True, False}:
                from tpuprof.utils.trace import logger
                logger.warning(
                    "multi-host resume: hosts %s restored a checkpoint "
                    "but hosts %s start from zero (their artifacts are "
                    "missing or were cleared) — results are unaffected; "
                    "the fresh hosts simply rescan their stripes",
                    sorted(p for p, r, _ in peers if r),
                    sorted(p for p, r, _ in peers if not r))
        fleet_replay: List[int] = []
        if fleet_member is not None:
            # the elastic join/leave barrier: reconcile adopted manifest
            # claims against the checkpoint cursor (the handoff token).
            # Claims the checkpoint covers are final; claims marked done
            # AFTER the last save — and any claim with no checkpoint at
            # all — are replayed from scratch, because the fold state
            # covering them died with the predecessor.
            ck_done = set(fleet_ck_done or []) if restored else set()
            in_progress = {resume_frag[0]} \
                if restored and resume_frag is not None else set()
            if restored:
                # ownership fencing on the handoff: fragments the
                # checkpoint's fold covers may have been STOLEN and
                # re-scanned by survivors while this member was down
                # (adoption already dropped them from the claimed
                # view).  The restored fold contains their rows and
                # cannot subtract them — discard the restore and
                # replay the still-owned claims from scratch instead
                # of double-counting the stolen fragments
                stolen_cover = sorted((ck_done | in_progress)
                                      - fleet_member.claimed("a"))
                if stolen_cover:
                    from tpuprof.utils.trace import logger
                    logger.warning(
                        "fleet member %s: fragments %s of the adopted "
                        "checkpoint were stolen by survivors while "
                        "this member was down — discarding the "
                        "restored fold and rescanning the still-owned "
                        "claims from zero",
                        fleet_member.host_id, stolen_cover)
                    log_event("fleet_adopt_fenced",
                              host=fleet_member.host_id,
                              stolen=stolen_cover)
                    restored = False
                    state, skip, resume_frag = None, 0, None
                    ck_done, in_progress = set(), set()
                    quarantine.seed([])
                    hostagg = HostAgg(plan, config)
                    sampler = RowSampler(config.quantile_sketch_size,
                                         plan.n_num, seed=config.seed,
                                         process_index=pshard[0])
                    host_hll = khll.HostRegisters(
                        plan.n_hash, config.hll_precision) \
                        if use_host_hll else None
                    resume.last_saved = -1
            for k in sorted(ck_done):
                fleet_member.mark_done("a", k)
            fleet_replay = sorted(fleet_member.claimed("a")
                                  - ck_done - in_progress)
            fleet_member.undo_done("a", fleet_replay)
            if restored:
                # commit the restored leaves with the step programs'
                # state sharding (runtime/mesh.place_state) so the
                # joined member's first fold reuses the steady-state
                # executable — the byte-stability the join acceptance
                # test pins rests on this
                state = runner.place_state(jax.device_get(state))
                log_event("fleet_adopt", host=fleet_member.host_id,
                          cursor=int(skip), done=sorted(ck_done),
                          replay=fleet_replay)
        cursor = skip
        # fragment-positioned streaming whenever checkpointing is on, so
        # saved cursors carry (fragment, batch) and resume skips whole
        # fragments' I/O instead of re-decoding the prefix.  A resume
        # cursor without a position (in-memory source) falls back to the
        # decode-and-skip batch counter.
        use_positions = resume is not None and ingest.supports_positions() \
            and (skip == 0 or resume_frag is not None)
        resume_pos = (resume_frag[0], resume_frag[1] + 1) \
            if use_positions and resume_frag is not None else None

        scan_s = max(int(config.scan_batches), 1)
        if resume is not None and scan_s > 1 \
                and resume.every % scan_s != 0:
            # every due checkpoint forces a flush, so a cadence that is
            # not a multiple of the group size keeps truncating groups —
            # silently paying per-batch dispatch latency would defeat
            # the staged path the user asked for
            from tpuprof.utils.trace import logger
            logger.warning(
                "checkpoint_every_batches=%d is not a multiple of "
                "scan_batches=%d: checkpoint flushes truncate staged "
                "groups, so some (or all) dispatches fall back to the "
                "per-batch path — align the cadence to keep the "
                "multi-batch scan", resume.every, scan_s)
        with_hll = host_hll is None

        def flush_group(pending, fold_staged, fold_one):
            """THE staged-vs-tail flush policy (shared by both passes):
            a FULL group ships as one stacked placement folded by a
            single multi-batch scan dispatch (the benched fast path —
            amortizes per-dispatch latency); partial groups (tails,
            checkpoint boundaries) fold per-batch through the step
            program, which reuses one fixed compiled signature instead
            of compiling a scan program per group size."""
            if len(pending) == scan_s and scan_s > 1:
                fold_staged(pending)
            else:
                for p in pending:
                    fold_one(p)
            pending.clear()

        sp_eds_d = None         # (lo, hi, mean) replicated device arrays

        def _staged_a(group):
            nonlocal state, state_h
            if fused_scan and state_h is not None:
                state, state_h = runner.scan_ab(
                    state, state_h,
                    runner.stage_batches(group, with_hll=with_hll),
                    *sp_eds_d)
                return
            state = runner.scan_a(
                state, runner.stage_batches(group, with_hll=with_hll))

        def _one_a(p):
            nonlocal state, state_h
            if fused_scan and state_h is not None:
                state, state_h = runner.step_ab(
                    state, state_h,
                    runner.put_batch(p, with_hll=with_hll), *sp_eds_d)
                return
            state = runner.step_a(
                state, runner.put_batch(p, with_hll=with_hll))

        def flush_a(pending):
            flush_group(pending, _staged_a, _one_a)

        def _hit_host_death(key):
            # the participation kill switch (faults site host_death):
            # NOT quarantinable, NOT retryable — an elastic member
            # departs loudly (deletes its heartbeat) so survivors
            # detect the death immediately; fixed-membership runs let
            # the typed error escape to the CLI (exit 8)
            try:
                _faults.hit("host_death", key=key)
            except HostDeathError:
                if fleet_member is not None:
                    fleet_member.depart()
                raise

        # elastic done-marking: fragment k is marked complete when the
        # first batch of a LATER fragment folds (in-order delivery means
        # every batch of k folded first); the final fragment closes at
        # stream end
        _cur_frag = [resume_frag[0]
                     if restored and resume_frag is not None else None]

        def _note_frag(fp, phase="a"):
            if fleet_member is None or fp is None:
                return
            if _cur_frag[0] is not None and fp[0] != _cur_frag[0]:
                fleet_member.mark_done(phase, _cur_frag[0])
            _cur_frag[0] = fp[0]

        with span("scan_a", cols=len(plan.specs), n_num=plan.n_num,
                  n_hash=plan.n_hash):
            # centering shift from the first batch's prefix — any value
            # near the data scale conditions the f32 sums equally well.
            # The estimate is agreed ACROSS hosts (deadlock-safe even for
            # a host with an empty fragment stripe) so every device in
            # the global mesh carries the same shift and the collective
            # merge's rebase is exactly the identity.
            batches = prefetch_prepared(
                ingest, plan, pad, config.hll_precision,
                depth=max(2, min(scan_s, 8)),
                skip_batches=0 if use_positions else skip,
                positions=use_positions, resume_pos=resume_pos,
                workers=config.prepare_workers,
                prep_workers=config.prep_workers,
                full_hashes=config.exact_distinct,
                batch_guard=batch_guard,
                raw_stream=_fleet_stream(
                    fleet_member, "a", ingest,
                    resume_frag=resume_frag if restored else None,
                    replay=fleet_replay)
                if fleet_member is not None else None)
            # the shift estimate needs a REAL first batch; quarantined
            # heads are re-chained below so cursor accounting stays
            # in stream order
            poisoned_head: List[Any] = []
            first_hb = next(batches, None)
            while isinstance(first_hb, _guard.PoisonBatch):
                poisoned_head.append(first_hb)
                first_hb = next(batches, None)
            if fused_scan and first_hb is not None:
                if sp_edges is None:
                    # provisional edges: the artifact seed where one
                    # resolved, first-batch sketch for the rest (cold
                    # start, new columns).  A checkpoint restore
                    # arrives with sp_edges already set — the resumed
                    # fold MUST keep binning on the same edges.
                    sp_edges = _sp.sketch_edges(first_hb.x,
                                                first_hb.nrows,
                                                into=sp_seeds)
                if state_h is None:
                    state_h = runner.init_pass_b()
                sp_eds_d = (runner.put_replicated(sp_edges.lo,
                                                  dtype=np.float32),
                            runner.put_replicated(sp_edges.hi,
                                                  dtype=np.float32),
                            runner.put_replicated(sp_edges.mean,
                                                  dtype=np.float32))
            if state is None:
                shift = merge_shift_estimates(
                    estimate_shift(first_hb)
                    if first_hb is not None else None)
                state = runner.init_pass_a(shift)
            elif pshard[1] > 1:
                # a RESTORED host must still participate in the fleet's
                # shift agreement: in a mixed fleet (some hosts resumed,
                # some fresh) skipping it would skew the allgather
                # sequence and cross collective payloads downstream.
                # The result is discarded — this host's state keeps the
                # shift it was built with, and the cross-host moment
                # merge rebases differing shifts exactly
                # (kernels/moments.merge).
                merge_shift_estimates(
                    estimate_shift(first_hb)
                    if first_hb is not None else None)
            last_frag = resume_frag
            pending: List[HostBatch] = []
            if first_hb is not None or poisoned_head:
                head = poisoned_head + \
                    ([first_hb] if first_hb is not None else [])
                for hb in itertools.chain(head, batches):
                    if isinstance(hb, _guard.PoisonBatch):
                        # batch failed past the retry budget: skip it,
                        # keep the stream alive.  The cursor still
                        # advances — the batch WAS consumed from the raw
                        # stream, so a resume must not replay it.
                        cursor += 1
                        _note_frag(hb.frag_pos)
                        last_frag = hb.frag_pos or last_frag
                        quarantine.admit(site=hb.site, error=hb.error,
                                         cursor=cursor, rows=hb.rows,
                                         frag_pos=hb.frag_pos)
                        if resume is not None and resume.due(cursor):
                            flush_a(pending)
                            resume.save(state, sampler, hostagg,
                                        host_hll, cursor,
                                        frag_pos=last_frag,
                                        quarantine=quarantine,
                                        fleet_done=fleet_member.done("a")
                                        if fleet_member else None)
                        continue
                    _note_frag(hb.frag_pos)
                    _hit_host_death(cursor)
                    try:
                        _faults.hit("fold", key=cursor)
                        # host-side folds run as batches arrive (they
                        # overlap the async device dispatches of
                        # earlier groups)
                        sampler.update(hb.x, hb.nrows)
                        if host_hll is not None:
                            host_hll.update(hb.hll, hb.nrows)
                        hostagg.update(hb)
                    except Exception as exc:
                        if not quarantine.enabled:
                            raise
                        # fold is NOT idempotent (sampler/HLL/MG state
                        # may hold partial contributions) — no retry;
                        # quarantine the batch and press on
                        cursor += 1
                        last_frag = hb.frag_pos or last_frag
                        quarantine.admit(site="fold", error=exc,
                                         cursor=cursor, rows=hb.nrows,
                                         frag_pos=hb.frag_pos)
                        continue
                    pending.append(hb)
                    cursor += 1
                    last_frag = hb.frag_pos or last_frag
                    # a due checkpoint forces a flush so the artifact's
                    # cursor equals the device-folded batch count (host
                    # and device views agree only at flush boundaries)
                    ckpt_due = resume is not None and resume.due(cursor)
                    if len(pending) >= scan_s or ckpt_due:
                        flush_a(pending)
                        if ckpt_due:
                            resume.save(state, sampler, hostagg, host_hll,
                                        cursor, frag_pos=last_frag,
                                        quarantine=quarantine,
                                        fleet_done=fleet_member.done("a")
                                        if fleet_member else None)
                flush_a(pending)
                if fleet_member is not None and _cur_frag[0] is not None:
                    # the stream drained completely: the last fragment
                    # read is fully folded
                    fleet_member.mark_done("a", _cur_frag[0])
            if drain_timeout and state is not None:
                # bound the device-side drain: a wedged dispatch fails
                # with a heartbeat instead of hanging the run
                runner.wait_ready(
                    state, drain_timeout,
                    heartbeat=lambda: {"cursor": int(cursor),
                                       "rows": int(hostagg.n_rows)})
            # drain boundary: HBM/RSS headroom gauges (silent on CPU)
            obs.memory.sample()
        if resume is not None and resume.last_saved != cursor:
            # pass A complete: keep the final state on disk so a crash
            # during merge/pass-B resumes with the whole stream skipped
            # instead of rescanning; cleared only after assembly
            resume.save(state, sampler, hostagg, host_hll, cursor,
                        frag_pos=last_frag, quarantine=quarantine,
                        fleet_done=fleet_member.done("a")
                        if fleet_member else None)
        # single-host pass-B bounds come off the DEVICE (the twin of
        # khistogram.pass_b_bounds, parity-pinned): the bounds jit
        # enqueues BEFORE the merged-state fetch, so pass B never waits
        # on a host round trip — the same orchestration bench.py times.
        # Multi-host keeps the host recipe: bin edges must come from the
        # GLOBALLY merged moments or each host would bin differently.
        bounds_d = None
        if pshard[1] == 1 and fleet_member is None \
                and config.exact_passes and plan.n_num > 0 \
                and not fused_scan:
            # elastic fleets keep the host recipe too: bin edges must
            # come from the FLEET-merged moments or members would bin
            # differently.  Fused profiles have no pass B to overlap —
            # the hit check and any targeted re-bin use the host
            # recipe (singlepass.exact_bounds_f32, the device twin's
            # parity-pinned equal).
            bounds_d = runner.bounds_b_device(state)
        fleet_regs = None
        fleet_q: Optional[List] = None
        with span("merge", hosts=pshard[1]):
            res_a = runner.finalize_a(state)
            if fleet_member is not None:
                # elastic resume barrier: contribute this member's
                # finalized part, wait for full fragment coverage
                # (stealing + replaying dead members' fragments), fold
                # every part with the same merge laws the collectives
                # apply (runtime/fleet.py)
                def _steal_scan_a(frags):
                    return _scan_fragments_pass_a(
                        frags, ingest, plan, pad, config, runner,
                        batch_guard, host_hll is not None)

                (res_a, hostagg, sampler, fleet_regs, fleet_q,
                 fleet_q_mark) = _elastic_merge_a(
                    fleet_member, res_a, hostagg, sampler, host_hll,
                    quarantine, _steal_scan_a, barrier_timeout)
            else:
                # cross-host: each host's device sketches merged over
                # ICI by the mesh collectives; the finalized states and
                # host-side aggregates ride DCN gathers
                res_a = merge_pass_a_states(res_a)
                hostagg = merge_host_aggs(hostagg)
                if pshard[1] > 1:
                    # one k-way spill resolve for the fleet (rank 0
                    # reads, everyone adopts) instead of N re-reads
                    from tpuprof.runtime.distributed import (
                        resolve_unique_distributed)
                    resolve_unique_distributed(hostagg.unique)
                sampler = merge_samplers(sampler)
        log_event("pass_a", rows=hostagg.n_rows, devices=runner.n_dev,
                  n_num=plan.n_num, n_hash=plan.n_hash)

        momf = kmoments.finalize(res_a["mom"])
        rho_all = kcorr.finalize(res_a["corr"])
        probes = list(config.quantile_probes)
        quants = sampler.quantiles(probes)
        sample_vals, sample_kept = sampler.columns()
        if fleet_regs is not None:
            # elastic: per-part effective registers (host regs where a
            # member had them, its device plane otherwise — the formats
            # are bit-identical) already max-folded across parts
            hll_est = khll.finalize(fleet_regs)
        elif host_hll is not None:
            from tpuprof.runtime.distributed import merge_hll_registers
            hll_est = khll.finalize(merge_hll_registers(host_hll).regs)
        else:
            hll_est = khll.finalize(res_a["hll"])

        # ---- pass B: exact histograms + MAD + top-k recount --------------
        hists: Optional[List] = None
        mad: Optional[np.ndarray] = None
        recounter: Optional[Recounter] = None
        rho_spear: Optional[np.ndarray] = None
        spear_approx = False
        exact_lanes: Optional[np.ndarray] = None
        run_pass_b = config.exact_passes and ingest.rescannable \
            and plan.n_num > 0 and hostagg.n_rows > 0
        # fused adoption (runtime/singlepass.py): finalize the fused
        # histogram fold, compare the provisional edges against the
        # exact pass-A bounds, and decide what — if anything — a
        # second scan still owes: a targeted re-bin of the missed
        # lanes, the top-k recount, the Spearman rank pass, or nothing
        # (the warm-edge single-pass fast path).
        res_h = None
        sp_hits = None
        sp_exact = None
        rebin_lanes: Optional[np.ndarray] = None
        res_b_adopted = None
        if fused_scan and state_h is not None and hostagg.n_rows > 0:
            res_h = runner.finalize_b(state_h)
            sp_hits, sp_exact = _sp.hit_lanes(sp_edges, momf)
            _sp.record_outcome(sp_hits)
            need_recount = bool(hostagg.mg)
            if run_pass_b:
                if sp_hits.all() and not need_recount \
                        and not config.spearman:
                    # every edge held and nothing else needs a second
                    # read: the profile is complete after ONE scan
                    run_pass_b = False
                    res_b_adopted = dict(res_h)
                else:
                    rebin_lanes = np.nonzero(~sp_hits)[0]
            else:
                # no second scan exists (non-rescannable source or
                # exact_passes=False): adopt the exact histogram/MAD
                # where the edges held, keep the sample tier elsewhere
                res_b_adopted = dict(res_h)
                if not sp_hits.all():
                    exact_lanes = sp_hits
        if run_pass_b:
            recounter = Recounter(hostagg)
            rebin_names: List[str] = []
            if rebin_lanes is not None:
                # fused targeted re-bin: device work only for the
                # missed columns, with the EXACT bounds the hit check
                # compared against (subset of the same f32 arrays)
                state_b = runner.init_pass_b(len(rebin_lanes)) \
                    if len(rebin_lanes) else None
                if len(rebin_lanes):
                    _faults.hit("singlepass_rebin")
                    lo_e, hi_e, mean_e = sp_exact
                    lo_d = runner.put_replicated(lo_e[rebin_lanes],
                                                 dtype=np.float32)
                    hi_d = runner.put_replicated(hi_e[rebin_lanes],
                                                 dtype=np.float32)
                    mean_d = runner.put_replicated(mean_e[rebin_lanes],
                                                   dtype=np.float32)
                    lane_names = {s.num_lane: s.name for s in plan.specs
                                  if s.role == "num"}
                    rebin_names = [str(lane_names[i])
                                   for i in rebin_lanes.tolist()]
                else:
                    lo_d = hi_d = mean_d = None
            else:
                state_b = runner.init_pass_b()
                if bounds_d is not None:
                    lo_d, hi_d, mean_d = bounds_d
                else:
                    lo, hi, mean_c = khistogram.pass_b_bounds(momf)
                    lo_d = runner.put_replicated(lo, dtype=np.float32)
                    hi_d = runner.put_replicated(hi, dtype=np.float32)
                    mean_d = runner.put_replicated(mean_c,
                                                   dtype=np.float32)
            spear_state = None
            if config.spearman:
                spear_state = runner.init_spearman()
                if runner.spear_grid:
                    # pallas tier: dense-compare ranks on a G-point grid.
                    # The wide tier's rank kernel has a VMEM budget
                    # calibrated for G <= 256, so its grid is clamped.
                    from tpuprof.kernels import fused as kfused
                    g = min(config.spearman_grid, kfused.MAX_SPEAR_GRID)
                    if g < config.spearman_grid:
                        from tpuprof.utils.trace import logger
                        logger.warning(
                            "spearman_grid=%d clamped to %d: the pallas "
                            "grid tiers are compile-probed only up to "
                            "that resolution (kernels/fused.py)",
                            config.spearman_grid, g)
                    spear_grid = runner.put_replicated(
                        sampler.cdf_grid(g), dtype=np.float32)
                else:
                    # exact tier: rank transform through the pass-A sample
                    # CDF (+inf pads unkept slots past every real value)
                    if hostagg.n_rows > 1_000_000:
                        # searchsorted serializes its gathers off-TPU too
                        # (measured ~4 s/64k-row batch on hardware —
                        # PERF.md); say so instead of silently crawling
                        from tpuprof.utils.trace import logger
                        logger.warning(
                            "spearman exact tier on a non-pallas mesh at "
                            "%d rows: expect minutes — the grid tier "
                            "(real TPU, use_fused) is ~100x faster with "
                            "~1/(2G) rank error", hostagg.n_rows)
                    srt, kept_n = sampler.sorted_padded()
                    kept_counts = runner.put_replicated(kept_n,
                                                        dtype=np.int32)
                    sorted_sample = runner.put_replicated(srt,
                                                          dtype=np.float32)
            def fold_spear(st, db_or_sb, staged):
                if runner.spear_grid:
                    if staged:
                        return runner.scan_spearman_grid(st, db_or_sb,
                                                         spear_grid)
                    return runner.step_spearman_grid(st, db_or_sb,
                                                     spear_grid)
                if staged:
                    # exact tier has no scan program (CPU meshes, where
                    # dispatch latency is negligible) — re-read the
                    # staged device slices per batch, no re-transfer
                    for i in range(db_or_sb.n_batches):
                        st = runner.step_spearman(
                            st, runner.slice_staged(db_or_sb, i),
                            sorted_sample, kept_counts)
                    return st
                return runner.step_spearman(st, db_or_sb, sorted_sample,
                                            kept_counts)

            import dataclasses as _dc

            def _hist_view(hb):
                """The batch the histogram fold consumes: whole for
                two-pass, the missed-column slice for a fused re-bin
                (the subset ships instead of the full plane — at small
                miss counts the transfer shrinks proportionally)."""
                if rebin_lanes is None:
                    return hb
                return _dc.replace(hb, x=hb.x[:, rebin_lanes])

            def _staged_b(group):
                """Full groups take the staged scan_b dispatch, and the
                Spearman state folds from the SAME staged placement —
                one transfer feeds both.  A fused re-bin's hist fold
                takes its own column-subset placement (Spearman, when
                on, still needs the full plane)."""
                nonlocal state_b, spear_state
                if rebin_lanes is None:
                    sb = runner.stage_batches(group, with_hll=False)
                    state_b = runner.scan_b(state_b, sb, lo_d, hi_d,
                                            mean_d)
                    if spear_state is not None:
                        spear_state = fold_spear(spear_state, sb, True)
                    return
                if state_b is not None:
                    sb_sub = runner.stage_batches(
                        [_hist_view(p) for p in group], with_hll=False)
                    state_b = runner.scan_b(state_b, sb_sub, lo_d, hi_d,
                                            mean_d)
                if spear_state is not None:
                    sb = runner.stage_batches(group, with_hll=False)
                    spear_state = fold_spear(spear_state, sb, True)

            def _one_b(p):
                nonlocal state_b, spear_state
                if rebin_lanes is None:
                    db = runner.put_batch(p, with_hll=False)
                    state_b = runner.step_b(state_b, db, lo_d, hi_d,
                                            mean_d)
                    if spear_state is not None:
                        spear_state = fold_spear(spear_state, db, False)
                    return
                if state_b is not None:
                    db_sub = runner.put_batch(_hist_view(p),
                                              with_hll=False)
                    state_b = runner.step_b(state_b, db_sub, lo_d, hi_d,
                                            mean_d)
                if spear_state is not None:
                    db = runner.put_batch(p, with_hll=False)
                    spear_state = fold_spear(spear_state, db, False)

            def flush_b(pending):
                flush_group(pending, _staged_b, _one_b)

            def _steal_scan_b(frags):
                """Replay stolen fragments into a fresh finalized
                pass-B part (bounds/candidates are fleet-global, so any
                member can recount any fragment)."""
                st_b = runner.init_pass_b()
                sp_st = runner.init_spearman() \
                    if spear_state is not None else None
                rec = Recounter(hostagg)

                def _stream():
                    for fi in frags:
                        yield from ingest.read_fragment(fi)

                for shb in prefetch_prepared(
                        ingest, plan, pad, config.hll_precision,
                        hashes=False, workers=config.prepare_workers,
                        prep_workers=config.prep_workers,
                        batch_guard=batch_guard, raw_stream=_stream()):
                    if isinstance(shb, _guard.PoisonBatch):
                        quarantine.admit(site=shb.site + "_pass_b",
                                         error=shb.error, rows=shb.rows,
                                         frag_pos=shb.frag_pos)
                        continue
                    rec.update(shb)
                    sdb = runner.put_batch(shb, with_hll=False)
                    st_b = runner.step_b(st_b, sdb, lo_d, hi_d, mean_d)
                    if sp_st is not None:
                        sp_st = fold_spear(sp_st, sdb, False)
                return {"kind": "pass_b",
                        "res_b": runner.finalize_b(st_b),
                        "counts": rec.counts,
                        "spear": runner.finalize_spearman(sp_st)
                        if sp_st is not None else None}

            import time as _time
            _t0_b = _time.perf_counter()
            with span("scan_b", spearman=config.spearman):
                # hashes=False: pass B never reads the HLL plane, so the
                # host hash loop is skipped on the second scan
                pending_b: List[HostBatch] = []
                for hb in prefetch_prepared(ingest, plan, pad,
                                            config.hll_precision,
                                            depth=max(2, min(scan_s, 8)),
                                            hashes=False,
                                            workers=config.prepare_workers,
                                            prep_workers=config.prep_workers,
                                            batch_guard=batch_guard,
                                            raw_stream=_fleet_stream(
                                                fleet_member, "b", ingest,
                                                replay=sorted(
                                                    fleet_member
                                                    .claimed("b")))
                                            if fleet_member is not None
                                            else None):
                    if isinstance(hb, _guard.PoisonBatch):
                        # pass-B skip shares the pass-A budget; the
                        # entry's pass field keeps the manifest honest
                        # about WHICH statistics lost the batch
                        quarantine.admit(site=hb.site + "_pass_b",
                                         error=hb.error, rows=hb.rows,
                                         frag_pos=hb.frag_pos)
                        continue
                    recounter.update(hb)
                    pending_b.append(hb)
                    if len(pending_b) >= scan_s:
                        flush_b(pending_b)
                flush_b(pending_b)
                if fleet_member is not None:
                    res_b, counts, rho_spear = _elastic_merge_b(
                        fleet_member,
                        {"kind": "pass_b",
                         "res_b": runner.finalize_b(state_b),
                         "counts": recounter.counts,
                         "spear": runner.finalize_spearman(spear_state)
                         if spear_state is not None else None},
                        _steal_scan_b, barrier_timeout)
                    recounter.counts = counts
                    spear_state = None     # finalized + merged above
                else:
                    res_b = merge_pass_b_states(
                        runner.finalize_b(state_b)) \
                        if state_b is not None else None
                    recounter.counts = merge_recount_arrays(
                        recounter.counts)
            if spear_state is not None:
                rho_spear = kcorr.finalize(merge_corr_states(
                    runner.finalize_spearman(spear_state)))
            if rebin_lanes is not None:
                # fused: hit lanes keep their single-scan counts, miss
                # lanes adopt the exact re-bin — identical to two-pass
                # lane for lane
                if len(rebin_lanes) and res_b is not None:
                    res_b = _sp.merge_rebinned(res_h, res_b, rebin_lanes)
                    _sp.record_rebin(_time.perf_counter() - _t0_b,
                                     rebin_names, sp_edges.origin)
                else:
                    res_b = dict(res_h)
            hists, mad = khistogram.finalize(
                res_b, momf["fmin"], momf["fmax"], momf["n"], config.bins)
        elif config.spearman and hostagg.n_rows > 0 and plan.n_num > 1:
            # the rank pass cannot run (single-pass mode or a
            # non-rescannable source) — estimate from the K-row merged
            # uniform sample instead of omitting: rank correlation of a
            # uniform row sample has ~1/sqrt(K) standard error
            # (ingest/sample.spearman), and the matrix says so via
            # .attrs["approx"]
            spear_approx = True
            rho_spear = sampler.spearman()
            from tpuprof.utils.trace import logger
            logger.info(
                "spearman: single-pass mode — matrix estimated from the "
                "%d-row sample (rank error ~%.3f)",
                min(sampler.values.shape[0], sampler.k),
                1.0 / np.sqrt(max(sampler.k, 1)))
        if fused_scan and res_b_adopted is not None:
            # warm-edge single pass (or the no-second-scan tier):
            # finalize the adopted counts; exact_lanes (when set) gates
            # per-lane adoption in _numeric_stats — miss lanes keep the
            # sample-derived tier exactly as two-pass single-pass would
            hists, mad = khistogram.finalize(
                res_b_adopted, momf["fmin"], momf["fmax"], momf["n"],
                config.bins)
        if recounter is None and config.exact_passes \
                and ingest.rescannable and hostagg.n_rows > 0 \
                and not (fused_scan and res_b_adopted is not None):
            # no numeric columns — only the top-k recount matters.
            # hashes=False: the recount reads categorical codes only, so
            # the host hash + HLL-packing loop is skipped on this scan.
            recounter = Recounter(hostagg)

            def _steal_recount(frags):
                rec = Recounter(hostagg)

                def _stream():
                    for fi in frags:
                        yield from ingest.read_fragment(fi)

                for shb in prefetch_prepared(
                        ingest, plan, pad, config.hll_precision,
                        hashes=False, workers=config.prepare_workers,
                        prep_workers=config.prep_workers,
                        batch_guard=batch_guard, raw_stream=_stream()):
                    if isinstance(shb, _guard.PoisonBatch):
                        quarantine.admit(site=shb.site + "_pass_b",
                                         error=shb.error, rows=shb.rows,
                                         frag_pos=shb.frag_pos)
                        continue
                    rec.update(shb)
                return {"kind": "pass_b", "res_b": None,
                        "counts": rec.counts, "spear": None}

            with span("scan_b", recount_only=True):
                for hb in prefetch_prepared(
                        ingest, plan, pad,
                        config.hll_precision, hashes=False,
                        workers=config.prepare_workers,
                        prep_workers=config.prep_workers,
                        batch_guard=batch_guard,
                        raw_stream=_fleet_stream(
                            fleet_member, "b", ingest,
                            replay=sorted(fleet_member.claimed("b")))
                        if fleet_member is not None else None):
                    if isinstance(hb, _guard.PoisonBatch):
                        quarantine.admit(site=hb.site + "_pass_b",
                                         error=hb.error, rows=hb.rows,
                                         frag_pos=hb.frag_pos)
                        continue
                    recounter.update(hb)
                if fleet_member is not None:
                    _, recounter.counts, _ = _elastic_merge_b(
                        fleet_member,
                        {"kind": "pass_b", "res_b": None,
                         "counts": recounter.counts, "spear": None},
                        _steal_recount, barrier_timeout)
                else:
                    # each host recounts only its own fragment stripe
                    recounter.counts = merge_recount_arrays(
                        recounter.counts)

        stats = _assemble(plan, config, ingest.sample(config.sample_rows),
                          hostagg, momf, rho_all, quants, sample_vals,
                          sample_kept, hll_est, hists, mad, recounter,
                          probes, rho_spear=rho_spear,
                          spear_approx=spear_approx,
                          exact_lanes=exact_lanes)
        q_entries = quarantine.entries
        if fleet_member is not None:
            # the fleet's pass-A skips rode the contribution parts
            # (deterministic part order); this member's LATER entries
            # (pass-B steals) follow
            q_entries = list(fleet_q or []) \
                + quarantine.entries[fleet_q_mark:]
        elif pshard[1] > 1:
            # every host gathers every stripe's skips (symmetric
            # collective — all hosts call it, even with empty lists);
            # host 0's report then lists the fleet's degradation
            q_entries = [e for part in allgather_objects(q_entries)
                         for e in part]
        if q_entries:
            # only degraded runs carry the key — clean-run stats (and
            # the rendered HTML) stay byte-identical to pre-quarantine
            stats["_quarantine"] = q_entries
        # spill runs go FIRST: a crash between the two deletes leaves an
        # artifact whose missing runs degrade honestly on resume
        # (__setstate__ demotes to OVERFLOW), whereas the reverse order
        # would orphan run files no future cleanup sweep owns
        if pshard[1] > 1 and config.unique_spill_dir:
            # shared-spill-dir deployments: every host's assemble reads
            # the SAME run files (resolve's memmaps) — barrier before
            # any host deletes them, or a fast host could yank a slow
            # host's files mid-resolve
            allgather_objects("unique-cleanup-barrier")
        hostagg.unique.cleanup()     # spill runs are working space only
        if resume is not None:
            resume.clear()           # profile assembled: artifact is stale
        # this profile's phase timings ride the stats dict (the report
        # footer reads them from there — global state would attribute
        # another profile's scan to this report)
        stats["_phases"] = get_phase_report(reset=True)
        # likewise the metrics snapshot (counters/spans/checkpoint
        # durations) for the report's pipeline-stats footer, plus a
        # final snapshot into the JSONL sink for offline reads
        obs.memory.sample()     # final headroom reading rides the snapshot
        snap = obs.snapshot_if_enabled()
        if snap is not None:
            stats["_obs"] = snap
        obs.finalize(reason="collect")
        # fleet aggregation (obs/fleet.py): gather every process's
        # registry over DCN; host 0 writes <metrics_path>.fleet.prom +
        # a fleet_snapshot event.  Symmetric collective — every host
        # reaches this line (same reason the q_entries gather above is
        # unconditional), and a disabled registry's wire is still valid,
        # so mixed metrics settings cannot deadlock.
        if fleet_member is not None:
            # elastic twin of publish_fleet: wires ride the fleet dir,
            # the surviving leader writes <metrics>.fleet.prom with
            # per-host labels + the rebalance counters — no collective,
            # so a dead member cannot wedge the dump
            fleet_member.publish(obs.resolve_metrics_path(config),
                                 reason="collect")
            fleet_member.close()
        elif pshard[1] > 1 or obs.enabled():
            from tpuprof.runtime.distributed import publish_fleet
            publish_fleet("collect",
                          metrics_path=obs.resolve_metrics_path(config),
                          quarantined=len(quarantine.entries))
        return stats


# ---------------------------------------------------------------------------
# Assembly: merged device/host results -> the stats dict contract
# ---------------------------------------------------------------------------

def _sample_mode(values: np.ndarray, kept: np.ndarray) -> float:
    """Mode estimated from the uniform sample (exact when the sample holds
    the whole column)."""
    v = values[kept]
    if not v.size:
        return np.nan
    uniq, cnt = np.unique(v, return_counts=True)
    return float(uniq[np.argmax(cnt)])


def _assemble(plan, config, sample_df, hostagg, momf, rho_all, quants,
              sample_vals, sample_kept, hll_est, hists, mad, recounter,
              probes, rho_spear=None, spear_approx=False,
              exact_lanes=None) -> Dict[str, Any]:
    n = hostagg.n_rows
    variables: Dict[str, Dict[str, Any]] = {}
    freq: Dict[str, pd.Series] = {}

    # ---- first sweep: per-column counts/distincts + provisional kinds ----
    # spilled unique-tracker columns are decided here (exact cross-epoch
    # duplicate resolution over the disk runs — kernels/unique.resolve);
    # exact_distinct columns additionally carry their exact counts
    unique_status = hostagg.unique.resolve()
    unique_counts = hostagg.unique.distinct_counts()
    kinds: Dict[str, str] = {}
    commons: Dict[str, Dict[str, Any]] = {}
    for spec in plan.specs:
        distinct_approx = False
        if spec.role == "num":
            lane = spec.num_lane
            n_missing = int(momf["n_missing"][lane])
            count = n - n_missing
            if count > 0 and momf["min"][lane] == momf["max"][lane]:
                distinct = 1
            elif spec.base_kind == schema.BOOL:
                distinct = 2 if count else 0
            elif spec.name in unique_counts:
                # exact_distinct: the full-hash stream counted exactly
                distinct = min(unique_counts[spec.name], count)
            else:
                distinct = int(round(hll_est[spec.hash_lane]))
                distinct = max(min(distinct, count), 1 if count else 0)
                distinct_approx = count > 0
        elif spec.role == "date":
            n_missing = hostagg.date_null[spec.name]
            count = n - n_missing
            if spec.name in unique_counts:
                distinct = min(unique_counts[spec.name], count)
            else:
                distinct = int(round(hll_est[spec.hash_lane]))
                distinct = max(min(distinct, count), 1 if count else 0)
                distinct_approx = count > 0
        elif spec.opaque:
            # nested="opaque": count/missing/memory only — there is no
            # value stream, so cardinality is declared unknown (None)
            # rather than estimated
            n_missing = hostagg.cat_null[spec.name]
            count = n - n_missing
            commons[spec.name] = {
                "count": count,
                "n_missing": n_missing,
                "p_missing": n_missing / n if n else 0.0,
                "distinct_count": None,
                "p_unique": None,
                "is_unique": False,
                "distinct_approx": True,
                "memorysize": hostagg.memorysize(spec.name),
            }
            kinds[spec.name] = schema.CAT
            continue
        else:
            n_missing = hostagg.cat_null[spec.name]
            count = n - n_missing
            mg = hostagg.mg[spec.name]
            exact_distinct = mg.distinct_count()
            if exact_distinct is not None:
                distinct = exact_distinct
            elif spec.name in unique_counts:
                # exact_distinct mode: the spill-run union count is the
                # reference's countDistinct answer, exact at any n
                distinct = min(unique_counts[spec.name], count)
            else:
                # MG overflowed — but the duplicate tracker keeps the
                # reference's exact `distinct == count -> UNIQUE` rule
                # (kernels/unique.py); only the OVERFLOW tier is an
                # estimate, and it says so in the report warnings
                est = max(min(int(round(hll_est[spec.hash_lane])), count),
                          1 if count else 0)
                status = unique_status.get(spec.name)
                if status == kunique.UNIQUE:
                    distinct = count        # no duplicate in any row: exact
                elif status == kunique.DUP:
                    distinct = min(est, count - 1)  # a dup exists: < count
                    distinct_approx = True
                else:
                    distinct = est
                    distinct_approx = True
        commons[spec.name] = {
            "count": count,
            "n_missing": n_missing,
            "p_missing": n_missing / n if n else 0.0,
            "distinct_count": distinct,
            "p_unique": distinct / count if count else 0.0,
            # UNIQUE/is_unique are EXACT claims in the reference; an HLL
            # estimate that happens to clamp to `count` must not make them
            "is_unique": count > 0 and distinct == count
            and not distinct_approx,
            "distinct_approx": distinct_approx,
            # Arrow buffer bytes (the streamed-source analogue of the
            # reference's series.memory_usage)
            "memorysize": hostagg.memorysize(spec.name),
        }
        kind = schema.classify(spec.base_kind, distinct, count)
        if kind == schema.UNIQUE and distinct_approx:
            kind = schema.CAT
        kinds[spec.name] = kind

    # ---- correlation rejection over refined-NUM columns ------------------
    num_specs = [s for s in plan.specs
                 if s.role == "num" and kinds[s.name] == schema.NUM]
    num_names = [s.name for s in num_specs]
    lanes = [s.num_lane for s in num_specs]
    corr_df = pd.DataFrame(rho_all[np.ix_(lanes, lanes)],
                           index=num_names, columns=num_names) \
        if len(lanes) >= 2 else pd.DataFrame()
    rejected = schema.reject_by_correlation(corr_df, num_names, config) \
        if len(lanes) >= 2 else {}
    for name in rejected:
        kinds[name] = schema.CORR

    # ---- per-column stats -------------------------------------------------
    for spec in plan.specs:
        name, kind, common = spec.name, kinds[spec.name], commons[spec.name]
        stats = dict(common)
        if kind == schema.NUM:
            stats.update(_numeric_stats(spec.num_lane, spec, momf, quants,
                                        sample_vals, sample_kept, hists,
                                        mad, probes, config,
                                        exact_lanes=exact_lanes))
        elif kind == schema.BOOL:
            # same FIELD SET as the oracle's describe_bool_1d (categorical
            # fields + mean) — the dict contract must not vary by backend
            # (tests/test_field_parity.py); the numeric lane still supplies
            # the exact true/false counts
            lane = spec.num_lane
            n_true = int(round(momf["sum"][lane])) if common["count"] else 0
            vc = pd.Series({True: n_true,
                            False: common["count"] - n_true}
                           ).sort_values(ascending=False)
            freq[name] = vc
            stats["mean"] = float(momf["mean"][lane])
            stats["mode"] = bool(vc.index[0]) if common["count"] else np.nan
            stats["mode_approx"] = False    # from exact true/false counts
            stats["top"] = stats["mode"]
            stats["freq"] = int(vc.iloc[0]) if common["count"] else 0
        elif kind == schema.CAT:
            if spec.opaque:
                # no value stream: the reference fields exist (contract)
                # but carry "unknown", and no freq table renders
                stats["mode"] = None
                stats["top"] = None
                stats["freq"] = 0
            else:
                vc = (recounter.value_counts(name)
                      if recounter is not None
                      else pd.Series({v: c for v, c in
                                      hostagg.mg[name].top(
                                          config.topk_capacity)}))
                vc = vc.sort_values(ascending=False)
                stats["mode"] = vc.index[0] if len(vc) else np.nan
                stats["top"] = stats["mode"]
                stats["freq"] = int(vc.iloc[0]) if len(vc) else 0
                freq[name] = vc.head(config.top_freq)
        elif kind == schema.DATE:
            lo = hostagg.date_min.get(name)
            hi = hostagg.date_max.get(name)
            stats["min"] = pd.Timestamp(lo) if lo is not None else pd.NaT
            stats["max"] = pd.Timestamp(hi) if hi is not None else pd.NaT
            stats["range"] = (stats["max"] - stats["min"]) \
                if lo is not None else pd.NaT
        elif kind == schema.CONST:
            stats["mode"] = _const_mode(spec, momf, hostagg)
        elif kind == schema.UNIQUE:
            stats["first_rows"] = [
                v for v in hostagg.first_values.get(name, []) if v is not None
            ][:5]
        elif kind == schema.CORR:
            other, rho = rejected[name]
            stats.update({"correlation_var": other, "correlation": rho})
        stats["type"] = kind
        variables[name] = stats

    # pass-B bound seeds for the NEXT profile's fused scan: every
    # numeric lane's exact f32 (lo, hi, mean), sealed into artifacts as
    # sketches["bin_seeds"] (artifact/store.build_sketches) so an
    # undrifted source's next fused cycle hits on every lane.  A
    # private key like _phases/_obs: never exported, never rendered.
    if plan.n_num > 0:
        from tpuprof.runtime import singlepass as _sp_seeds
        bin_seeds = _sp_seeds.bin_seeds(plan, momf)
    else:
        bin_seeds = {}
    table = schema.make_table_stats(
        n, variables,
        memorysize=float(sum(hostagg.memorysize(c)
                             for c in hostagg.col_nbytes))
        if hostagg.col_nbytes else np.nan)
    messages = schema.derive_messages(variables, config)
    correlations = {"pearson": corr_df}
    if rho_spear is not None and len(lanes) >= 2:
        spear_df = pd.DataFrame(
            rho_spear[np.ix_(lanes, lanes)], index=num_names,
            columns=num_names)
        # sample-estimated matrices say so (single-pass/streaming tier;
        # ~1/sqrt(K) rank error) — .attrs rides pandas copies
        spear_df.attrs["approx"] = bool(spear_approx)
        correlations["spearman"] = spear_df
    out = {
        "table": table,
        "variables": variables,
        "freq": freq,
        "correlations": correlations,
        "messages": messages,
        "sample": sample_df,
    }
    if bin_seeds:
        out["_bin_seeds"] = bin_seeds
    return out


def _numeric_stats(lane, spec, momf, quants, sample_vals, sample_kept,
                   hists, mad, probes, config,
                   exact_lanes=None) -> Dict[str, Any]:
    out = {
        "mean": float(momf["mean"][lane]),
        "std": float(momf["std"][lane]),
        "variance": float(momf["variance"][lane]),
        "cv": float(momf["cv"][lane]),
        "skewness": float(momf["skewness"][lane]),
        "kurtosis": float(momf["kurtosis"][lane]),
        "sum": float(momf["sum"][lane]),
        "min": float(momf["min"][lane]),
        "max": float(momf["max"][lane]),
        "n_zeros": int(momf["n_zeros"][lane]),
        "n_infinite": int(momf["n_inf"][lane]),
    }
    out["range"] = out["max"] - out["min"]
    n_valid = int(momf["n"][lane]) + int(momf["n_inf"][lane])
    out["p_zeros"] = out["n_zeros"] / n_valid if n_valid else 0.0
    out["p_infinite"] = out["n_infinite"] / n_valid if n_valid else 0.0
    for idx, p in enumerate(probes):
        out[schema.QUANTILE_FIELDS[p]] = float(quants[idx, lane])
    out["iqr"] = out["p75"] - out["p25"]
    # a fused profile with no second scan adopts the exact histogram/
    # MAD only for lanes whose provisional edges held (exact_lanes —
    # runtime/singlepass.py); miss lanes keep the sample tier exactly
    # as two-pass single-pass mode would.  None = every lane exact
    # (the historical meaning of hists/mad being present).
    lane_exact = exact_lanes is None or bool(exact_lanes[lane])
    if mad is not None and lane_exact:
        out["mad"] = float(mad[lane])
    else:  # single-pass mode: MAD from the uniform sample
        v = sample_vals[lane][sample_kept[lane]]
        out["mad"] = float(np.abs(v - v.mean()).mean()) if v.size else np.nan
    if hists is not None and lane_exact:
        out["histogram"] = hists[lane]
    else:  # single-pass mode: sample-scaled histogram
        v = sample_vals[lane][sample_kept[lane]]
        if v.size and np.isfinite(momf["fmin"][lane]) \
                and momf["fmax"][lane] > momf["fmin"][lane]:
            counts, edges = np.histogram(
                v, bins=config.bins,
                range=(momf["fmin"][lane], momf["fmax"][lane]))
            scale = momf["n"][lane] / max(v.size, 1)
            out["histogram"] = ((counts * scale).astype(np.int64), edges)
        else:
            out["histogram"] = None
    out["mini_histogram"] = out["histogram"]
    out["mode"] = _sample_mode(sample_vals[lane], sample_kept[lane])
    # exact iff the sample holds EVERY value of the column (then
    # _sample_mode is a full value-count); otherwise it is a sample
    # estimate and says so — the reference's mode is exact value-counts,
    # and a silent estimate would claim parity it does not have.  A
    # column with infinities is never claimed exact: the sample keeps
    # finite values only, while the reference's value-counts include inf
    # (so inf could BE the true mode).
    out["mode_approx"] = \
        int(sample_kept[lane].sum()) < int(momf["n"][lane]) \
        or int(momf["n_inf"][lane]) > 0
    return out


def _const_mode(spec, momf, hostagg):
    if spec.role == "num":
        v = momf["min"][spec.num_lane]
        if not np.isfinite(v):        # empty column: min is the +inf identity
            return np.nan
        if spec.base_kind == schema.BOOL:
            return bool(v)
        return float(v)
    if spec.role == "date":
        lo = hostagg.date_min.get(spec.name)
        return pd.Timestamp(lo) if lo is not None else pd.NaT
    top = hostagg.mg[spec.name].top(1)
    return top[0][0] if top else np.nan


def _empty_stats(config) -> Dict[str, Any]:
    return {
        "table": schema.make_table_stats(0, {}),
        "variables": {},
        "freq": {},
        "correlations": {"pearson": pd.DataFrame()},
        "messages": [],
        "sample": pd.DataFrame(),
    }
