"""CPU oracle backend — exact numpy/pandas reference semantics.

This engine is the ground truth for every statistic (SURVEY.md §4.1): the
TPU backend must match it to float tolerance (exact stats) or within
published sketch bounds (quantiles/HLL/top-k).  It mirrors the behavior of
the reference's describe()/describe_*_1d() dispatch
(spark_df_profiling/base.py [U], SURVEY.md §2.1) on a pandas DataFrame.

Statistical conventions (chosen so the fused TPU kernel can reproduce them
exactly from merged central moments):

* ``count``       = non-null values;  ``n_missing`` = nulls.
* moments (mean/std/variance/skewness/kurtosis/sum/mad/cv) are over
  *finite* values; ±inf is tallied in ``n_infinite`` (Spark's avg() would
  propagate inf — deliberately diverging so moments stay informative).
* ``min``/``max``/``range`` are over non-null values including ±inf
  (matches Spark min/max).
* ``skewness`` is population skewness g1 = m3 / m2^1.5 and ``kurtosis`` is
  population *excess* kurtosis m4 / m2² − 3 — the same estimators Spark
  SQL's skewness()/kurtosis() aggregates use.
* quantiles use numpy linear interpolation (the oracle is exact where the
  reference's approxQuantile was itself approximate).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import pandas as pd

from tpuprof import schema
from tpuprof.config import ProfilerConfig


def _central_moments(x: np.ndarray):
    """(n, mean, m2, m3, m4) population central moments of a 1-D array."""
    n = x.size
    if n == 0:
        return 0, np.nan, np.nan, np.nan, np.nan
    mean = float(np.mean(x))
    d = x - mean
    m2 = float(np.mean(d * d))
    m3 = float(np.mean(d ** 3))
    m4 = float(np.mean(d ** 4))
    return n, mean, m2, m3, m4


def describe_numeric_1d(series: pd.Series, config: ProfilerConfig,
                        common: Dict[str, Any],
                        vc: pd.Series) -> Dict[str, Any]:
    """Reference: describe_numeric_1d — one Spark agg + approxQuantile +
    histogram per column (SURVEY §3.1 hot loop); here plain numpy."""
    values = series.dropna().to_numpy(dtype=np.float64, na_value=np.nan)
    finite = values[np.isfinite(values)]
    n_inf = int(np.isinf(values).sum())
    stats = dict(common)

    n, mean, m2, m3, m4 = _central_moments(finite)
    variance = m2 * n / (n - 1) if n > 1 else np.nan   # sample variance,
    std = float(np.sqrt(variance)) if n > 1 else np.nan  # ddof=1 (Spark stddev)
    stats.update({
        "mean": mean if n else np.nan,
        "std": std,
        "variance": variance,
        "sum": float(np.sum(finite)) if n else np.nan,
        "mad": float(np.mean(np.abs(finite - mean))) if n else np.nan,
        "cv": std / mean if n > 1 and mean != 0 else np.nan,
        "skewness": m3 / m2 ** 1.5 if n and m2 > 0 else np.nan,
        "kurtosis": m4 / (m2 * m2) - 3.0 if n and m2 > 0 else np.nan,
        "n_zeros": int((values == 0).sum()),
        "n_infinite": n_inf,
    })
    stats["p_zeros"] = stats["n_zeros"] / common["count"] if common["count"] else 0.0
    stats["p_infinite"] = n_inf / common["count"] if common["count"] else 0.0

    vmin = float(np.min(values)) if values.size else np.nan
    vmax = float(np.max(values)) if values.size else np.nan
    stats.update({"min": vmin, "max": vmax, "range": vmax - vmin})

    if finite.size:
        probes = list(config.quantile_probes)
        qs = np.quantile(finite, probes)
        for p, q in zip(probes, qs):
            stats[schema.QUANTILE_FIELDS[p]] = float(q)
        stats["iqr"] = stats["p75"] - stats["p25"]
        counts, edges = np.histogram(finite, bins=config.bins)
        stats["histogram"] = (counts.astype(np.int64), edges)
        stats["mini_histogram"] = stats["histogram"]
    else:
        for field in schema.QUANTILE_FIELDS.values():
            stats[field] = np.nan
        stats["iqr"] = np.nan
        stats["histogram"] = stats["mini_histogram"] = None

    stats["mode"] = vc.index[0] if len(vc) else np.nan
    stats["mode_approx"] = False      # oracle mode is exact value-counts
    return stats


def describe_date_1d(series: pd.Series, common: Dict[str, Any]) -> Dict[str, Any]:
    """Reference: describe_date_1d — min/max (+range) only (SURVEY §2.1)."""
    stats = dict(common)
    values = series.dropna()
    if len(values):
        vmin, vmax = values.min(), values.max()
        stats.update({"min": vmin, "max": vmax, "range": vmax - vmin})
    else:
        stats.update({"min": pd.NaT, "max": pd.NaT, "range": pd.NaT})
    return stats


def describe_categorical_1d(series: pd.Series, common: Dict[str, Any],
                            vc: pd.Series) -> Dict[str, Any]:
    """Reference: describe_categorical_1d — groupBy(col).count() descending,
    the 'top frequencies' table (SURVEY §2.1)."""
    stats = dict(common)
    stats["mode"] = vc.index[0] if len(vc) else np.nan
    stats["top"] = vc.index[0] if len(vc) else np.nan
    stats["freq"] = int(vc.iloc[0]) if len(vc) else 0
    return stats


def describe_bool_1d(series: pd.Series, common: Dict[str, Any],
                     vc: pd.Series) -> Dict[str, Any]:
    stats = describe_categorical_1d(series, common, vc)
    values = series.dropna()
    stats["mean"] = float(values.astype("float64").mean()) if len(values) else np.nan
    stats["mode_approx"] = False      # exact value-counts
    return stats


def describe_constant_1d(series: pd.Series, common: Dict[str, Any]) -> Dict[str, Any]:
    stats = dict(common)
    values = series.dropna()
    stats["mode"] = values.iloc[0] if len(values) else np.nan
    return stats


def describe_unique_1d(series: pd.Series, common: Dict[str, Any]) -> Dict[str, Any]:
    stats = dict(common)
    stats["first_rows"] = series.dropna().head(5).tolist()
    return stats


_UNHASHABLE = (list, dict, set, bytearray, np.ndarray)


def _nested_str(x):
    # ndarray cells (Table.to_pandas turns arrow lists into arrays)
    # print "[1 2]"; going through .tolist() matches the TPU ingest,
    # whose to_pylist() yields python containers ("[1, 2]")
    return str(x.tolist() if isinstance(x, np.ndarray) else x)


def _is_unhashable_col(s: pd.Series) -> bool:
    """True for columns holding unhashable cells (lists/dicts/arrays —
    nested parquet data lands here).  ``infer_dtype`` (one C pass)
    screens first; only mixed/unknown columns pay the per-cell probe."""
    hashable_kinds = frozenset((
        "string", "unicode", "bytes", "empty", "boolean", "integer",
        "floating", "mixed-integer-float", "decimal", "complex",
        "categorical", "date", "datetime", "datetime64", "time",
        "timedelta", "timedelta64", "period", "interval"))
    return s.dtype == object \
        and pd.api.types.infer_dtype(s, skipna=True) \
        not in hashable_kinds \
        and any(issubclass(t, _UNHASHABLE) for t in set(s.map(type)))


def _opaque_stub(series: pd.Series, n: int) -> Dict[str, Any]:
    """nested="opaque" stats for one column: count/missing/memory only,
    cardinality declared unknown — mirrors the TPU backend's opaque
    assembly field-for-field (tests/test_parity-style cross-backend
    agreement)."""
    count = int(series.count())
    return {
        "type": schema.CAT,
        "count": count,
        "n_missing": n - count,
        "p_missing": (n - count) / n if n else 0.0,
        "distinct_count": None,
        "p_unique": None,
        "is_unique": False,
        "distinct_approx": True,
        "memorysize": float(series.memory_usage(index=False, deep=True)),
        "mode": None,
        "top": None,
        "freq": 0,
    }


def _stringify_unhashable(df: pd.DataFrame) -> pd.DataFrame:
    """Columns holding unhashable values (lists/dicts/arrays — nested
    parquet data lands here) profile as their string form: one exotic
    column must not crash the whole profile, and a stringified
    categorical is the useful degradation (distincts/top-k still mean
    something).  Mirrored by the TPU ingest (ingest/arrow.py).  The
    whole column is type-probed (a mixed column whose FIRST value is
    hashable still crashes nunique otherwise); NaN/None stay missing
    (na_action) instead of becoming the string "nan".

    Cost control: see ``_is_unhashable_col``."""
    out = {}
    for col in df.columns:
        s = df[col]
        if _is_unhashable_col(s):
            s = s.map(_nested_str, na_action="ignore")
        out[col] = s
    return pd.DataFrame(out, index=df.index)


def _common_fields(series: pd.Series, n: int) -> Dict[str, Any]:
    count = int(series.count())
    distinct = int(series.nunique(dropna=True))
    return {
        "count": count,
        "n_missing": n - count,
        "p_missing": (n - count) / n if n else 0.0,
        "distinct_count": distinct,
        "p_unique": distinct / count if count else 0.0,
        "is_unique": count > 0 and distinct == count,
        # the oracle counts distincts exactly; the TPU backend sets this
        # when a column's distinct count fell back to the HLL estimate
        "distinct_approx": False,
        "memorysize": float(series.memory_usage(index=False, deep=True)),
    }


def pearson_rejection(df: pd.DataFrame, numeric_cols: List[str],
                      config: ProfilerConfig):
    """Pairwise Pearson over numeric columns + reference rejection rule:
    scanning columns in order, a column whose |ρ| vs an *earlier kept*
    column exceeds corr_reject is flagged CORR (SURVEY §2.1)."""
    if len(numeric_cols) < 2:
        return pd.DataFrame(), {}
    corr = df[numeric_cols].corr(method="pearson")
    return corr, schema.reject_by_correlation(corr, numeric_cols, config)


class CPUStatsBackend:
    """Exact oracle over a pandas DataFrame (SURVEY §3.5 CPUStatsBackend)."""

    name = "cpu"

    def collect(self, source: Any, config: ProfilerConfig) -> Dict[str, Any]:
        # _as_pandas owns the projection (the reference's df.select
        # idiom): unknown names raise BEFORE any file-backed read
        raw = _as_pandas(source, columns=config.columns)
        n = len(raw)
        order = list(raw.columns)
        opaque_stubs: Dict[Any, Dict[str, Any]] = {}
        if config.nested == "opaque":
            keep = []
            for col in raw.columns:
                if _is_unhashable_col(raw[col]):
                    opaque_stubs[col] = _opaque_stub(raw[col], n)
                else:
                    keep.append(col)
            # every kept column was just probed hashable, so the
            # stringify pass would be the identity — skip its re-probe
            df = raw[keep]
        else:
            df = _stringify_unhashable(raw)

        base_kinds: Dict[str, str] = {}
        commons: Dict[str, Dict[str, Any]] = {}
        kinds: Dict[str, str] = {}
        for col in df.columns:
            series = df[col]
            commons[col] = _common_fields(series, n)
            base_kinds[col] = schema.classify_dtype(series)
            kinds[col] = schema.classify(
                base_kinds[col], commons[col]["distinct_count"],
                commons[col]["count"])

        numeric_cols = [c for c in df.columns if kinds[c] == schema.NUM]
        corr_matrix, rejected = pearson_rejection(df, numeric_cols, config)
        for col, (other, rho) in rejected.items():
            kinds[col] = schema.CORR

        variables: Dict[str, Dict[str, Any]] = {}
        freq: Dict[str, pd.Series] = {}
        for col in df.columns:
            series, kind, common = df[col], kinds[col], commons[col]
            if kind in (schema.NUM, schema.CAT, schema.BOOL):
                vc = series.dropna().value_counts()
            if kind == schema.NUM:
                stats = describe_numeric_1d(series, config, common, vc)
            elif kind == schema.CAT:
                stats = describe_categorical_1d(series, common, vc)
                # reference shows the top-N frequencies table; the dict
                # carries what the renderer needs, not the full distribution
                freq[col] = vc.head(config.top_freq)
            elif kind == schema.BOOL:
                stats = describe_bool_1d(series, common, vc)
                freq[col] = vc.head(config.top_freq)
            elif kind == schema.DATE:
                stats = describe_date_1d(series, common)
            elif kind == schema.CONST:
                stats = describe_constant_1d(series, common)
            elif kind == schema.CORR:
                other, rho = rejected[col]
                stats = dict(common)
                stats.update({"correlation_var": other, "correlation": rho})
            else:  # UNIQUE
                stats = describe_unique_1d(series, common)
            stats["type"] = kind
            variables[col] = stats

        if opaque_stubs:
            # stubs slot back into the SOURCE column order
            variables = {c: (opaque_stubs[c] if c in opaque_stubs
                             else variables[c]) for c in order}
        # table total = sum of what each column REPORTS: the profiled
        # frame's (possibly stringified) bytes plus the opaque columns'
        # raw bytes — keeps table vs per-column memory consistent in
        # both modes
        mem_total = float(df.memory_usage(deep=True).sum()) + sum(
            s["memorysize"] for s in opaque_stubs.values())
        table = schema.make_table_stats(n, variables, memorysize=mem_total)
        messages = schema.derive_messages(variables, config)
        correlations = {"pearson": corr_matrix}
        if config.spearman and len(numeric_cols) >= 2:
            correlations["spearman"] = df[numeric_cols].corr(method="spearman")
        if opaque_stubs:
            # the sample keeps the opaque columns (5 head rows of raw
            # values — the reference's sample section, not a decode)
            sample = raw.head(config.sample_rows)
        else:
            sample = df.head(config.sample_rows)
        return {
            "table": table,
            "variables": variables,
            "freq": freq,
            "correlations": correlations,
            "messages": messages,
            "sample": sample,
        }


def _as_pandas(source: Any, columns=None) -> pd.DataFrame:
    """``columns`` projects (in the caller's order), validated up front;
    file-backed reads push it into the scanner so excluded columns'
    pages are never read — the nested-column escape hatch works for the
    oracle too."""
    from tpuprof.ingest.arrow import validate_projection
    if isinstance(source, pd.DataFrame):
        if columns is not None:
            # match on STRINGIFIED labels (the TPU engine sees pyarrow's
            # stringified names, e.g. int labels from header-less CSVs)
            # but index with the originals — source[["0"]] on int labels
            # would KeyError
            validate_projection(columns, source.columns)
            by_str = {str(c): c for c in source.columns}
            return source[[by_str[c] for c in columns]]
        return source
    try:
        import pyarrow as pa
        import pyarrow.dataset as ds
        if isinstance(source, pa.Table):
            if columns is not None:
                return source.select(
                    validate_projection(columns, source.schema.names)
                ).to_pandas()
            return source.to_pandas()
        if isinstance(source, str):
            source = ds.dataset(source)
        if isinstance(source, ds.Dataset):
            if columns is not None:
                return source.to_table(columns=validate_projection(
                    columns, source.schema.names)).to_pandas()
            return source.to_table().to_pandas()
    except ImportError:
        pass
    raise TypeError(f"CPUStatsBackend cannot profile {type(source)!r}")
