"""Exception types.

``InputError`` marks errors caused by what the USER asked for — an
unknown ``columns=`` name, a checkpoint that does not match the current
source/config — as opposed to internal failures.  The CLI reports
InputError as a one-line ``tpuprof: error: ...`` with exit code 2;
everything else keeps its traceback so real bugs stay diagnosable.
Subclasses ValueError, so library callers that caught ValueError before
keep working.
"""


class InputError(ValueError):
    pass
