"""Exception taxonomy (ROBUSTNESS.md "degradation ladder").

``InputError`` marks errors caused by what the USER asked for — an
unknown ``columns=`` name, a checkpoint that does not match the current
source/config — as opposed to internal failures.  The CLI reports
InputError as a one-line ``tpuprof: error: ...`` with exit code 2;
everything else keeps its traceback so real bugs stay diagnosable.
Subclasses ValueError, so library callers that caught ValueError before
keep working.

The fault-tolerance layer (runtime/guard.py, runtime/checkpoint.py)
adds four more, each keeping the base class its call sites historically
raised so existing ``except`` clauses keep working:

* ``TransientError`` (OSError) — the retryable class: flaky reads,
  wire hiccups, injected test faults.  The retry layer also treats raw
  ``OSError`` and Arrow IO/decode errors as transient.
* ``CorruptCheckpointError`` (ValueError — checkpoint loads raised
  ValueError before) — an artifact that fails the CRC/version/shape
  integrity checks, or whose pickle/zip payload is torn.  Never a raw
  ``EOFError``/``UnpicklingError``/``BadZipFile``; the CLI maps it to
  exit code 3.
* ``CorruptArtifactError`` (ValueError) — a persisted stats artifact
  (tpuprof/artifact) failed its CRC/schema integrity checks; the CLI's
  ``diff``/incremental paths map it to exit code 6 so automation can
  tell "artifact rotted" from "inputs were wrong".
* ``PoisonBatchError`` (RuntimeError) — a batch kept failing past the
  retry budget AND the quarantine budget (``max_quarantined``) is
  exhausted or disabled; carries the quarantine manifest so callers can
  report which batches were skipped before giving up.
* ``WatchdogTimeout`` (TimeoutError) — a watched blocking call (device
  drain, multi-host resume barrier) exceeded its configured timeout;
  carries the site and a heartbeat snapshot taken at expiry.  CLI exit
  code 4.

The elastic fleet runtime (runtime/fleet.py) adds two more:

* ``CorruptManifestError`` (ValueError) — a fleet-directory artifact
  (fragment manifest, claim record, contribution part) failed its CRC/
  schema integrity checks.  A torn manifest must never silently
  re-shard a fleet; the CLI maps it to exit code 7.
* ``HostDeathError`` (RuntimeError) — this process's participation in
  the fleet was killed (today: only by the deterministic
  ``host_death:@k`` fault site — tpuprof/testing/faults.py).  The
  fleet layer deletes this host's heartbeat on the way out so
  survivors detect the death immediately; the CLI maps it to exit
  code 8.

The network serving plane (tpuprof/serve/http.py) adds one more:

* ``ServeUnavailableError`` (OSError) — the HTTP edge named by
  ``tpuprof submit --url`` could not be reached at all (connection
  refused, DNS failure, socket timeout).  Distinct from "the daemon
  answered and rejected the job" (an HTTP status) and from "the job
  ran and failed" (the job's own exit code): automation retrying on
  a down edge must be able to branch on THIS without parsing prose;
  the CLI maps it to exit code 9.

The static-analysis suite (tpuprof/analysis — ANALYSIS.md) adds:

* ``LintFindingsError`` (InputError) — `tpuprof lint` found
  unsuppressed invariant violations; shares InputError's exit code 2.

The profile warehouse (tpuprof/warehouse — ARTIFACTS.md) adds two:

* ``WarehouseUnavailableError`` (RuntimeError) — a columnar warehouse
  operation was requested but pyarrow is not importable in this
  environment.  The JSON artifact path is deliberately unaffected (it
  has no pyarrow dependency); the CLI maps this to exit code 10 so a
  wrapper can tell "install pyarrow" from every other failure shape.
* ``CorruptWarehouseError`` (CorruptArtifactError) — a columnar stats
  file (``tpuprof-stats-parquet-v1``) failed its integrity checks:
  truncated/undecodable Parquet bytes, a missing or foreign schema id
  in the file metadata.  Never a raw pyarrow traceback; shares
  CorruptArtifactError's exit code 6 ("a persisted product rotted").

The AOT executable cache (runtime/aot.py — ROADMAP 3(d)) adds one:

* ``CorruptAotCacheError`` (CorruptArtifactError) — an AOT store
  entry failed its integrity checks: truncation at any offset, a CRC
  mismatch, a fingerprint that disagrees with its digest-addressed
  filename, or a serialized executable the deserializer rejects.  The
  acquire seam demotes it LOUDLY to a fresh compile (restarts can be
  slow again but never wrong) and unlinks the entry, so this rarely
  reaches a CLI; when it does (direct store surgery), it shares
  CorruptArtifactError's exit code 6.

The overload layer (serve/scheduler.py + serve/http.py — ISSUE 19)
adds one:

* ``DeadlineExceededError`` (TimeoutError) — a serve job's client-set
  deadline (``X-Tpuprof-Deadline-Ms`` / ``--deadline-ms``) expired
  before the job started running.  The scheduler never starts an
  already-dead job: the mesh time would be wasted on an answer nobody
  is waiting for.  Distinct from ``WatchdogTimeout`` ("the work ran
  too long") — this is "the work never ran because the caller stopped
  caring"; the CLI maps it to exit code 11.

The edge read tier (serve/cache.py ResultCache — ISSUE 16) adds one:

* ``CorruptReadCacheError`` (CorruptArtifactError) — a read-cache
  entry's payload bytes no longer match the CRC recorded at store
  time (in-memory bit rot, or a bug that mutated a cached buffer).
  The cache demotes the entry LOUDLY to a miss — a repeat request can
  cost a recompute but never serve rotten bytes — so this rarely
  escapes the cache; when it does, it shares CorruptArtifactError's
  exit code 6.
"""

from typing import Any, Dict, List, Optional


class InputError(ValueError):
    pass


class TransientError(OSError):
    """An error worth retrying: the operation is idempotent and the
    failure class (I/O hiccup, injected fault) is expected to clear."""


class CorruptCheckpointError(ValueError):
    """A checkpoint artifact failed integrity validation (CRC32,
    truncation, version, undecodable payload)."""


class CorruptArtifactError(ValueError):
    """A stats artifact (tpuprof/artifact store) failed integrity
    validation: truncated/undecodable JSON, a CRC32 mismatch, a missing
    or unsupported schema id, or a torn fold-state payload.  A torn
    artifact must never silently feed a drift report; the CLI maps this
    to exit code 6."""


class CorruptResultError(CorruptArtifactError):
    """A serve result file (tpuprof/serve spool transport) exists but
    does not parse — torn by a crash on a non-atomic filesystem or
    rotted on disk.  ``wait_result`` re-polls past it (the writer may
    still replace it atomically) and raises THIS at the deadline instead
    of a misleading "is the daemon running?" timeout; ``read_result``
    raises it immediately.  Never a raw ``json.JSONDecodeError``.
    Subclasses :class:`CorruptArtifactError`, so it shares exit code 6
    ("a persisted product rotted")."""


class PoisonBatchError(RuntimeError):
    """A batch failed permanently and no quarantine budget remains."""

    def __init__(self, message: str,
                 manifest: Optional[List[Dict[str, Any]]] = None):
        super().__init__(message)
        self.manifest = list(manifest or [])


class CorruptManifestError(ValueError):
    """A fleet-directory artifact (fragment manifest, claim record,
    contribution part — runtime/fleet.py) failed integrity validation:
    truncated/undecodable bytes, a CRC32 mismatch, or a schema the
    fleet cannot trust.  Never a raw ``EOFError``/``UnpicklingError``;
    the CLI maps it to exit code 7."""


class HostDeathError(RuntimeError):
    """This process's fleet participation was deterministically killed
    (the ``host_death:@k`` fault site).  Carries the batch count at
    death so tests can assert the injection point."""

    def __init__(self, site: str, at_call: int):
        super().__init__(
            f"injected host death at {site!r} (call {at_call}) — this "
            "process stops participating in the fleet")
        self.site = site
        self.at_call = at_call


class ServeUnavailableError(OSError):
    """The `tpuprof serve` HTTP edge could not be reached (connection
    refused / DNS failure / socket timeout on ``tpuprof submit --url``).
    The request never entered any queue — safe to retry against the
    same or another edge; the CLI maps it to exit code 9."""


class WarehouseUnavailableError(RuntimeError):
    """A columnar-warehouse operation (tpuprof/warehouse) needs pyarrow
    and this environment cannot import it.  Carries no partial state:
    nothing was written, and the JSON artifact path (which has no
    pyarrow dependency) is unaffected.  The CLI maps this to exit code
    10 — "install pyarrow or set warehouse_format=off" is an
    environment problem, distinct from every data-integrity shape."""


class CorruptWarehouseError(CorruptArtifactError):
    """A columnar stats file (``tpuprof-stats-parquet-v1`` —
    tpuprof/warehouse/columnar.py) failed integrity validation:
    truncated or undecodable Parquet bytes, or a missing/foreign schema
    id in the file metadata.  Never a raw ``pyarrow.lib.ArrowInvalid``;
    history queries walk past a corrupt generation the way checkpoint
    restore walks its chain.  Subclasses :class:`CorruptArtifactError`,
    so it shares exit code 6 ("a persisted product rotted")."""


class CorruptAotCacheError(CorruptArtifactError):
    """An AOT executable-cache entry (runtime/aot.py) failed integrity
    validation: truncated/bit-flipped envelope bytes, a payload CRC
    mismatch, an internal fingerprint that disagrees with the entry's
    digest-addressed filename, or a stored executable
    ``deserialize_and_load`` rejects.  Never a raw pickle/json error;
    the runner-acquire seam catches this, logs loudly, deletes the
    rotten entry, and falls through to the fresh-compile path — a
    corrupt cache may cost a restart its warm start, never its
    correctness.  Subclasses :class:`CorruptArtifactError`, so it
    shares exit code 6 ("a persisted product rotted")."""


class CorruptReadCacheError(CorruptArtifactError):
    """An edge read-cache entry (serve/cache.py ResultCache) failed its
    integrity check: the payload bytes re-hash to a different CRC than
    the one recorded when the entry was stored.  The cache catches
    this, logs loudly, drops the entry, and reports a miss — a rotten
    cache may cost a repeat request its sub-millisecond answer, never
    its correctness (the PR-15 AOT-demote discipline applied to the
    read tier).  Subclasses :class:`CorruptArtifactError`, so it shares
    exit code 6 ("a persisted product rotted")."""


class LintFindingsError(InputError):
    """`tpuprof lint` found unsuppressed invariant violations
    (tpuprof/analysis; ANALYSIS.md).  Subclasses :class:`InputError`
    and shares its exit code 2 — "the tree you asked us to bless is
    not blessable" is an input problem, the same convention argparse
    and config validation already use — so CI gates on exit 2 without
    a new branch."""


class WatchdogTimeout(TimeoutError):
    """A watched blocking call overran its deadline."""

    def __init__(self, site: str, timeout_s: float,
                 heartbeat: Optional[Dict[str, Any]] = None):
        super().__init__(
            f"watchdog: {site!r} exceeded {timeout_s:g}s"
            + (f" (heartbeat: {heartbeat})" if heartbeat else ""))
        self.site = site
        self.timeout_s = timeout_s
        self.heartbeat = heartbeat


class DeadlineExceededError(TimeoutError):
    """A serve job's client-propagated deadline expired before the job
    started running (serve/scheduler.py — ISSUE 19).  The scheduler
    refuses to start an already-dead job; carries how late the job was
    when it reached the front of the queue so operators can size
    ``serve_backlog``/workers.  The CLI maps it to exit code 11."""

    def __init__(self, job_id: str, late_by_s: float):
        super().__init__(
            f"deadline exceeded: job {job_id!r} reached the front of "
            f"the queue {late_by_s:.3f}s past its client deadline — "
            "not started")
        self.job_id = job_id
        self.late_by_s = late_by_s


# the typed taxonomy the CLI (and the crash flight recorder's
# postmortem dumps — obs/blackbox.py) treats as "expected failure
# shapes": one-line message + distinct exit code, no traceback
TYPED_ERRORS = (InputError, CorruptCheckpointError, CorruptArtifactError,
                CorruptManifestError, PoisonBatchError, WatchdogTimeout,
                HostDeathError, ServeUnavailableError, LintFindingsError,
                WarehouseUnavailableError, DeadlineExceededError)

_EXIT_CODES = (
    # order matters: InputError, CorruptCheckpointError,
    # CorruptArtifactError and CorruptManifestError are all ValueErrors
    # — the most specific classes must match first (likewise
    # DeadlineExceededError and WatchdogTimeout are both TimeoutErrors,
    # but siblings — neither shadows the other)
    (CorruptCheckpointError, 3),
    (CorruptArtifactError, 6),
    (CorruptManifestError, 7),
    (DeadlineExceededError, 11),
    (WatchdogTimeout, 4),
    (PoisonBatchError, 5),
    (HostDeathError, 8),
    (ServeUnavailableError, 9),
    (WarehouseUnavailableError, 10),
    (InputError, 2),
)


def exit_code(exc: BaseException) -> int:
    """The CLI's exit code for a typed error (1 for anything else) —
    kept here so wrappers, the CLI, and the postmortem bundle all speak
    one mapping."""
    for cls, code in _EXIT_CODES:
        if isinstance(exc, cls):
            return code
    return 1
