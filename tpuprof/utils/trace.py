"""Tracing / profiling / structured logging (SURVEY.md §5).

* ``trace_to(dir)`` — capture a TensorBoard-viewable ``jax.profiler``
  trace of everything inside the context (the ``--trace`` CLI flag);
  no-op when dir is falsy.
* ``phase_timer(name)`` — wall-clock a pipeline phase (ingest / scan /
  merge / render).  Since the obs subsystem landed this is an alias of
  :func:`tpuprof.obs.span`: same per-phase totals and
  ``get_phase_report()`` contract, plus span events/histograms when
  metrics are on.  Existing call sites keep working unchanged.
* ``log_event(event, **fields)`` — structured single-line JSON records on
  the ``tpuprof`` logger (rows ingested, batches, device util).  Field
  values are coerced via ``default=str`` so numpy scalars / paths /
  timestamps never crash the pipeline they describe.
"""

from __future__ import annotations

import contextlib
import json
import logging
from typing import Iterator, Optional

from tpuprof.obs.spans import get_phase_report, span as phase_timer  # noqa: F401 — re-exported API

logger = logging.getLogger("tpuprof")


@contextlib.contextmanager
def trace_to(trace_dir: Optional[str]) -> Iterator[None]:
    if not trace_dir:
        yield
        return
    import jax
    try:
        with jax.profiler.trace(trace_dir):
            yield
    finally:
        # the trace file exists even when the body raised — say where it
        # is precisely THEN, when someone will want to look at it
        logger.info("tpuprof trace written to %s (view with TensorBoard)",
                    trace_dir)


def log_event(event: str, **fields) -> None:
    logger.debug("%s", json.dumps({"event": event, **fields}, default=str))
