"""Fused pass-A pallas kernel: moments + pairwise-Pearson Gram in ONE read.

Why this kernel exists: on TPU the profile scan is memory-bound, and the
measured cost model of the target device makes every *separate* XLA
reduction re-read the batch from HBM (each pass over a 64k x 200 f32
batch ~ 12ms at the observed ~5 GB/s effective bandwidth, while the MXU
sustains ~46 TFLOP/s).  The XLA formulation of pass A
(kernels/moments.py + kernels/corr.py) issues ~12 reduction passes plus
4 matmuls per batch; this kernel computes the SAME state update with a
single streaming read of the batch:

* VPU, per block: validity masks, centered values d and d², per-column
  sums s1..s4, min/max over non-null values, finite min/max, and the
  n/zeros/inf/missing counts — all accumulated in registers/VMEM;
* MXU, per block: the pairwise-complete Gram blocks
  ``[P|S1] = dᵀ·[d|m]`` and ``[S2;N] = [d²;m]ᵀ·m`` (corr.py semantics)
  at HIGHEST precision, accumulated into VMEM-resident output blocks.

Layout: the batch arrives exactly as the mesh ships it — ``xt`` is
(cols, rows) so the kernel's lane axis is the row axis and NO transpose
is materialized (an XLA transpose is a full extra HBM pass).  The grid
iterates row tiles; output blocks have constant index maps so Mosaic
keeps them VMEM-resident and writes them back once.

Unlike the adaptive-shift XLA path, the fused kernel takes the centering
``shift`` as an input: the backend estimates it host-side from a prefix
of the first batch (any value near the data scale conditions the f32
sums equally well), which also makes every device/batch share one shift
so the collective merge's rebase becomes the identity.

The XLA twin (``update_xla``) keeps CPU meshes and tests running; both
paths produce the moments.py / corr.py state dicts, so merge laws,
checkpointing and finalize are unchanged.  Equivalence is tested in
interpreter mode and against the CPU oracle (tests/test_fused.py).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuprof.kernels import corr as kcorr
from tpuprof.kernels import histogram
from tpuprof.kernels import moments as kmoments
from tpuprof.obs import blackbox as _blackbox
from tpuprof.obs import metrics as _obs_metrics

Array = jnp.ndarray

# ---- device-fold telemetry (OBSERVABILITY.md) ---------------------------
# Dispatch COUNTS are free (host-side increments at the enqueue sites in
# runtime/mesh.py).  Block TIMINGS are not: jax dispatch is async, so a
# wall time requires jax.block_until_ready, which serializes the pipeline
# it measures.  observe_dispatch therefore samples — every Nth dispatch
# (obs.block_sample(), config.metrics_block_sample / --metrics-interval
# wiring) pays one sync and lands in the histogram; N=0 never syncs.
_DISPATCHES = _obs_metrics.counter(
    "tpuprof_device_dispatch_total",
    "device program dispatches, by program (step_a/scan_a/...)")
_BLOCK_SECONDS = _obs_metrics.histogram(
    "tpuprof_device_block_seconds",
    "sampled wall seconds from enqueue to block_until_ready, by program")
_dispatch_seq = [0]     # process-wide sample phase (racy += is fine: the
                        # worst case is a sample skipped or doubled)
# pass-B gets its own kernel-labelled series (OBSERVABILITY.md): the
# legacy/cumulative formulations are runtime-selectable
# (config.pass_b_kernel), so a fleet mixing them must be able to
# attribute dispatch counts/timings to the kernel actually running
_PASS_B_DISPATCHES = _obs_metrics.counter(
    "tpuprof_pass_b_dispatch_total",
    "pass-B device dispatches, by binning kernel (legacy/cumulative)")
_PASS_B_SECONDS = _obs_metrics.histogram(
    "tpuprof_pass_b_dispatch_seconds",
    "sampled pass-B enqueue-to-ready wall seconds, by binning kernel")


def observe_dispatch(program: str, result, batches: int = 1,
                     kernel: str = None):
    """Record one device dispatch (and sometimes time it).  Called by
    MeshRunner at every enqueue site with the dispatch's result pytree;
    returns the result unchanged so call sites stay expressions.
    ``kernel`` (pass-B sites only) additionally feeds the
    kernel-labelled pass-B series."""
    # dispatch milestones land in the crash flight recorder even with
    # metrics off (obs/blackbox.py): a postmortem of a wedged drain
    # shows what the device was last asked to run
    _blackbox.record("dispatch", program=program, batches=batches)
    if not _obs_metrics.enabled():
        return result
    _DISPATCHES.inc(program=program)
    if batches > 1:
        _DISPATCHES.inc(batches, program=f"{program}_batches")
    if kernel is not None:
        _PASS_B_DISPATCHES.inc(kernel=kernel)
    rate = 0
    try:
        from tpuprof import obs
        rate = obs.block_sample()
    except Exception:
        pass
    if rate > 0:
        _dispatch_seq[0] += 1
        if _dispatch_seq[0] % rate == 0:
            import time
            t0 = time.perf_counter()
            jax.block_until_ready(result)
            elapsed = time.perf_counter() - t0
            _BLOCK_SECONDS.observe(elapsed, program=program)
            if kernel is not None:
                _PASS_B_SECONDS.observe(elapsed, kernel=kernel)
    return result

C_ALIGN = 8            # sublane-axis (column) padding multiple — the f32
                       # min sublane tile; 128 alignment is only required
                       # on the LANE axis, so typical column counts
                       # (e.g. 200) need no padding copy at all
# The narrow kernel holds the two (C, 2C) Gram blocks VMEM-resident plus
# ~6 (2C, R) temporaries per block, so the row tile shrinks as columns
# grow and the whole formulation stops fitting VMEM past ~512 columns
# (empirical compile probe on v5e; PERF.md).  Wider tables switch to the
# column-tiled kernel (below) up to MAX_FUSED_COLS_WIDE; MeshRunner
# falls back to the XLA path beyond that.
MAX_FUSED_COLS = 512
MAX_FUSED_COLS_WIDE = 2048     # compile-verified on hardware; beyond
                               # this the XLA path takes over
R_TILE = 1024          # lane-axis (row) tile at narrow widths


def _pick_r_tile(C: int) -> int:
    if C <= 256:
        return 1024
    if C <= 384:
        return 512
    return 256


_HI = jax.lax.Precision.HIGHEST


def _kernel(xt_ref, rv_ref, shift_ref, sums_ref, counts_ref,
            gram1_ref, gram2_ref):
    i = pl.program_id(0)
    x = xt_ref[...]                       # (C, R) — columns are sublanes
    rv = rv_ref[...] > 0                  # (1, R) bool
    shift = shift_ref[...]                # (C, 1)

    masks = _masks(x, rv, shift)
    m, d, d2 = masks[3], masks[4], masks[5]

    # MXU: contract the lane (row) axis of both operands
    dm = jnp.concatenate([d, m], axis=0)            # (2C, R)
    g1 = jax.lax.dot_general(d, dm, (((1,), (1,)), ((), ())),
                             precision=_HI,
                             preferred_element_type=jnp.float32)  # (C, 2C)
    d2m = jnp.concatenate([d2, m], axis=0)          # (2C, R)
    g2 = jax.lax.dot_general(d2m, m, (((1,), (1,)), ((), ())),
                             precision=_HI,
                             preferred_element_type=jnp.float32)  # (2C, C)

    @pl.when(i == 0)
    def _init():
        sums_ref[...] = _stats_identity(sums_ref.shape)
        counts_ref[...] = jnp.zeros_like(counts_ref)
        gram1_ref[...] = jnp.zeros_like(gram1_ref)
        gram2_ref[...] = jnp.zeros_like(gram2_ref)

    _accumulate_stats(sums_ref, counts_ref, x, rv, masks)
    gram1_ref[...] += g1
    gram2_ref[...] += g2


def _masks(x, rv, shift):
    """(isnan, notnull, finite, m, d, d2) for one (C, R) tile — the one
    validity/centering convention shared by every pass-A kernel tier."""
    isnan = jnp.isnan(x)
    notnull = rv & ~isnan                 # non-null (±inf included)
    finite = notnull & ~jnp.isinf(x)
    m = finite.astype(jnp.float32)
    d = jnp.where(finite, x - shift, 0.0)
    return isnan, notnull, finite, m, d, d * d


def _stats_identity(shape):
    """Identity elements for the (C, 8) sums block: 0 for the additive
    lanes, ±inf for min/max (lanes 4/6 min, 5/7 max) — built via iota
    because pallas kernels cannot capture host constants."""
    lane = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return jnp.where((lane == 4) | (lane == 6), jnp.inf,
                     jnp.where((lane == 5) | (lane == 7),
                               -jnp.inf, 0.0)).astype(jnp.float32)


def _accumulate_stats(sums_ref, counts_ref, x, rv, masks) -> None:
    """Fold one tile's per-column sums/min-max/counts into the (C, 8)
    accumulator blocks (lane roles: 0-3 add, 4/6 min, 5/7 max — a
    lane-mask select because slice-assign would lower to an unsupported
    scatter)."""
    isnan, notnull, finite, m, d, d2 = masks
    s1 = jnp.sum(d, axis=1, keepdims=True)
    s2 = jnp.sum(d2, axis=1, keepdims=True)
    s3 = jnp.sum(d2 * d, axis=1, keepdims=True)
    s4 = jnp.sum(d2 * d2, axis=1, keepdims=True)
    minv = jnp.min(jnp.where(notnull, x, jnp.inf), axis=1, keepdims=True)
    maxv = jnp.max(jnp.where(notnull, x, -jnp.inf), axis=1, keepdims=True)
    fmin = jnp.min(jnp.where(finite, x, jnp.inf), axis=1, keepdims=True)
    fmax = jnp.max(jnp.where(finite, x, -jnp.inf), axis=1, keepdims=True)
    sums = jnp.concatenate([s1, s2, s3, s4, minv, maxv, fmin, fmax],
                           axis=1)
    acc = sums_ref[...]
    lane = jax.lax.broadcasted_iota(jnp.int32, acc.shape, 1)
    sums_ref[...] = jnp.where(
        lane < 4, acc + sums,
        jnp.where((lane == 4) | (lane == 6),
                  jnp.minimum(acc, sums), jnp.maximum(acc, sums)))

    i32 = jnp.int32
    n = jnp.sum(finite.astype(i32), axis=1, keepdims=True)
    nz = jnp.sum((notnull & (x == 0.0)).astype(i32), axis=1, keepdims=True)
    ninf = jnp.sum((notnull & jnp.isinf(x)).astype(i32), axis=1,
                   keepdims=True)
    nmiss = jnp.sum((rv & isnan).astype(i32), axis=1, keepdims=True)
    z = jnp.zeros_like(n)
    counts_ref[...] += jnp.concatenate(
        [n, nz, ninf, nmiss, z, z, z, z], axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_tiles(xt: Array, row_valid: Array, shift: Array,
                 interpret: bool = False):
    cols, rows = xt.shape
    cpad = -cols % C_ALIGN
    C = cols + cpad
    r_tile = _pick_r_tile(C)
    rpad = -rows % r_tile
    # row padding is marked invalid via rv; column padding rows are NaN
    xt_p = jnp.pad(xt, ((0, cpad), (0, rpad)), constant_values=jnp.nan)
    rv_p = jnp.pad(row_valid.astype(jnp.float32), (0, rpad))[None, :]
    shift_p = jnp.pad(shift.astype(jnp.float32), (0, cpad))[:, None]
    n_rt = (rows + rpad) // r_tile
    out = pl.pallas_call(
        _kernel,
        grid=(n_rt,),
        in_specs=[
            pl.BlockSpec((C, r_tile), lambda i: (0, i)),
            pl.BlockSpec((1, r_tile), lambda i: (0, i)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((C, 8), lambda i: (0, 0)),
            pl.BlockSpec((C, 8), lambda i: (0, 0)),
            pl.BlockSpec((C, 2 * C), lambda i: (0, 0)),
            pl.BlockSpec((2 * C, C), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, 8), jnp.float32),
            jax.ShapeDtypeStruct((C, 8), jnp.int32),
            jax.ShapeDtypeStruct((C, 2 * C), jnp.float32),
            jax.ShapeDtypeStruct((2 * C, C), jnp.float32),
        ],
        interpret=interpret,
    )(xt_p, rv_p, shift_p)
    sums, counts, g1, g2 = out
    return (sums[:cols], counts[:cols]) + _slice_grams(g1, g2, cols, C)


# ---------------------------------------------------------------------------
# Column-tiled pass A for wide tables
# (MAX_FUSED_COLS < cols <= MAX_FUSED_COLS_WIDE)
# ---------------------------------------------------------------------------
#
# The pairwise Gram is quadratic in columns, so past the narrow kernel's
# VMEM limit the blocks must tile: grid (i, j, r) with rows fastest; each
# (i, j) pair accumulates its (C_T, C_T) P/S1/S2/N output blocks across
# row tiles on the MXU, and the per-column VPU stats ride the j == 0
# visits so every value still feeds them exactly once.  Each row tile is
# read 2·n_ct times (once per partner tile) — at these widths the MXU
# work is the bound, so the extra reads are covered.

C_TILE_W = 256
R_TILE_W = 512


def _kernel_wide(xi_ref, xj_ref, rv_ref, shift_i_ref, shift_j_ref,
                 sums_ref, counts_ref, p_ref, s1_ref, s2_ref, n_ref, *,
                 skip_stats: bool = False):
    j = pl.program_id(1)
    r = pl.program_id(2)
    rv = rv_ref[...] > 0                      # (1, R)

    xi = xi_ref[...]                          # (C_T, R)
    masks_i = _masks(xi, rv, shift_i_ref[...])
    m_i, d_i, d2_i = masks_i[3], masks_i[4], masks_i[5]

    xj = xj_ref[...]
    _, _, _, m_j, d_j, _ = _masks(xj, rv, shift_j_ref[...])

    dn = (((1,), (1,)), ((), ()))
    p_blk = jax.lax.dot_general(d_i, d_j, dn, precision=_HI,
                                preferred_element_type=jnp.float32)
    s1_blk = jax.lax.dot_general(d_i, m_j, dn, precision=_HI,
                                 preferred_element_type=jnp.float32)
    s2_blk = jax.lax.dot_general(d2_i, m_j, dn, precision=_HI,
                                 preferred_element_type=jnp.float32)
    n_blk = jax.lax.dot_general(m_i, m_j, dn, precision=_HI,
                                preferred_element_type=jnp.float32)

    @pl.when(r == 0)
    def _init_grams():
        p_ref[...] = jnp.zeros_like(p_ref)
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)
        n_ref[...] = jnp.zeros_like(n_ref)

    p_ref[...] += p_blk
    s1_ref[...] += s1_blk
    s2_ref[...] += s2_blk
    n_ref[...] += n_blk

    # per-column stats: once per value — only on the j == 0 sweep
    # (skip_stats callers only want the Gram, e.g. the Spearman rank
    # pass; the blocks are still initialized so the discarded outputs
    # are defined)
    @pl.when((j == 0) & (r == 0))
    def _init_stats():
        sums_ref[...] = _stats_identity(sums_ref.shape)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    if not skip_stats:
        @pl.when(j == 0)
        def _stats():
            _accumulate_stats(sums_ref, counts_ref, xi, rv, masks_i)


@functools.partial(jax.jit, static_argnames=("interpret", "skip_stats"))
def _fused_tiles_wide(xt: Array, row_valid: Array, shift: Array,
                      interpret: bool = False, skip_stats: bool = False):
    cols, rows = xt.shape
    cpad = -cols % C_TILE_W
    rpad = -rows % R_TILE_W
    xt_p = jnp.pad(xt, ((0, cpad), (0, rpad)), constant_values=jnp.nan)
    rv_p = jnp.pad(row_valid.astype(jnp.float32), (0, rpad))[None, :]
    shift_p = jnp.pad(shift.astype(jnp.float32), (0, cpad))[:, None]
    C = cols + cpad
    n_ct = C // C_TILE_W
    n_rt = (rows + rpad) // R_TILE_W
    outs = pl.pallas_call(
        functools.partial(_kernel_wide, skip_stats=skip_stats),
        grid=(n_ct, n_ct, n_rt),
        in_specs=[
            pl.BlockSpec((C_TILE_W, R_TILE_W), lambda i, j, r: (i, r)),
            pl.BlockSpec((C_TILE_W, R_TILE_W), lambda i, j, r: (j, r)),
            pl.BlockSpec((1, R_TILE_W), lambda i, j, r: (0, r)),
            pl.BlockSpec((C_TILE_W, 1), lambda i, j, r: (i, 0)),
            pl.BlockSpec((C_TILE_W, 1), lambda i, j, r: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((C_TILE_W, 8), lambda i, j, r: (i, 0)),
            pl.BlockSpec((C_TILE_W, 8), lambda i, j, r: (i, 0)),
            pl.BlockSpec((C_TILE_W, C_TILE_W), lambda i, j, r: (i, j)),
            pl.BlockSpec((C_TILE_W, C_TILE_W), lambda i, j, r: (i, j)),
            pl.BlockSpec((C_TILE_W, C_TILE_W), lambda i, j, r: (i, j)),
            pl.BlockSpec((C_TILE_W, C_TILE_W), lambda i, j, r: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, 8), jnp.float32),
            jax.ShapeDtypeStruct((C, 8), jnp.int32),
            jax.ShapeDtypeStruct((C, C), jnp.float32),
            jax.ShapeDtypeStruct((C, C), jnp.float32),
            jax.ShapeDtypeStruct((C, C), jnp.float32),
            jax.ShapeDtypeStruct((C, C), jnp.float32),
        ],
        interpret=interpret,
    )(xt_p, xt_p, rv_p, shift_p, shift_p)
    sums, counts, P, S1, S2, N = outs
    return (sums[:cols], counts[:cols], P[:cols, :cols],
            S1[:cols, :cols], S2[:cols, :cols], N[:cols, :cols])


def _slice_grams(g1, g2, cols: int, C: int):
    """(P, S1, S2, N) from the two stacked Gram outputs — the one block
    convention shared by the Pearson and Spearman kernels."""
    return (g1[:cols, :cols], g1[:cols, C:C + cols],
            g2[:cols, :cols], g2[C:C + cols, :cols])


def _fold_corr(co: Dict[str, Array], P: Array, S1: Array, S2: Array,
               N: Array) -> Dict[str, Array]:
    """Add one batch's Gram blocks into a corr.py state (shift must be
    pre-set; counts round exactly — batch rows < 2²⁴ in f32)."""
    return {
        "shift": co["shift"],
        "set": jnp.ones((), dtype=jnp.int32),
        "N": co["N"] + jnp.round(N).astype(jnp.int32),
        "S1": co["S1"] + S1,
        "S2": co["S2"] + S2,
        "P": co["P"] + P,
    }


def update(mom: Dict[str, Array], co: Dict[str, Array], xt: Array,
           row_valid: Array, interpret: bool = False
           ) -> Tuple[Dict[str, Array], Dict[str, Array]]:
    """Fold one batch into the moments.py + corr.py states with a single
    pallas pass (column-tiled past MAX_FUSED_COLS).  Requires the
    states' shifts to be pre-set (init with an explicit shift); ``xt``
    is (cols, rows) as the mesh ships batches."""
    tiles = _fused_tiles if xt.shape[0] <= MAX_FUSED_COLS \
        else _fused_tiles_wide
    sums, counts, P, S1, S2, N = tiles(
        xt, row_valid, mom["shift"], interpret=interpret)
    return _fold_mom(mom, sums, counts), _fold_corr(co, P, S1, S2, N)


def update_xla(mom: Dict[str, Array], co: Dict[str, Array], xt: Array,
               row_valid: Array) -> Tuple[Dict[str, Array], Dict[str, Array]]:
    """The XLA twin (CPU meshes, fallback): the pre-existing per-kernel
    formulation, same state contract."""
    x = xt.T
    return (kmoments.update(mom, x, row_valid),
            kcorr.update(co, x, row_valid))


# ---------------------------------------------------------------------------
# Single-pass combined kernel: pass A + provisional-edge histogram
# (profile_passes=fused — runtime/singlepass.py)
# ---------------------------------------------------------------------------
#
# The two-pass structure reads every batch from HBM twice (and, far
# worse e2e, ingests/preps/ships it from the host twice).  With
# provisional bin edges known UP FRONT (artifact-seeded or sketched
# from the first batch), this kernel folds the narrow pass-A state AND
# the histogram/MAD accumulators in literally one read of the tile:
# the same _masks/Gram/stats blocks as _kernel, plus the pass-B tile
# accumulation shared with pallas_hist (hist_tile_* — so both dispatch
# shapes count bit-for-bin identically).  VMEM adds one (C, nbins)
# int32 block and a (C, 1) dev block over _kernel's budget; the row
# tile is halved as margin (conservative pending an on-chip compile
# probe — the chip tunnel is down this round, PERF.md round 10).
#
# Wide tables (cols > MAX_FUSED_AB_COLS) keep two programs: back-to-
# back pallas calls in one XLA module trip Mosaic's scoped-VMEM
# accounting (PERF.md), so the mesh runtime dispatches the column-
# tiled pass-A kernel and the pallas histogram as a PAIRED dispatch
# over one staged placement instead — still one host
# read/prep/transfer per batch, and byte-trivially identical to
# two-pass (the very same compiled programs run).

#: width cap of the combined single-pass kernel.  Starts at the
#: narrow pass-A kernel's limit (the combined kernel shares its tile
#: geometry — identity requires it); an on-chip VMEM probe may lower
#: it independently without touching pass-A behavior.
MAX_FUSED_AB_COLS = MAX_FUSED_COLS

def _kernel_ab(xt_ref, rv_ref, shift_ref, lo_ref, scale_ref, mean_ref,
               sums_ref, counts_ref, gram1_ref, gram2_ref, hist_ref,
               dev_ref, *, nbins: int, hist_kernel: str):
    from tpuprof.kernels import pallas_hist as ph
    i = pl.program_id(0)
    x = xt_ref[...]                       # (C, R)
    rv = rv_ref[...] > 0                  # (1, R)
    shift = shift_ref[...]                # (C, 1)

    masks = _masks(x, rv, shift)
    finite, m, d, d2 = masks[2], masks[3], masks[4], masks[5]

    dm = jnp.concatenate([d, m], axis=0)
    g1 = jax.lax.dot_general(d, dm, (((1,), (1,)), ((), ())),
                             precision=_HI,
                             preferred_element_type=jnp.float32)
    d2m = jnp.concatenate([d2, m], axis=0)
    g2 = jax.lax.dot_general(d2m, m, (((1,), (1,)), ((), ())),
                             precision=_HI,
                             preferred_element_type=jnp.float32)

    hist = ph.HIST_TILES[hist_kernel](x, finite, lo_ref[...],
                                      scale_ref[...], nbins)
    dev = ph.mad_tile(x, finite, mean_ref[...])

    @pl.when(i == 0)
    def _init():
        sums_ref[...] = _stats_identity(sums_ref.shape)
        counts_ref[...] = jnp.zeros_like(counts_ref)
        gram1_ref[...] = jnp.zeros_like(gram1_ref)
        gram2_ref[...] = jnp.zeros_like(gram2_ref)
        hist_ref[...] = jnp.zeros_like(hist_ref)
        dev_ref[...] = jnp.zeros_like(dev_ref)

    _accumulate_stats(sums_ref, counts_ref, x, rv, masks)
    gram1_ref[...] += g1
    gram2_ref[...] += g2
    hist_ref[...] += hist
    dev_ref[...] += dev


@functools.partial(jax.jit,
                   static_argnames=("nbins", "hist_kernel", "interpret"))
def _fused_ab_tiles(xt: Array, row_valid: Array, shift: Array,
                    lo: Array, hi: Array, mean: Array, nbins: int,
                    hist_kernel: str = "cumulative",
                    interpret: bool = False):
    cols, rows = xt.shape
    cpad = -cols % C_ALIGN
    C = cols + cpad
    # the SAME row tile as the separate pass-A kernel — load-bearing
    # for the identity contract: a different tile count would regroup
    # the f32 += accumulation across tiles and the fused moments/Gram
    # sums would drift a ulp from two-pass's.  The hist block rides on
    # top of _kernel's VMEM budget; if the on-chip compile probe (chip
    # tunnel down this round) shows an overflow at the upper widths,
    # lower MAX_FUSED_AB_COLS — over-cap widths take the mesh's paired
    # dispatch, which reuses the two-pass programs verbatim
    r_tile = _pick_r_tile(C)
    rpad = -rows % r_tile
    xt_p = jnp.pad(xt, ((0, cpad), (0, rpad)), constant_values=jnp.nan)
    rv_p = jnp.pad(row_valid.astype(jnp.float32), (0, rpad))[None, :]
    shift_p = jnp.pad(shift.astype(jnp.float32), (0, cpad))[:, None]
    lo_p = jnp.pad(lo.astype(jnp.float32), (0, cpad))[:, None]
    # the SAME scale recipe as pallas_hist.histogram_tiles — bit-equal
    # inputs are what make fused counts byte-identical to pass B's
    width = jnp.maximum(hi - lo, 1e-30).astype(jnp.float32)
    scale_p = jnp.pad(nbins / width, (0, cpad))[:, None]
    mean_p = jnp.pad(mean.astype(jnp.float32), (0, cpad))[:, None]
    n_rt = (rows + rpad) // r_tile
    out = pl.pallas_call(
        functools.partial(_kernel_ab, nbins=nbins,
                          hist_kernel=hist_kernel),
        grid=(n_rt,),
        in_specs=[
            pl.BlockSpec((C, r_tile), lambda i: (0, i)),
            pl.BlockSpec((1, r_tile), lambda i: (0, i)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((C, 8), lambda i: (0, 0)),
            pl.BlockSpec((C, 8), lambda i: (0, 0)),
            pl.BlockSpec((C, 2 * C), lambda i: (0, 0)),
            pl.BlockSpec((2 * C, C), lambda i: (0, 0)),
            pl.BlockSpec((C, nbins), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, 8), jnp.float32),
            jax.ShapeDtypeStruct((C, 8), jnp.int32),
            jax.ShapeDtypeStruct((C, 2 * C), jnp.float32),
            jax.ShapeDtypeStruct((2 * C, C), jnp.float32),
            jax.ShapeDtypeStruct((C, nbins), jnp.int32),
            jax.ShapeDtypeStruct((C, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xt_p, rv_p, shift_p, lo_p, scale_p, mean_p)
    sums, counts, g1, g2, hist, dev = out
    if hist_kernel == "cumulative":
        # differenced OUTSIDE the kernel, exactly as histogram_tiles
        # does for the standalone pass-B program
        from tpuprof.kernels.histogram import counts_from_cumulative
        hist = counts_from_cumulative(hist)
    return ((sums[:cols], counts[:cols])
            + _slice_grams(g1, g2, cols, C)
            + (hist[:cols], dev[:cols, 0]))


def _fold_mom(mom: Dict[str, Array], sums: Array, counts: Array
              ) -> Dict[str, Array]:
    """Fold one batch's (C, 8) sums/counts blocks into a moments.py
    state — the update()/update_with_hist() shared epilogue."""
    return {
        "shift": mom["shift"],
        "n": mom["n"] + counts[:, 0],
        "s1": mom["s1"] + sums[:, 0],
        "s2": mom["s2"] + sums[:, 1],
        "s3": mom["s3"] + sums[:, 2],
        "s4": mom["s4"] + sums[:, 3],
        "minv": jnp.minimum(mom["minv"], sums[:, 4]),
        "maxv": jnp.maximum(mom["maxv"], sums[:, 5]),
        "fmin": jnp.minimum(mom["fmin"], sums[:, 6]),
        "fmax": jnp.maximum(mom["fmax"], sums[:, 7]),
        "n_zeros": mom["n_zeros"] + counts[:, 1],
        "n_inf": mom["n_inf"] + counts[:, 2],
        "n_missing": mom["n_missing"] + counts[:, 3],
    }


def update_with_hist(mom: Dict[str, Array], co: Dict[str, Array],
                     hist: Dict[str, Array], xt: Array, row_valid: Array,
                     lo: Array, hi: Array, mean: Array,
                     hist_kernel: str = "cumulative",
                     interpret: bool = False):
    """Fold one batch into the moments + corr + histogram states with a
    SINGLE pallas read of the batch (narrow widths —
    ``xt.shape[0] <= MAX_FUSED_COLS``; the mesh runtime pairs two
    dispatches beyond that).  ``lo``/``hi``/``mean`` are the
    provisional per-column pass-B inputs (runtime/singlepass.py);
    returns ``(mom, co, hist)``."""
    nbins = hist["counts"].shape[1]
    sums, counts, P, S1, S2, N, hcounts, dev = _fused_ab_tiles(
        xt, row_valid, mom["shift"], lo, hi, mean, nbins,
        hist_kernel=hist_kernel, interpret=interpret)
    hist_out = {"counts": hist["counts"] + hcounts,
                "abs_dev": hist["abs_dev"] + dev}
    return (_fold_mom(mom, sums, counts),
            _fold_corr(co, P, S1, S2, N), hist_out)


def update_with_hist_xla(mom: Dict[str, Array], co: Dict[str, Array],
                         hist: Dict[str, Array], xt: Array,
                         row_valid: Array, lo: Array, hi: Array,
                         mean: Array, hist_kernel: str = "cumulative"):
    """The XLA twin of :func:`update_with_hist` (CPU meshes): the SAME
    per-kernel updates two_pass dispatches, composed into one program —
    one dispatch, one host read, and bit-identical sub-results because
    the sub-graphs are the very functions the separate passes jit."""
    mom_out, co_out = update_xla(mom, co, xt, row_valid)
    if hist_kernel == "cumulative":
        hist_out = histogram.update_cumulative(hist, xt.T, row_valid,
                                               lo, hi, mean)
    else:
        hist_out = histogram.update(hist, xt.T, row_valid, lo, hi, mean)
    return mom_out, co_out, hist_out


# ---------------------------------------------------------------------------
# Spearman grid-rank kernel
# ---------------------------------------------------------------------------
#
# The exact searchsorted rank transform (runtime/mesh.local_step_spear)
# measured ~4 s/batch on the target device — XLA lowers the per-column
# binary search to serialized gathers.  The pallas formulation ranks each
# value against a per-column G-point CDF grid (sample quantiles at
# probes (j+0.5)/G, host-derived from the pass-A row sample) with dense
# VPU compares — rank = (#grid<v + #grid<=v) / 2G — and feeds the ranks
# straight into the same pairwise-complete Gram the Pearson path uses,
# all in one read of the batch.  Rank resolution is 1/G on top of the
# sample's O(1/sqrt(K)) CDF error (documented approximation tier; the
# CPU-mesh path keeps exact average-tie ranks).  Ranks live in [0,1], so
# a constant shift of 0.5 conditions the f32 Gram perfectly.

def _spear_kernel(xt_ref, rv_ref, grid_ref, gram1_ref, gram2_ref, *,
                  n_grid: int):
    i = pl.program_id(0)
    x = xt_ref[...]                       # (C, R)
    rv = rv_ref[...] > 0                  # (1, R)
    finite = rv & jnp.isfinite(x)

    rank = _grid_ranks(x, grid_ref, n_grid)

    m = finite.astype(jnp.float32)
    d = jnp.where(finite, rank - 0.5, 0.0)
    dm = jnp.concatenate([d, m], axis=0)
    g1 = jax.lax.dot_general(d, dm, (((1,), (1,)), ((), ())),
                             precision=_HI,
                             preferred_element_type=jnp.float32)
    d2m = jnp.concatenate([d * d, m], axis=0)
    g2 = jax.lax.dot_general(d2m, m, (((1,), (1,)), ((), ())),
                             precision=_HI,
                             preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _init():
        gram1_ref[...] = jnp.zeros_like(gram1_ref)
        gram2_ref[...] = jnp.zeros_like(gram2_ref)

    gram1_ref[...] += g1
    gram2_ref[...] += g2


@functools.partial(jax.jit, static_argnames=("interpret",))
def _spear_tiles(xt: Array, row_valid: Array, grid: Array,
                 interpret: bool = False):
    cols, rows = xt.shape
    n_grid = grid.shape[1]
    cpad = -cols % C_ALIGN
    C = cols + cpad
    r_tile = _pick_r_tile(C)
    rpad = -rows % r_tile
    xt_p = jnp.pad(xt, ((0, cpad), (0, rpad)), constant_values=jnp.nan)
    rv_p = jnp.pad(row_valid.astype(jnp.float32), (0, rpad))[None, :]
    grid_p = jnp.pad(grid.astype(jnp.float32), ((0, cpad), (0, 0)),
                     constant_values=jnp.inf)
    n_rt = (rows + rpad) // r_tile
    g1, g2 = pl.pallas_call(
        functools.partial(_spear_kernel, n_grid=n_grid),
        grid=(n_rt,),
        in_specs=[
            pl.BlockSpec((C, r_tile), lambda i: (0, i)),
            pl.BlockSpec((1, r_tile), lambda i: (0, i)),
            pl.BlockSpec((C, n_grid), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((C, 2 * C), lambda i: (0, 0)),
            pl.BlockSpec((2 * C, C), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, 2 * C), jnp.float32),
            jax.ShapeDtypeStruct((2 * C, C), jnp.float32),
        ],
        interpret=interpret,
    )(xt_p, rv_p, grid_p)
    return _slice_grams(g1, g2, cols, C)


def _rank_kernel(xt_ref, rv_ref, grid_ref, out_ref, *, n_grid: int):
    """Materialize grid ranks for one row tile: rank in [0,1] where the
    value is finite, NaN elsewhere (the wide tier's stage 1 — the
    VMEM-resident single-pass formulation does not fit past
    MAX_FUSED_COLS, so ranks round-trip HBM and stage 2 reuses the
    column-tiled Gram kernel)."""
    x = xt_ref[...]
    rv = rv_ref[...] > 0
    finite = rv & jnp.isfinite(x)
    rank = _grid_ranks(x, grid_ref, n_grid)
    out_ref[...] = jnp.where(finite, rank, jnp.nan)


def _grid_ranks(x, grid_ref, n_grid: int):
    """(#grid < x + #grid <= x) / 2G — the unrolled compare loop.  The
    compiler's scoped-VMEM demand for this loop scales with the x tile
    area TIMES the grid size (each (C, 1) point slice occupies a full
    128-lane-padded tile), so callers must keep the tile small enough:
    compile-probed on v5e, (256, 128) tiles hold at G=256 where
    (256, 512) overflow (tests/hardware probe; see _rank_tiles)."""
    lt = jnp.zeros_like(x)
    le = jnp.zeros_like(x)
    for j in range(n_grid):
        g = grid_ref[:, j:j + 1]
        lt += (g < x).astype(jnp.float32)
        le += (g <= x).astype(jnp.float32)
    return (lt + le) * (0.5 / n_grid)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _rank_tiles(xt: Array, row_valid: Array, grid: Array,
                interpret: bool = False) -> Array:
    cols, rows = xt.shape
    n_grid = grid.shape[1]
    cpad = -cols % C_TILE_W           # column-tiled like the wide Gram
    C = cols + cpad
    r_tile = 128                      # see _grid_ranks: scoped VMEM for
    rpad = -rows % r_tile             # the compare loop scales with
                                      # tile-area x G; 128 lanes hold
    xt_p = jnp.pad(xt, ((0, cpad), (0, rpad)), constant_values=jnp.nan)
    rv_p = jnp.pad(row_valid.astype(jnp.float32), (0, rpad))[None, :]
    grid_p = jnp.pad(grid.astype(jnp.float32), ((0, cpad), (0, 0)),
                     constant_values=jnp.inf)
    n_ct = C // C_TILE_W
    n_rt = (rows + rpad) // r_tile
    ranks = pl.pallas_call(
        functools.partial(_rank_kernel, n_grid=n_grid),
        grid=(n_ct, n_rt),
        in_specs=[
            pl.BlockSpec((C_TILE_W, r_tile), lambda c, i: (c, i)),
            pl.BlockSpec((1, r_tile), lambda c, i: (0, i)),
            pl.BlockSpec((C_TILE_W, n_grid), lambda c, i: (c, 0)),
        ],
        out_specs=pl.BlockSpec((C_TILE_W, r_tile), lambda c, i: (c, i)),
        out_shape=jax.ShapeDtypeStruct((C, rows + rpad), jnp.float32),
        interpret=interpret,
    )(xt_p, rv_p, grid_p)
    return ranks[:cols, :rows]


def spearman_update(co: Dict[str, Array], xt: Array, row_valid: Array,
                    grid: Array, interpret: bool = False
                    ) -> Dict[str, Array]:
    """Fold one batch of grid ranks into a corr.py state (whose shift
    must be the constant 0.5 — ranks are in [0,1]) — the narrow
    single-pass kernel.  Wider tables run rank_transform and
    spearman_update_wide as TWO programs (mesh runtime dispatches them
    separately: back-to-back pallas calls in one XLA module trip the
    compiler's scoped-VMEM accounting)."""
    P, S1, S2, N = _spear_tiles(xt, row_valid, grid, interpret=interpret)
    return _fold_corr(co, P, S1, S2, N)


def rank_transform(xt: Array, row_valid: Array, grid: Array,
                   interpret: bool = False) -> Array:
    """Stage 1 of the wide Spearman tier: (cols, rows) grid ranks in
    [0,1], NaN where the value is non-finite."""
    return _rank_tiles(xt, row_valid, grid, interpret=interpret)


def spearman_update_wide(co: Dict[str, Array], ranks_t: Array,
                         row_valid: Array, interpret: bool = False
                         ) -> Dict[str, Array]:
    """Stage 2 of the wide Spearman tier: the column-tiled Gram over the
    rank matrix (the kernel's per-column stats sweep is skipped)."""
    half = jnp.full((ranks_t.shape[0],), 0.5, dtype=jnp.float32)
    _, _, P, S1, S2, N = _fused_tiles_wide(ranks_t, row_valid, half,
                                           interpret=interpret,
                                           skip_stats=True)
    return _fold_corr(co, P, S1, S2, N)


# Both grid tiers are calibrated for G <= 256 (see _grid_ranks): the
# wide rank kernel's VMEM tile budget holds at (256, 128)xG=256, and the
# narrow tier's fully-unrolled 2G-compare loop was compile-probed on
# hardware — G=512 at 200 cols did not finish compiling in >9 min while
# G=256 compiles in seconds.  The backend clamps the grid it builds for
# EITHER tier to this and warns (config.spearman_grid accepts higher
# values only for the interpreter/CPU paths).
MAX_SPEAR_GRID = 256
