"""Exact per-column duplicate detection from the host hash stream.

Why this exists: the reference's ``distinct == n → UNIQUE`` type
classification (SURVEY.md §2.1) is EXACT — Spark's countDistinct scans
every value.  tpuprof's categorical distinct counts come from a
Misra-Gries summary while it fits (exact) and the HLL estimate after it
overflows (±1.04/√2¹¹ ≈ 2.3%), and an estimate essentially never equals
``count`` — so a 1M-row all-unique ID column would silently classify CAT
instead of UNIQUE.  This tracker restores the exact answer to the one
question classification needs — "was any value seen twice?" — without
exact distinct counting.

Mechanism: per column, keep every seen 64-bit value hash in sorted
chunks; each batch is sorted (exposing within-batch duplicates) and
probed against the chunks with ``searchsorted``.  The first duplicate
DEMOTES the column to ``DUP`` and frees its storage — for non-unique
columns (the common case) that happens within the first batch or two, so
memory concentrates on genuinely-unique columns only.

Past the in-memory budgets there are two tiers:

* ``spill_dir`` set — the column's consolidated sorted (dup-free) chunk
  spills to a disk RUN and tracking continues: the in-stream probes
  cover the current epoch, and ``resolve()`` k-way-merges every run +
  the live chunks at finalize (memmap range-slices of the uniform hash
  space, so RAM stays bounded at ~128 MB however large n is).  This is
  the Spark-shuffle analogue: EXACT ``UNIQUE``/``DUP`` at any n, with
  disk as the working space (8 B/row/column).
* no ``spill_dir`` — the column demotes to ``OVERFLOW`` and
  classification falls back to the HLL estimate with an explicit
  approximation warning (schema.MSG_APPROX_DISTINCT).

A 64-bit hash collision can mask a truly-unique column as DUP with
probability ~n²/2⁶⁵ (≈3e-8 at n=1e6) — the same collision contract the
HLL plane and the top-k store already accept (ingest/arrow.py).

Merge law (multi-host, SURVEY §4.2): DUP anywhere is definitive; else
OVERFLOW anywhere is OVERFLOW; else the peer's in-memory chunks fold in
through the same probe path and the peer's spilled RUNS are adopted by
path — ``__setstate__`` validated them present on the receiving host
(uuid filenames + size check), which is exactly the shared-spill-dir
deployment (NFS/objFS across a pod).  resolve()'s hash-range k-way
merge then finds cross-host duplicates by the same law as cross-epoch
ones, so exact UNIQUE/DUP survives multi-host at any n.  A peer whose
spill disk is NOT visible here arrives already demoted to OVERFLOW (the
honest bound when runs are unreachable).

Round 8 (hash partitioning + overlapped spill — ISSUE 8): the tracker
routes every hash to one of P partitions by its TOP bits, so each
sort/dedup/spill/resolve operates on a cache-sized partition and
partitions never cross-merge at resolve (P independent unions replace
the global k-way hash-range walk).  Spill runs carry a partition-index
header (RUN_MAGIC) and their writes overlap the scan on the shared io
tier (ingest/prep.py) — distinct counts, UNIQUE/DUP claims and the
demote-on-storage-abort behavior are byte-identical at every partition
and worker count; pre-round-8 headerless runs keep loading.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tpuprof.obs import events as _events
from tpuprof.obs import metrics as _metrics

UNIQUE = "unique"       # no duplicate among all rows seen so far (exact)
DUP = "dup"             # at least one duplicate seen (exact)
OVERFLOW = "overflow"   # gave up within budget — distinct is approximate

# resolve() merges spilled runs in hash-range slices of at most this
# many rows (128 MB of uint64) — RAM stays bounded at any total n
RESOLVE_SLICE_ROWS = 1 << 24

# Partitioned spill-run format (round 8): an 8-byte magic, the writer's
# partition count P and a CRC32 of the partition index (uint32 each),
# then P uint64 per-partition row counts, then the payload — each
# partition's sorted dup-free uint64 values in ascending partition
# order.  The partition id is the hash's TOP bits, so the payload is
# ALSO one globally-sorted array: a reader with a different partition
# count (or a pre-round-8 headerless run, recognized by its exact
# rows*8 size) slices it by searchsorted instead of the index.  Any
# truncation or bit-flip fails the size/CRC checks as CorruptRunError.
RUN_MAGIC = b"TPUQRUN2"
_RUN_HEAD = len(RUN_MAGIC) + 8          # magic + <II (P, crc32(index))

_SPILL_BYTES = _metrics.counter(
    "tpuprof_unique_spill_bytes_total",
    "bytes of sorted hash runs written by the exact-unique tracker")
_SPILL_SECONDS = _metrics.histogram(
    "tpuprof_unique_spill_seconds",
    "wall time per spill-run write (header + tofile), wherever it ran")
_PARTITIONS_G = _metrics.gauge(
    "tpuprof_unique_partitions",
    "hash partitions the exact-unique tracker scatters into")
_SPILL_PENDING_G = _metrics.gauge(
    "tpuprof_unique_spill_pending",
    "spill writes queued on the io tier, not yet durable")


class CorruptRunError(ValueError):
    """A spill-run file failed integrity validation: truncated header
    or payload, partition-index CRC mismatch, or a row count that
    disagrees with the tracker's record.  Never escapes the tracker —
    every read path treats it exactly like a vanished run: the column
    demotes to the honest OVERFLOW (a DUP already in evidence
    survives), so a torn run can cost exactness but never correctness."""

# cleanup() reclaims OTHER tokens' spill files only past this age: a
# crashed chain's post-checkpoint orphans (which no artifact references)
# eventually get swept, while a still-live concurrent writer's runs —
# which cleanup cannot distinguish by name — are never touched young.
# No realistic profile keeps a run file live this long.
ORPHAN_SWEEP_AGE_S = 24 * 3600
# Refresh referenced-run mtimes at most this often (see touch_runs):
# a quarter of the sweep gate keeps live runs provably young while
# paying O(run files) utime syscalls only a handful of times per day.
TOUCH_INTERVAL_S = ORPHAN_SWEEP_AGE_S // 4


class UniqueTracker:
    """Tracks, per column, whether any value hash occurred twice — and,
    in ``count_exact`` mode, the EXACT distinct count at any n.

    Counting mode (config.exact_distinct; needs a spill dir): instead of
    demoting a column on its first duplicate, the tracker keeps folding
    — LAZILY.  Batches append raw (unsorted, dup-included) to the live
    buffer; spills np.unique the buffer into a sorted dup-free run; and
    ``distinct_counts()`` k-way-merges runs + unique'd buffers by hash
    range to count the union exactly.  The UNIQUE/DUP claim is settled
    at resolve by the count-vs-rows-fed comparison (``_fed``), not by
    per-batch probes — dropping the per-batch sort+probe cut the
    wide-numeric (200-column) overhead ~3x (PERF.md round 5).  This
    exceeds the sanctioned HLL deviation (SURVEY §7.2): the reference's
    ``countDistinct`` exactness is restored for every tracked column,
    up to 64-bit hash collisions (~n²/2⁶⁵ — the same collision contract
    the UNIQUE/DUP claims already carry)."""

    def __init__(self, names: Iterable[str], budget_rows: int,
                 total_budget_rows: int,
                 spill_dir: Optional[str] = None,
                 count_exact: bool = False,
                 own_spill_dir: bool = False,
                 partitions: int = 1,
                 spill_workers: int = 0):
        self.budget = int(budget_rows)
        self.total_budget = int(total_budget_rows)
        self.spill_dir = spill_dir
        p = int(partitions)
        if p < 1 or (p & (p - 1)):
            raise ValueError(
                f"partitions must be a power of two >= 1, got {partitions}")
        # every sort/dedup/spill/resolve operates per partition (the
        # hash's top log2(P) bits), so working sets stay cache-sized
        # and partitions never cross-merge — results are identical at
        # every count (a value's partition is a function of the value)
        self._partitions = p
        _PARTITIONS_G.set(p)
        # spill writes in flight on the shared io tier (ingest/prep.py);
        # 0 = write synchronously on the caller's thread.  Queued runs
        # publish into _runs at SUBMIT time (deterministic order at any
        # width); every read/persist path drains first (_drain_spills)
        self._spill_workers = max(int(spill_workers), 0)
        self._spill_pending: List[Tuple] = []   # (future, name, path,
        self._draining = False                  #  rows, parts)
        # True when the DIRECTORY was auto-derived for this profile
        # (config.parity), not user-chosen: cleanup may remove it, not
        # just the run files — a user's (possibly shared) dir is never
        # touched
        self.own_spill_dir = bool(own_spill_dir)
        names = list(names)
        self.status: Dict[str, str] = {}
        self._chunks: Dict[str, List[np.ndarray]] = {}
        self._rows: Dict[str, int] = {}
        self._kind: Dict[str, str] = {}   # hash implementation per column
        self._live = 0          # rows held across all still-UNIQUE columns
        # disk runs per column: [(path, rows)] — each file is a sorted,
        # internally dup-free uint64 array (one spilled epoch).  The
        # filename token is unique per tracker so hosts sharing a spill
        # dir (NFS) can never collide
        import uuid
        self._runs: Dict[str, List[Tuple[str, int]]] = {}
        self._spill_token = uuid.uuid4().hex[:12]
        self._spill_seq = 0
        # run files THIS instance wrote: __del__ removes only these, so
        # GC of a transient unpickled copy (e.g. a failed checkpoint
        # load) can never destroy files a live artifact references
        self._owned: List[str] = []
        # runs demoted while persistent=True: the LAST saved checkpoint
        # still references them by path, so physical deletion is
        # deferred until the next successful save (reap_retired) or
        # cleanup() — a crash in between must leave resume intact
        self._retired: List[str] = []
        # True while a checkpoint artifact references the runs: a CRASH
        # must leave them on disk for resume, so GC cleanup is disabled
        # and only explicit cleanup() (post-assembly) deletes them
        self.persistent = False
        # memo: name -> (state_key, status, count_or_None)
        self._resolve_memo: Dict[str, Tuple] = {}
        self._last_touch = 0.0          # see touch_runs

        disabled = self.budget <= 0 or self.total_budget <= 0
        # per-column: still counting exact distincts (requires storage,
        # so it needs a spill dir to survive the budget)
        counting = bool(count_exact) and spill_dir is not None \
            and not disabled
        self._counting: Dict[str, bool] = {}
        # raw valid rows ever fed per counting column (duplicates
        # included): the lazy tier's UNIQUE claim is count == fed
        self._fed: Dict[str, int] = {}
        # per-column raw-row threshold for the next in-memory compaction
        # (_compact_or_spill); absent => the per-column budget
        self._next_compact: Dict[str, int] = {}
        # counting columns whose live buffer is currently one sorted
        # dup-free chunk (post-compaction): resolve skips the re-unique
        self._clean: set = set()
        for n in names:
            self.status[n] = OVERFLOW if disabled else UNIQUE
            self._chunks[n] = []
            self._rows[n] = 0
            self._kind[n] = ""
            self._runs[n] = []
            self._counting[n] = counting
            self._fed[n] = 0

    def active(self, name: str) -> bool:
        """True while this column's hashes must keep flowing in: either
        the exact no-duplicate claim is still open, or counting mode is
        still accumulating the exact distinct count."""
        return self.status.get(name) == UNIQUE \
            or self._counting.get(name, False)

    def deactivate(self, name: str, status: str = OVERFLOW) -> None:
        """Give up exact tracking for a column (e.g. a batch arrived
        without hashes, so coverage can no longer be guaranteed) —
        counting stops too: a gap in coverage invalidates the count."""
        self._demote(name, status)

    def _demote(self, name: str, status: str) -> None:
        """Stop tracking a column and free its storage.  Counting always
        stops here (every demote path loses count coverage), and a
        DUP verdict ALREADY IN EVIDENCE survives a storage abort:
        demoting a counting column to OVERFLOW (spill failure, hashless
        batch, kind clash) would discard a claim the data on hand
        settles — opting into MORE exactness must never report less.
        The lazy tier settles claims only at resolve, so an abort pays
        one best-effort walk over what is buffered/spilled: a duplicate
        found there is final regardless of the lost future coverage."""
        self._drain_spills()    # settle queued runs before walking them
        if status == OVERFLOW and self._counting.get(name, False) \
                and self.status.get(name) == UNIQUE \
                and (self._chunks.get(name) or self._runs.get(name)):
            try:
                _st, cnt = self._resolve_spilled(name, count=True)
                if cnt is not None and cnt < self._fed.get(name, cnt):
                    status = DUP
            except Exception:
                pass        # best-effort only; OVERFLOW stays honest
        self._counting[name] = False
        if status == OVERFLOW and self.status.get(name) == DUP:
            status = DUP
        self._live -= self._rows[name]
        self._rows[name] = 0
        self._chunks[name] = []
        self.status[name] = status
        self._drop_runs(name)

    def _drop_runs(self, name: str) -> None:
        paths = [p for p, _rows in self._runs.get(name, ())]
        self._runs[name] = []
        if self.persistent:
            # the last saved checkpoint artifact may still reference
            # these files — a crash before the NEXT save must find them
            # on disk or resume silently loses the exact answer the
            # spill tier promised.  Defer deletion to reap_retired()
            # (after the next save) / cleanup()
            self._retired.extend(paths)
            return
        for path in paths:
            try:
                os.remove(path)
            except OSError:
                pass

    def reap_retired(self) -> None:
        """Physically delete runs demoted since the previous checkpoint
        save.  Call only once a NEW artifact — which no longer
        references them — is durably on disk."""
        for path in self._retired:
            try:
                os.remove(path)
            except OSError:
                pass
        self._retired = []

    def touch_runs(self, force: bool = False) -> None:
        """Refresh the mtime of every run this tracker still references.

        cleanup()'s orphan sweep uses file age (> ORPHAN_SWEEP_AGE_S) as
        its only evidence of abandonment; run files are written once and
        never rewritten, so any tracker alive longer than the gate — a
        long checkpoint chain, a stream that never checkpoints, a crash
        chain resumed days later — holds runs an unrelated profile's
        sweep of the shared dir could legally destroy.  update() /
        resolve() / distinct_counts() call this, rate-limited to
        TOUCH_INTERVAL_S so the common case is one clock read; restore
        forces a pass so inherited runs are restamped before any sweep
        can race it.

        _retired runs are touched too: the LAST saved artifact still
        references them by path until the next save's reap, and a crash
        resume needs them intact.

        Residual exposure (documented bound): only running code can
        refresh an mtime, so a tracker that receives NO calls for longer
        than ORPHAN_SWEEP_AGE_S - TOUCH_INTERVAL_S (>= 18 h fully idle)
        cannot defend its files; a concurrent profile's sweep may then
        reclaim them, and the column degrades honestly (OVERFLOW /
        estimate) on next access."""
        import time
        now = time.time()
        if not force and now - self._last_touch < TOUCH_INTERVAL_S:
            return
        self._last_touch = now
        for path in self._retired:
            try:
                os.utime(path, None)
            except OSError:
                pass
        for runs in self._runs.values():
            for path, _rows in runs:
                try:
                    os.utime(path, None)
                except OSError:
                    pass

    def _compact_or_spill(self, name: str) -> bool:
        """Budget relief for the lazy tier: dedup the raw buffer in
        memory FIRST and spill ONLY when the column's DISTINCT count
        exceeds the per-column budget (or global memory pressure
        demands it) — exactly the probed tier's spill policy, so dup-
        and mid-cardinality columns keep its near-zero disk footprint
        instead of shedding a mostly-redundant run per budget of raw
        rows.  A kept buffer re-compacts only after growing budget//2
        raw rows past its distinct size (_next_compact), bounding the
        re-sort churn at amortized O(log) per value."""
        u = self._compact_buffer(name)
        if self._rows[name] <= self.budget \
                and self._live <= self.total_budget:
            return True
        self._next_compact[name] = self.budget
        return bool(self.spill_dir and self._spill(name, merged=u))

    def _pshift(self) -> np.uint64:
        """Right-shift that maps a hash to its partition id (top bits)."""
        return np.uint64(64 - (self._partitions.bit_length() - 1))

    def _partition_unique(self, h: np.ndarray) -> List[np.ndarray]:
        """Radix-scatter ``h`` by its top bits, then np.unique each
        partition — the canonical compacted form: a list of sorted
        dup-free arrays in ascending partition order, whose
        concatenation is globally sorted (the partition id IS the
        hash's top bits).

        Implementation note (measured, PERF.md round 8): because the
        partition id is the value's TOP bits, sorting the values IS the
        radix scatter — the sort's leading comparisons group by
        partition — and one in-place sort + dedup + boundary split beat
        an explicit pid-argsort scatter followed by per-partition sorts
        at every buffer size this box could hold (the explicit scatter
        re-pays its gather).  The partition structure materializes as
        zero-copy views of the sorted buffer."""
        if h.size == 0:
            return [h]
        # h is the caller's freshly-concatenated (owned, private)
        # buffer: sort in place — np.unique would sort a COPY
        h.sort()
        keep = np.empty(h.size, dtype=bool)
        keep[0] = True
        np.not_equal(h[1:], h[:-1], out=keep[1:])
        u = h if bool(keep.all()) else h[keep]
        return self._split_sorted(u)

    def _split_sorted(self, u: np.ndarray) -> List[np.ndarray]:
        """Partition views of an already globally-sorted dup-free array
        (partition boundaries are just searchsorted probes)."""
        P = self._partitions
        if P == 1 or u.size == 0:
            return [u]
        step = (1 << 64) // P
        cuts = np.searchsorted(
            u, np.arange(1, P, dtype=np.uint64) * np.uint64(step))
        return [part for part in np.split(u, cuts) if part.size]

    def _compact_buffer(self, name: str) -> Optional[List[np.ndarray]]:
        """Dedup the live buffer into the canonical partitioned form
        (sorted dup-free arrays, ascending partition order — see
        ``_partition_unique``), maintaining the _rows/_live/_clean/
        _next_compact bookkeeping — the single home for this
        bookkeeping (compaction, the canonical memo key, and spill
        staging all route through here)."""
        chunks = self._chunks.get(name) or []
        if not chunks:
            return None
        if name in self._clean:
            return chunks
        parts = self._partition_unique(np.concatenate(chunks))
        size = sum(int(p.size) for p in parts)
        self._live -= self._rows[name] - size
        self._rows[name] = size
        self._chunks[name] = parts
        self._next_compact[name] = size + \
            max(self.budget // 2, 1)
        self._clean.add(name)
        return parts

    def _spill(self, name: str,
               merged: Optional[Sequence[np.ndarray]] = None) -> bool:
        """Write the column's consolidated in-memory chunks to a disk
        run (the partitioned v2 format — see RUN_MAGIC) and free the
        memory; tracking continues in a fresh epoch.  ``merged`` skips
        the re-dedup when the caller just computed the canonical parts
        (_compact_or_spill).  With ``spill_workers > 0`` the write is
        queued on the shared io tier (ingest/prep.py) and the scan
        keeps folding — the run publishes into ``_runs`` at submit time
        (deterministic order at any width) and every read/persist path
        drains the queue first; a failed overlapped write is settled at
        drain exactly like a synchronous failure (the unwritten values
        return to the live buffer, then the column demotes through the
        same best-effort walk)."""
        if merged is None:
            merged = self._partition_unique(
                np.concatenate(self._chunks[name]))
        elif isinstance(merged, np.ndarray):
            merged = self._split_sorted(merged)
        rows = sum(int(p.size) for p in merged)
        path = os.path.join(
            self.spill_dir,
            f"tpuprof-uniq-{self._spill_token}-{self._spill_seq}.u64")
        self._spill_seq += 1
        if self._spill_workers > 0:
            # bounded, in-order completion like the two-tier preparer:
            # settle the OLDEST write once the window fills, so RAM
            # holds at most spill_workers freed-but-unwritten buffers
            while len(self._spill_pending) >= self._spill_workers:
                self._settle_spill(self._spill_pending.pop(0))
                _SPILL_PENDING_G.set(len(self._spill_pending))
            if not (self.status.get(name) == UNIQUE
                    or self._counting.get(name, False)):
                # a settled failure just demoted THIS column — nothing
                # left to spill (its buffers were walked and freed)
                return True
            from tpuprof.ingest.prep import submit_io
            parts = list(merged)
            fut = submit_io(lambda: self._write_run(path, parts, name),
                            self._spill_workers)
            self._spill_pending.append((fut, name, path, rows, parts))
            _SPILL_PENDING_G.set(len(self._spill_pending))
        else:
            try:
                self._write_run(path, merged, name)
            except OSError as exc:
                self._spill_write_failed(name, path, exc)
                return False
        self._runs[name].append((path, rows))
        self._owned.append(path)
        self._live -= self._rows[name]
        self._rows[name] = 0
        self._chunks[name] = []
        self._clean.discard(name)
        return True

    def _write_run(self, path: str, parts: Sequence[np.ndarray],
                   name: str) -> None:
        """Serialize one partitioned run: header (magic, P, index CRC),
        per-partition row counts, then each partition's sorted values.
        Runs on the io tier for overlapped spills; OSError propagates
        to the caller/settler, which owns the demote semantics."""
        import time
        t0 = time.perf_counter()
        shift = self._pshift()
        counts = np.zeros(self._partitions, dtype="<u8")
        for part in parts:
            if part.size:
                counts[int(part[0] >> shift)
                       if self._partitions > 1 else 0] = part.size
        index = counts.tobytes()
        header = RUN_MAGIC + struct.pack(
            "<II", self._partitions, zlib.crc32(index)) + index
        # two attempts: a concurrent profile sharing the dir (e.g. the
        # fixed parity dir) may rmdir it between our makedirs and the
        # write — recreating once makes that race harmless
        for attempt in (0, 1):
            os.makedirs(self.spill_dir, exist_ok=True)
            try:
                with open(path, "wb") as fh:
                    fh.write(header)
                    for part in parts:
                        np.ascontiguousarray(part).tofile(fh)
                break
            except OSError:
                try:
                    os.remove(path)
                except OSError:
                    pass
                if attempt:
                    raise
        rows = int(counts.sum())
        nbytes = len(header) + rows * 8
        seconds = time.perf_counter() - t0
        _SPILL_BYTES.inc(nbytes)
        _SPILL_SECONDS.observe(seconds)
        _events.emit("unique_spill", column=name, rows=rows,
                     bytes=nbytes, seconds=round(seconds, 6),
                     queued=self._spill_workers > 0)

    def _spill_write_failed(self, name: str, path: str,
                            exc: BaseException) -> None:
        """Shared failure report for sync and overlapped spill writes:
        the user explicitly asked for exactness — a full/unwritable
        spill disk must not demote silently; also reap the partial
        file so the spill dir stays clean."""
        import logging
        logging.getLogger("tpuprof").warning(
            "unique spill to %s failed (%s): column %r falls back "
            "to the HLL distinct estimate", path, exc, name)
        try:
            os.remove(path)
        except OSError:
            pass

    def _settle_spill(self, entry: Tuple) -> None:
        """Wait for one queued spill write.  Success drops the buffer
        references (the run on disk now carries the values); failure
        re-files the unwritten values into the live buffer and demotes
        through the SAME path a synchronous spill failure takes, so the
        demote-on-storage-abort contract (a DUP in evidence survives;
        anything else degrades to the honest OVERFLOW) is identical at
        any worker count."""
        fut, name, path, rows, parts = entry
        try:
            fut.result()
            return
        except OSError as exc:
            self._spill_write_failed(name, path, exc)
        self._runs[name] = [r for r in self._runs[name] if r[0] != path]
        if path in self._owned:
            self._owned.remove(path)
        self._retired = [p for p in self._retired if p != path]
        if self.status.get(name) == UNIQUE or self._counting.get(name):
            # restore the unwritten values so the best-effort claim
            # walk below sees exactly what the sync path would have
            self._chunks[name].extend(np.asarray(p) for p in parts)
            self._clean.discard(name)
            self._rows[name] += rows
            self._live += rows
            self._overflow_warn(name)
            self._demote(name, OVERFLOW)

    def _drain_spills(self) -> None:
        """Block until every queued spill write settled (oldest first).
        Re-entrant-safe: a settle's demote walk re-enters through
        _resolve_spilled, which must not re-order the queue."""
        if self._draining or not self._spill_pending:
            return
        self._draining = True
        try:
            while self._spill_pending:
                self._settle_spill(self._spill_pending.pop(0))
        finally:
            self._draining = False
            _SPILL_PENDING_G.set(0)

    def flush_spills(self) -> None:
        """Public drain: block until every queued spill run is durably
        on disk (failed writes demote their columns exactly as a
        synchronous failure would).  Checkpoint/artifact writers call
        this so a saved artifact never references an unwritten run —
        pickling does it implicitly (__getstate__), this makes the
        ordering explicit."""
        self._drain_spills()

    def update(self, name: str, hashes: np.ndarray,
               hash_kind: str = "") -> None:
        """Fold one batch's valid-row hashes (duplicates included) in.

        ``hash_kind`` names the implementation that produced the hashes
        ("native" | "pandas"); the same value hashes DIFFERENTLY under
        the two, so a column whose stream switches implementations can
        no longer be compared exactly and demotes to OVERFLOW."""
        self.touch_runs()       # liveness signal: keep runs sweep-safe
        counting = self._counting.get(name, False)
        if self.status.get(name) != UNIQUE and not counting:
            return
        h = np.asarray(hashes, dtype=np.uint64)
        if not h.size:
            return
        if hash_kind:
            if self._kind[name] and self._kind[name] != hash_kind:
                self._demote(name, OVERFLOW)
                return
            self._kind[name] = hash_kind
        if counting:
            # LAZY exact-count tier (round 5): append the raw hashes and
            # defer every sort/dedup to spill time and the resolve walk.
            # Counting mode never benefits from incremental duplicate
            # detection — the count AND the UNIQUE/DUP claim both fall
            # out of the union count (claim == no-dup <=> count equals
            # rows fed, tracked in _fed).  The per-batch sort+probe this
            # replaces made wide-numeric exact_distinct 14x the sketch
            # tier (PERF.md round 5).
            if h.base is not None:
                h = h.copy()    # own the memory: a view pins its parent
            self._fed[name] += h.size
            self._chunks[name].append(h)
            self._clean.discard(name)
            self._rows[name] += h.size      # RAW rows buffered (lazy
            self._live += h.size            # tier), not distinct rows
            if self._rows[name] > self._next_compact.get(name,
                                                         self.budget) \
                    or self._live > self.total_budget:
                if not self._compact_or_spill(name):
                    self._overflow_warn(name)
                    self._demote(name, OVERFLOW)
            return
        sh = np.sort(h)
        # within-batch dedup (chunks store DISTINCT values, so memory
        # tracks cardinality, not row count)
        dup = False
        if sh.size > 1:
            keep = np.empty(sh.size, dtype=bool)
            keep[0] = True
            np.not_equal(sh[1:], sh[:-1], out=keep[1:])
            if not keep.all():
                dup = True
                sh = sh[keep]
        # probe the live chunks: detects duplicates for the UNIQUE claim
        # and discards already-stored values (keeps chunks mutually
        # dup-free, so the live rows count IS the epoch's distinct count)
        for c in self._chunks[name]:
            pos = np.searchsorted(c, sh)
            inb = pos < c.size
            hit = np.zeros(sh.size, dtype=bool)
            hit[inb] = c[pos[inb]] == sh[inb]
            if hit.any():
                dup = True
                sh = sh[~hit]
        if dup:
            self._demote(name, DUP)
            return
        if not sh.size:
            return
        self._chunks[name].append(sh)
        self._clean.discard(name)       # no longer the canonical form
        self._rows[name] += sh.size
        self._live += sh.size
        if self._rows[name] > self.budget or self._live > self.total_budget:
            if not (self.spill_dir and self._spill(name)):
                self._overflow_warn(name)
                self._demote(name, OVERFLOW)
            return
        if len(self._chunks[name]) > 8:
            # keep the probe loop short: fold the chunk list back into
            # one sorted array (amortized O(n log n) per column)
            self._chunks[name] = [np.sort(np.concatenate(
                self._chunks[name]))]

    def _overflow_warn(self, name: str) -> None:
        if not self.spill_dir:
            import logging
            logging.getLogger("tpuprof").warning(
                "column %r exceeded the exact-UNIQUE tracking "
                "budget (unique_track_rows=%d): its distinct "
                "count falls back to the HLL estimate.  Set "
                "unique_spill_dir (CLI: --unique-spill-dir) to "
                "keep the classification exact at any size "
                "(disk cost: 8 bytes/row)", name, self.budget)

    def resolve(self) -> Dict[str, str]:
        """Final per-column statuses, with spilled columns decided
        EXACTLY: each run is internally dup-free and so is the live
        chunk set, so only cross-epoch duplicates remain — found by
        merging all runs + live chunks.  Hashes are uniform, so the
        merge walks fixed ranges of the hash space via memmap'd
        ``searchsorted`` windows: RAM stays ≤ RESOLVE_SLICE_ROWS rows
        however large the column.  Non-destructive (streaming snapshots
        may call it repeatedly); per-column results are memoized on the
        (runs, live-rows) state."""
        self._drain_spills()    # settle statuses before reporting them
        self.touch_runs()       # liveness signal: keep runs sweep-safe
        out = {}
        for name, st in self.status.items():
            if self._counting.get(name, False) and st != OVERFLOW:
                # lazy tier: the claim IS the count comparison — no dup
                # was ever folded iff the union count equals the raw
                # rows fed.  One walk serves claim and count (memoized
                # for distinct_counts).
                cnt = self._resolve_spilled(name, count=True)[1]
                if cnt is None:
                    # a run vanished — the exact COUNT is gone, but a
                    # DUP claim already in evidence (merged-in peer,
                    # restored artifact) is final and survives
                    out[name] = DUP if st == DUP else OVERFLOW
                elif st == DUP or cnt < self._fed.get(name, cnt):
                    out[name] = DUP
                else:
                    out[name] = UNIQUE
            elif st == UNIQUE and self._runs.get(name):
                out[name] = self._resolve_spilled(name, count=False)[0]
            else:
                out[name] = st
        return out

    def distinct_counts(self) -> Dict[str, int]:
        """EXACT distinct counts for columns still in counting mode
        (count_exact), at any n: the union of the (dup-free) spilled
        runs and the np.unique of the lazy tier's raw live buffers, via
        the hash-range k-way merge.  Non-destructive and memoized
        alongside the claim."""
        self._drain_spills()    # settle statuses before reporting them
        self.touch_runs()       # liveness signal: keep runs sweep-safe
        out: Dict[str, int] = {}
        for name, counting in self._counting.items():
            if not counting or self.status.get(name) == OVERFLOW:
                continue
            _st, count = self._resolve_spilled(name, count=True)
            if count is not None:
                out[name] = count
        return out

    def _canonical_key(self, name: str) -> Tuple:
        """Compact the lazy buffer to its canonical dedup'd form and
        return the memo key describing the column's state.

        Compaction first: the memo key must describe the canonical
        state, or a walk would memoize under a pre-compaction key that
        never matches again.  _fed is in the key because the lazy tier
        broke _rows's monotonicity — a compaction can shrink _rows back
        onto a value an earlier snapshot memoized with fewer values
        seen, and (runs, rows) alone would serve that stale count; _fed
        is monotone, so any new data invalidates.  Deterministic across
        hosts after a merge (chunks fold in a fixed order), which is
        what lets seed_resolution's injected verdicts match peers'
        locally-computed keys."""
        if self._counting.get(name, False):
            self._compact_buffer(name)
        return (tuple(self._runs[name]), self._rows[name],
                self._fed.get(name, 0))

    def _run_layout(self, path: str, rows: int
                    ) -> Tuple[int, Optional[np.ndarray]]:
        """Validate a run file and return ``(payload byte offset,
        per-partition prefix offsets or None)``.  Offsets come straight
        from the v2 header when the writer's partition count matches
        this tracker's; a foreign count — or a pre-round-8 headerless
        run, recognized by its exact ``rows * 8`` size — returns None
        and the reader slices the (globally sorted) payload by
        searchsorted instead.  Any truncation, bit-flip or row-count
        disagreement raises :class:`CorruptRunError`; a vanished file
        raises OSError.  Both are handled identically by every caller
        (honest demote)."""
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            head = fh.read(_RUN_HEAD)
            if head[:len(RUN_MAGIC)] != RUN_MAGIC:
                # no magic: either a pre-round-8 headerless run (whose
                # only validation was — and remains — its exact size)
                # or corruption.  The magic test runs FIRST: a v2 run
                # truncated to exactly rows*8 bytes must never pass as
                # legacy (a legacy run starting with the magic bytes
                # has probability 2^-64 — the same collision contract
                # the hashes themselves carry).
                if size == rows * 8:
                    return 0, None
                raise CorruptRunError(
                    f"spill run {path!r}: unrecognized layout "
                    f"({size} bytes for {rows} recorded rows)")
            if len(head) < _RUN_HEAD:
                raise CorruptRunError(
                    f"spill run {path!r}: truncated header")
            run_p, crc = struct.unpack("<II", head[len(RUN_MAGIC):])
            if not 1 <= run_p <= 1 << 16:
                raise CorruptRunError(
                    f"spill run {path!r}: implausible partition "
                    f"count {run_p}")
            index = fh.read(8 * run_p)
        if len(index) != 8 * run_p or zlib.crc32(index) != crc:
            raise CorruptRunError(
                f"spill run {path!r}: partition index corrupt "
                "(truncated or CRC mismatch)")
        counts = np.frombuffer(index, dtype="<u8")
        offset = _RUN_HEAD + 8 * run_p
        if int(counts.sum()) != rows or size != offset + rows * 8:
            raise CorruptRunError(
                f"spill run {path!r}: payload truncated or row count "
                f"mismatch ({size} bytes, {rows} recorded rows)")
        if run_p != self._partitions:
            return offset, None             # readable, slice by search
        prefix = np.zeros(run_p + 1, dtype=np.int64)
        prefix[1:] = np.cumsum(counts)
        return offset, prefix

    @staticmethod
    def _union_ranged(parts: List[np.ndarray], lo: int, hi: int,
                      n_sub: int, count: bool) -> Tuple[bool, int]:
        """Distinct count + duplicate detection across sorted dup-free
        arrays restricted to hashes in ``[lo, hi]``, in ``n_sub``
        bounded sub-ranges (RAM <= RESOLVE_SLICE_ROWS rows however
        large the column).  Returns (dup_found, distinct); when a dup
        settles the claim and no count is wanted, remaining sub-ranges
        are skipped (the count half of the return is then partial and
        the caller discards it — same contract the round-5 walk had)."""
        dup = False
        distinct = 0
        step = (hi - lo + 1) // n_sub
        for k in range(n_sub):
            slo = np.uint64(lo + k * step)
            shi = np.uint64(lo + (k + 1) * step - 1) \
                if k + 1 < n_sub else np.uint64(hi)
            sub = []
            for a in parts:
                i = int(np.searchsorted(a, slo, side="left"))
                j = int(np.searchsorted(a, shi, side="right"))
                if j > i:
                    sub.append(np.asarray(a[i:j]))
            if len(sub) < 2:
                distinct += sub[0].size if sub else 0
                continue            # one source can't cross-duplicate
            s = np.sort(np.concatenate(sub))
            if s.size > 1:
                news = int((s[1:] != s[:-1]).sum()) + 1
            else:
                news = int(s.size)
            if news != s.size:
                dup = True
                if not count:
                    return dup, distinct    # claim settled
            distinct += news
        return dup, distinct

    def _resolve_spilled(self, name: str, count: bool = False
                         ) -> Tuple[str, Optional[int]]:
        self._drain_spills()    # a queued run is not yet readable
        if self._counting.get(name, False) and not self._runs[name] \
                and name not in self._clean and self._chunks[name]:
            # Count-only fast path — the wide-shape common case once
            # the RAM-derived budget swallows the whole stream: no runs
            # to merge, so the union is one in-place sort + adjacent-
            # diff count over the raw buffer.  Skips canonicalization
            # (its dedup extract pays an extra copy the count never
            # needs) and the partition walk (one source per partition
            # has nothing to cross-merge).  Memo key: fed is monotone,
            # so any new data invalidates; a later compaction changes
            # _rows and merely re-walks to the same answer.
            key = ((), self._rows[name], self._fed.get(name, 0))
            memo = self._resolve_memo.get(name)
            if memo is not None and memo[0] == key:
                return memo[1], memo[2]
            s = np.concatenate(self._chunks[name])
            s.sort()
            if s.size > 1:
                distinct = int((s[1:] != s[:-1]).sum()) + 1
            else:
                distinct = int(s.size)
            status = UNIQUE if distinct == s.size else DUP
            self._resolve_memo[name] = (key, status, distinct)
            return status, distinct
        key = self._canonical_key(name)
        memo = self._resolve_memo.get(name)
        if memo is not None and memo[0] == key \
                and not (count and memo[2] is None
                         and memo[1] != OVERFLOW):
            return memo[1], memo[2]
        # every source is one sorted dup-free array: a memmap'd run
        # payload (with direct per-partition offsets when its header's
        # partition count matches ours) or a live canonical part
        sources: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
        for path, rows in self._runs[name]:
            try:
                offset, prefix = self._run_layout(path, rows)
                mm = np.memmap(path, dtype=np.uint64, mode="r",
                               offset=offset, shape=(rows,))
            except (OSError, ValueError):
                # a run vanished or rotted (tmp cleaner, resume on
                # another box, torn write): the exact claim is gone —
                # honest fallback.  Demote fully: the lazy tier's raw
                # buffers must not survive into the probed paths, whose
                # invariants (sorted, dup-free chunks) they violate
                # (counting is flipped off FIRST so _demote skips its
                # best-effort walk — a partial union would settle
                # false DUPs)
                self._counting[name] = False
                self._resolve_memo[name] = (key, OVERFLOW, None)
                # detach the SURVIVING runs before demoting: a restored
                # copy / cross-host gather owns none of these files, and
                # _drop_runs deleting them would destroy state a live
                # writer's artifact references (the same hazard
                # __setstate__ documents)
                self._runs[name] = []
                self._demote(name, OVERFLOW)
                return OVERFLOW, None
            sources.append((mm, prefix))
        if self._chunks[name]:
            if name in self._clean:
                # canonical partitioned parts (counting columns arrive
                # here pre-compacted by _canonical_key)
                for c in self._chunks[name]:
                    sources.append((c, None))
            else:
                # probed-path chunk lists are sorted and mutually
                # dup-free, so unique == the old sort-concatenate
                sources.append((np.unique(np.concatenate(
                    self._chunks[name])), None))
        # the partition walk: P independent unions — partitions never
        # cross-merge (a value's partition is a function of the value),
        # each union runs over a cache-sized slice, and run slices come
        # straight off the header index (no global k-way hash walk).
        # Oversized partitions (a column far past RESOLVE_SLICE_ROWS)
        # fall back to bounded sub-ranges within the partition.
        status = UNIQUE
        distinct = 0
        P = self._partitions
        step = (1 << 64) // P
        for p in range(P):
            lo = p * step
            hi = (p + 1) * step - 1 if p + 1 < P else (1 << 64) - 1
            parts = []
            total = 0
            for arr, prefix in sources:
                if prefix is not None:
                    i, j = int(prefix[p]), int(prefix[p + 1])
                else:
                    i = int(np.searchsorted(arr, np.uint64(lo),
                                            side="left"))
                    j = int(np.searchsorted(arr, np.uint64(hi),
                                            side="right"))
                if j > i:
                    parts.append(arr[i:j])
                    total += j - i
            if len(parts) < 2:
                distinct += int(parts[0].size) if parts else 0
                continue            # one source can't cross-duplicate
            n_sub = max(1, -(-total // RESOLVE_SLICE_ROWS))
            dup, news = self._union_ranged(parts, lo, hi, n_sub, count)
            distinct += news
            if dup:
                status = DUP
                if not count:
                    break           # claim settled; count not wanted
        self._resolve_memo[name] = (
            key, status, distinct if count or status == UNIQUE else None)
        # a clean full walk also yields the count for free when every
        # slice completed (status UNIQUE => no early break happened)
        return status, self._resolve_memo[name][2]

    def cleanup(self) -> None:
        """Delete every spill run (idempotent; call once the profile is
        assembled — checkpoints reference the files until then): all
        runs this tracker references by path, everything under its own
        filename token, and — age-gated (ORPHAN_SWEEP_AGE_S) — other
        tokens' abandoned litter (crashed chains' post-checkpoint
        orphans).  Young files under other tokens are never touched:
        they may belong to a still-live concurrent writer."""
        self._drain_spills()        # queued writes land, then delete
        self.persistent = False     # nothing references the runs now —
        # _drop_runs may delete physically instead of retiring
        for name in list(self._runs):
            self._drop_runs(name)
        self.reap_retired()
        if self.spill_dir:
            import glob
            import time
            # own token: sweep unconditionally (only this process writes
            # under it).  Everything else — inherited ancestor tokens,
            # unrelated dead processes — only past ORPHAN_SWEEP_AGE_S:
            # a file under another token could belong to a STILL-LIVE
            # writer sharing the artifact or the dir, and deleting it
            # would hollow that process's exact claim; age is the only
            # safe evidence of abandonment cleanup has.
            own = os.path.join(
                glob.escape(self.spill_dir),
                f"tpuprof-uniq-{self._spill_token}-*.u64")
            stale_before = time.time() - ORPHAN_SWEEP_AGE_S
            any_pat = os.path.join(glob.escape(self.spill_dir),
                                   "tpuprof-uniq-*.u64")
            for path in glob.glob(own):
                try:
                    os.remove(path)
                except OSError:
                    pass
            for path in glob.glob(any_pat):
                try:
                    if os.path.getmtime(path) < stale_before:
                        os.remove(path)
                except OSError:
                    pass
            if getattr(self, "own_spill_dir", False):
                # an auto-derived (parity) dir leaves no residue; rmdir
                # refuses non-empty, so a concurrent writer's young runs
                # keep the dir alive
                try:
                    os.rmdir(self.spill_dir)
                except OSError:
                    pass

    def __del__(self):
        # best-effort tmp hygiene for files THIS instance wrote only —
        # unpickled copies (checkpoint loads, cross-host gathers) own
        # nothing, so their GC cannot destroy a live artifact's runs.
        # Checkpointed trackers skip even that: a crash's GC must leave
        # the runs for resume (the artifact references them by path).
        try:
            if getattr(self, "persistent", False):
                return
            for path in getattr(self, "_owned", ()):
                try:
                    os.remove(path)
                except OSError:
                    pass
        except Exception:
            pass

    def __getstate__(self) -> Dict[str, object]:
        # an artifact must reference only DURABLE runs: block until
        # every overlapped spill write landed (a failed write demotes
        # its column here, exactly as a synchronous failure would)
        self._drain_spills()
        state = dict(self.__dict__)
        state["_resolve_memo"] = {}
        state["_owned"] = []
        # retired paths belong to the WRITER's save/reap cycle, not the
        # artifact: a restored process must neither delete nor track them
        state["_retired"] = []
        state["_spill_pending"] = []
        state["_draining"] = False
        return state

    def __setstate__(self, state) -> None:
        self.__dict__.update(state)
        self._resolve_memo = {}
        self._owned = []
        self._retired = []
        # mint a FRESH filename token for runs written after restore:
        # two processes resuming the same artifact (or a still-live
        # original writer) would otherwise generate identical run names
        # and silently overwrite each other's spill files.  The
        # inherited runs stay reachable through self._runs (cleanup
        # deletes by path); a crashed ancestor's post-checkpoint orphans
        # fall to the age-gated sweep.
        import uuid
        self._spill_token = uuid.uuid4().hex[:12]
        self._spill_seq = 0
        if not hasattr(self, "_counting"):      # pre-counting artifacts
            self._counting = {n: False for n in self.status}
        if not hasattr(self, "_next_compact"):
            self._next_compact = {}
        if not hasattr(self, "_partitions"):    # pre-round-8 artifacts
            self._partitions = 1
        if not hasattr(self, "_spill_workers"):
            self._spill_workers = 0
        self._spill_pending = []
        self._draining = False
        # restored buffers are conservatively dirty (re-unique once)
        self._clean = set()
        if not hasattr(self, "_fed"):
            # pre-lazy artifacts (probed counting): chunks and runs are
            # dup-free, so for a still-UNIQUE column the stored distinct
            # total IS the raw total (no duplicate was ever folded);
            # DUP-status columns' claims are already settled and their
            # resolve never consults _fed
            self._fed = {n: self._rows.get(n, 0)
                         + sum(r for _p, r in self._runs.get(n, ()))
                         for n in self.status}
        self._last_touch = 0.0
        lost = []
        for name, runs in list(self._runs.items()):
            for path, rows in runs:
                try:
                    # full layout validation, both formats: a legacy
                    # run must match its exact size, a partitioned run
                    # its header + index CRC + payload length — any
                    # truncation/bit-flip is caught HERE, before a
                    # resume trusts the file (CorruptRunError)
                    self._run_layout(path, rows)
                    ok = True
                except (OSError, CorruptRunError):
                    ok = False
                if not ok:
                    # checkpoint artifacts reference spill files by path;
                    # a resume without them degrades honestly.  Detach
                    # the run list BEFORE demoting: an unpickled copy
                    # owns none of these files, and _drop_runs deleting
                    # the survivors would destroy state a still-live
                    # writer references.  Counting flips off FIRST:
                    # _demote's best-effort claim walk would otherwise
                    # see only the live buffer (the runs are gone) and
                    # settle a FALSE DUP from the partial union
                    self._runs[name] = []
                    self._counting[name] = False
                    self._demote(name, OVERFLOW)
                    lost.append(name)
                    break
        if lost:
            # say it ONCE per tracker: the scan paid the spill I/O for
            # these columns, and without this the exactness loss (e.g. a
            # host-LOCAL spill dir in a multi-host run, whose peers can
            # never see the files) would be silent until the report's
            # distinct_approx flag
            import logging
            logging.getLogger("tpuprof").warning(
                "%d spilled column(s) (%s) fell back to the approximate "
                "distinct estimate: their run files are not readable "
                "here.  In multi-host runs exact UNIQUE needs "
                "unique_spill_dir on storage SHARED by all hosts",
                len(lost), ", ".join(sorted(lost)[:5]))
        # restamp surviving inherited runs (demoted columns' lists are
        # already empty): a chain resumed after ORPHAN_SWEEP_AGE_S holds
        # files past the sweep's age gate — fair game for any other
        # profile's cleanup() until touched
        self.touch_runs(force=True)

    def disown_runs(self) -> None:
        """Transfer run-file ownership away from this instance: its GC
        must no longer reap them.  Called on the ORIGINAL tracker after
        a cross-host merge, right before the caller rebinds its
        reference to the merged (unpickled) copy — which takes over via
        ``claim_runs``."""
        self._owned = []

    def claim_runs(self) -> None:
        """Take GC ownership of every run file this tracker references.
        Called on the MERGED tracker after a cross-host gather: without
        it no live object would own the fleet's spill files (unpickled
        copies start with ``_owned=[]``), and an exception between the
        merge and cleanup() would orphan them all.  Multiple hosts
        claiming the same shared paths is fine — deletion is
        idempotent and statuses demote identically everywhere."""
        self._owned = [p for runs in self._runs.values()
                       for p, _rows in runs]

    def _end_counting(self, name: str) -> None:
        """Flip a column out of lazy counting, restoring the probed
        paths' chunk invariant (the walk leaves the buffer in the
        canonical partitioned form — sorted, mutually dup-free chunks,
        exactly what the probe loop expects).  The claim is settled from EVERYTHING
        counted so far — dup evidence may survive only in _fed
        (compactions collapse buffered dups, spills collapse run dups),
        so checking the live buffer alone would forget real duplicates
        (review r5)."""
        if not self._counting.get(name, False):
            return
        dup = False
        if self.status.get(name) == UNIQUE:
            try:
                # canonicalize FIRST: the probed paths this column is
                # about to rejoin require sorted mutually-dup-free
                # chunks, and the count-only fast path deliberately
                # leaves raw buffers in place
                self._compact_buffer(name)
                _st, cnt = self._resolve_spilled(name, count=True)
                dup = cnt is not None and cnt < self._fed.get(name, cnt)
            except Exception:
                # the settle walk failed for an unforeseen reason: the
                # claim can no longer be AFFIRMED (dup evidence may be
                # collapsed in _fed) — degrade to the honest OVERFLOW,
                # never to a wrong exact UNIQUE
                self._counting[name] = False
                self._demote(name, OVERFLOW)
                return
        self._counting[name] = False
        if dup:
            # counting is already off, so _demote runs no walk; the
            # sticky-DUP rule keeps this verdict through later demotes
            self._demote(name, DUP)

    def seed_resolution(self, statuses: Dict[str, str],
                        counts: Optional[Dict[str, int]] = None) -> None:
        """Adopt another process's resolve() verdicts (and exact
        distinct counts) for still-spilled columns (memo injection,
        keyed on the current run/row state so a later mutation still
        invalidates it).  After a deterministic cross-host merge every
        host holds byte-identical run lists, so rank 0 can pay the
        k-way read once and peers adopt — N× shared-storage resolve
        traffic becomes 1× (runtime/distributed.py)."""
        counts = counts or {}
        for name, st in statuses.items():
            if self._runs.get(name) and (
                    self.status.get(name) == UNIQUE
                    or self._counting.get(name)):
                self._resolve_memo[name] = (self._canonical_key(name),
                                            st, counts.get(name))

    def merge(self, other: "UniqueTracker") -> None:
        # adopt only DURABLE runs: both sides settle their spill queues
        # (an unpickled peer arrives drained by __getstate__ already)
        self._drain_spills()
        other._drain_spills()
        for name, ost in other.status.items():
            if name not in self.status:
                continue
            okind = other._kind.get(name, "")
            mkind = self._kind.get(name, "")
            kind_clash = bool(okind and mkind and okind != mkind)
            counting = self._counting.get(name, False) \
                and other._counting.get(name, False)
            if not counting:
                # leaving counting mode: the lazy tier's raw buffers
                # violate the probed paths' invariants (sorted, dup-free
                # chunks) — normalize BOTH sides, settling dup evidence
                # either tracker holds only in its _fed (the peer's
                # collapsed duplicate must not vanish just because THIS
                # side never counted)
                self._end_counting(name)
                other._end_counting(name)
                ost = other.status[name]
            if counting and not kind_clash \
                    and OVERFLOW not in (self.status[name], ost):
                # counting survives a DUP on either side: adopt the
                # peer's runs + fold its chunks, and let resolve() count
                # the union exactly (same laws as the UNIQUE claim)
                if other._runs.get(name):
                    self._runs[name].extend(other._runs[name])
                if okind and not mkind:
                    self._kind[name] = okind
                if DUP in (self.status[name], ost):
                    self.status[name] = DUP
                fed_before = self._fed.get(name, 0)
                for c in other._chunks[name]:
                    self.update(name, c, hash_kind=okind)
                # the folds above counted only the peer's LIVE rows;
                # its spilled rows are part of its fed total too.  The
                # claim law stays count == fed across the merge.
                self._fed[name] = fed_before + other._fed.get(name, 0)
                continue
            if DUP in (self.status[name], ost):
                self._demote(name, DUP)
            elif OVERFLOW in (self.status[name], ost) or kind_clash:
                self._demote(name, OVERFLOW)
            else:
                # a cross-host duplicate is only detectable when both
                # hosts hashed with the same implementation; otherwise an
                # exact "no duplicate" claim would be unsound
                if other._runs.get(name):
                    # adopt the peer's spilled runs: reaching here means
                    # __setstate__ validated those files present ON THIS
                    # HOST (unique uuid filenames + size check), i.e. the
                    # spill dir is shared storage — a peer whose disk we
                    # cannot see arrives already demoted to OVERFLOW.
                    # Runs are internally dup-free; cross-host duplicates
                    # surface in resolve()'s k-way hash-range merge, the
                    # same law that resolves cross-epoch duplicates
                    # within one host (SURVEY §4.2 mergeability).
                    self._runs[name].extend(other._runs[name])
                if okind and not mkind:
                    self._kind[name] = okind
                for c in other._chunks[name]:
                    self.update(name, c, hash_kind=okind)
