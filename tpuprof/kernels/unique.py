"""Exact per-column duplicate detection from the host hash stream.

Why this exists: the reference's ``distinct == n → UNIQUE`` type
classification (SURVEY.md §2.1) is EXACT — Spark's countDistinct scans
every value.  tpuprof's categorical distinct counts come from a
Misra-Gries summary while it fits (exact) and the HLL estimate after it
overflows (±1.04/√2¹¹ ≈ 2.3%), and an estimate essentially never equals
``count`` — so a 1M-row all-unique ID column would silently classify CAT
instead of UNIQUE.  This tracker restores the exact answer to the one
question classification needs — "was any value seen twice?" — without
exact distinct counting.

Mechanism: per column, keep every seen 64-bit value hash in sorted
chunks; each batch is sorted (exposing within-batch duplicates) and
probed against the chunks with ``searchsorted``.  The first duplicate
DEMOTES the column to ``DUP`` and frees its storage — for non-unique
columns (the common case) that happens within the first batch or two, so
memory concentrates on genuinely-unique columns only.  A per-column and
a global row budget bound that worst case; columns past budget demote to
``OVERFLOW`` and classification falls back to the HLL estimate with an
explicit approximation warning in the report (schema.MSG_APPROX_DISTINCT).

A 64-bit hash collision can mask a truly-unique column as DUP with
probability ~n²/2⁶⁵ (≈3e-8 at n=1e6) — the same collision contract the
HLL plane and the top-k store already accept (ingest/arrow.py).

Merge law (multi-host, SURVEY §4.2): DUP anywhere is definitive; else
OVERFLOW anywhere is OVERFLOW; else the peer's chunks fold in through
the same probe path, so cross-host duplicates are detected exactly while
the combined rows fit the budget.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

import numpy as np

UNIQUE = "unique"       # no duplicate among all rows seen so far (exact)
DUP = "dup"             # at least one duplicate seen (exact)
OVERFLOW = "overflow"   # gave up within budget — distinct is approximate


class UniqueTracker:
    """Tracks, per column, whether any value hash occurred twice."""

    def __init__(self, names: Iterable[str], budget_rows: int,
                 total_budget_rows: int):
        self.budget = int(budget_rows)
        self.total_budget = int(total_budget_rows)
        names = list(names)
        self.status: Dict[str, str] = {}
        self._chunks: Dict[str, List[np.ndarray]] = {}
        self._rows: Dict[str, int] = {}
        self._kind: Dict[str, str] = {}   # hash implementation per column
        self._live = 0          # rows held across all still-UNIQUE columns
        disabled = self.budget <= 0 or self.total_budget <= 0
        for n in names:
            self.status[n] = OVERFLOW if disabled else UNIQUE
            self._chunks[n] = []
            self._rows[n] = 0
            self._kind[n] = ""

    def active(self, name: str) -> bool:
        return self.status.get(name) == UNIQUE

    def deactivate(self, name: str, status: str = OVERFLOW) -> None:
        """Give up exact tracking for a column (e.g. a batch arrived
        without hashes, so coverage can no longer be guaranteed)."""
        self._demote(name, status)

    def _demote(self, name: str, status: str) -> None:
        self._live -= self._rows[name]
        self._rows[name] = 0
        self._chunks[name] = []
        self.status[name] = status

    def update(self, name: str, hashes: np.ndarray,
               hash_kind: str = "") -> None:
        """Fold one batch's valid-row hashes (duplicates included) in.

        ``hash_kind`` names the implementation that produced the hashes
        ("native" | "pandas"); the same value hashes DIFFERENTLY under
        the two, so a column whose stream switches implementations can
        no longer be compared exactly and demotes to OVERFLOW."""
        if self.status.get(name) != UNIQUE:
            return
        h = np.asarray(hashes, dtype=np.uint64)
        if not h.size:
            return
        if hash_kind:
            if self._kind[name] and self._kind[name] != hash_kind:
                self._demote(name, OVERFLOW)
                return
            self._kind[name] = hash_kind
        sh = np.sort(h)
        if sh.size > 1 and (sh[1:] == sh[:-1]).any():
            self._demote(name, DUP)
            return
        for c in self._chunks[name]:
            pos = np.searchsorted(c, sh)
            inb = pos < c.size
            if inb.any() and (c[pos[inb]] == sh[inb]).any():
                self._demote(name, DUP)
                return
        self._chunks[name].append(sh)
        self._rows[name] += sh.size
        self._live += sh.size
        if self._rows[name] > self.budget or self._live > self.total_budget:
            self._demote(name, OVERFLOW)
            return
        if len(self._chunks[name]) > 8:
            # keep the probe loop short: fold the chunk list back into
            # one sorted array (amortized O(n log n) per column)
            self._chunks[name] = [np.sort(np.concatenate(
                self._chunks[name]))]

    def merge(self, other: "UniqueTracker") -> None:
        for name, ost in other.status.items():
            if name not in self.status:
                continue
            if DUP in (self.status[name], ost):
                self._demote(name, DUP)
            elif OVERFLOW in (self.status[name], ost):
                self._demote(name, OVERFLOW)
            else:
                # a cross-host duplicate is only detectable when both
                # hosts hashed with the same implementation; otherwise an
                # exact "no duplicate" claim would be unsound
                okind = other._kind.get(name, "")
                for c in other._chunks[name]:
                    self.update(name, c, hash_kind=okind)
