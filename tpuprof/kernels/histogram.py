"""Fixed-bin histograms for all numeric columns — XLA tier of pass B.

Replaces the reference's per-column RDD ``histogram()`` jobs (SURVEY.md
§2.2) with a single batched update over (cols × bins) counters.  Runs
in pass B, once the exact finite min/max per column are known from
pass A — reproducing np.histogram semantics exactly (right edge of the
last bin inclusive via the clip).

Two formulations (``ProfilerConfig.pass_b_kernel`` selects; the mesh
runtime routes real-TPU meshes to the pallas twins in pallas_hist.py):

* ``update`` (legacy) — flattened segment scatter-add over per-element
  bin indices;
* ``update_cumulative`` — cumulative ≥-edge compares on the SAME
  scaled value legacy feeds ``floor`` (``floor(t) >= b ⇔ t >= b`` for
  integer b, so the differenced counts are bit-for-bin identical), with
  the per-bin difference taken by :func:`counts_from_cumulative`
  OUTSIDE the counting pass.  No scatter, no index materialization —
  the formulation the pallas cumulative kernel mirrors on TPU.

Both fold into the same per-bin ``HistState`` — merges and finalize are
formulation-blind, and states from either path are byte-identical
(tests/test_hist_cumulative.py pins this).

Also accumulates Σ|x − mean| per column (the oracle's MAD needs the pass-A
mean), folding the second statistic into the same read of the batch.

Counts are int32: exact to 2.1B rows per bin — beyond the 1B-row target.
Merge is elementwise addition.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

Array = jnp.ndarray
HistState = Dict[str, Array]


def init(n_cols: int, bins: int) -> HistState:
    return {
        "counts": jnp.zeros((n_cols, bins), dtype=jnp.int32),
        "abs_dev": jnp.zeros((n_cols,), dtype=jnp.float32),
    }


def update(state: HistState, x: Array, row_valid: Array,
           lo: Array, hi: Array, mean: Array) -> HistState:
    """``lo``/``hi``: (cols,) finite min/max from pass A; ``mean``: (cols,)
    pass-A means for the MAD accumulation."""
    n_cols, bins = state["counts"].shape
    finite = row_valid[:, None] & jnp.isfinite(x)
    width = jnp.maximum(hi - lo, 1e-30)[None, :]
    idx = jnp.floor((x - lo[None, :]) / width * bins)
    idx = jnp.clip(idx, 0, bins - 1).astype(jnp.int32)
    col_ids = jnp.arange(n_cols, dtype=jnp.int32)[None, :]
    flat_ids = jnp.where(finite, col_ids * bins + idx, n_cols * bins)
    flat = jnp.zeros((n_cols * bins + 1,), dtype=jnp.int32)
    flat = flat.at[flat_ids.reshape(-1)].add(1)
    abs_dev = jnp.where(finite, jnp.abs(x - mean[None, :]), 0.0).sum(axis=0)
    return {
        "counts": state["counts"] + flat[: n_cols * bins].reshape(n_cols, bins),
        "abs_dev": state["abs_dev"] + abs_dev,
    }


def counts_from_cumulative(cum: Array) -> Array:
    """(cols, bins) cumulative ≥-edge counts → per-bin counts.

    ``cum[:, b]`` counts elements at-or-above edge b (column 0 = all
    binned elements), so ``counts[b] = cum[b] - cum[b+1]`` with an
    implicit ``cum[bins] = 0``.  The ``maximum(…, 0)`` is the
    negative-count guard: a well-formed cumulative input is monotone
    non-increasing by construction (integer thresholds against one
    computed value — a float non-monotonicity in derived EDGES cannot
    occur in-kernel), but a corrupted or hand-built input must clamp to
    an empty bin rather than emit a negative count that would poison
    every downstream sum (tests/test_hist_cumulative.py pins this on
    adversarial inputs)."""
    upper = jnp.concatenate(
        [cum[:, 1:], jnp.zeros((cum.shape[0], 1), dtype=cum.dtype)],
        axis=1)
    return jnp.maximum(cum - upper, 0)


def update_cumulative(state: HistState, x: Array, row_valid: Array,
                      lo: Array, hi: Array, mean: Array) -> HistState:
    """``update`` twin without the scatter: cumulative ≥-edge compares
    on the same ``(x - lo) / width * bins`` value, differenced by
    :func:`counts_from_cumulative`.  Bit-for-bin identical to ``update``
    for every input (module docstring)."""
    n_cols, bins = state["counts"].shape
    finite = row_valid[:, None] & jnp.isfinite(x)
    width = jnp.maximum(hi - lo, 1e-30)[None, :]
    t = (x - lo[None, :]) / width * bins
    t = jnp.where(finite, t, jnp.nan)      # NaN fails every >= compare
    # (rows, cols) >= (bins-1,) edges -> (cols, bins-1) lane reduces;
    # column 0 is the finite count (every finite element clips into
    # some bin), so no 0-edge compare is needed
    edges = jnp.arange(1, bins, dtype=t.dtype)
    cum_tail = jnp.sum(
        (t[:, :, None] >= edges[None, None, :]).astype(jnp.int32),
        axis=0)                            # (cols, bins-1)
    cum = jnp.concatenate(
        [jnp.sum(finite.astype(jnp.int32), axis=0, keepdims=True).T,
         cum_tail], axis=1)                # (cols, bins)
    abs_dev = jnp.where(finite, jnp.abs(x - mean[None, :]), 0.0).sum(axis=0)
    return {
        "counts": state["counts"] + counts_from_cumulative(cum),
        "abs_dev": state["abs_dev"] + abs_dev,
    }


def merge(a: HistState, b: HistState) -> HistState:
    return {"counts": a["counts"] + b["counts"],
            "abs_dev": a["abs_dev"] + b["abs_dev"]}


def pass_b_bounds(momf):
    """(lo, hi, mean) for the pass-B binning/MAD kernels from finalized
    pass-A moments, with non-finite entries (all-NaN columns) clamped to
    0 so the kernel's bin math stays well-defined.  Single source of
    truth for the backend (backends/tpu.py) and the benchmark — the two
    must time the same recipe."""
    import numpy as np

    lo = np.where(np.isfinite(momf["fmin"]), momf["fmin"], 0.0)
    hi = np.where(np.isfinite(momf["fmax"]), momf["fmax"], 0.0)
    mean = np.where(np.isfinite(momf["mean"]), momf["mean"], 0.0)
    return lo, hi, mean


def finalize(state, lo, hi, n, bins: int) -> Tuple["object", "object"]:
    """Host-side: (per-column (counts, edges) histograms, MAD array)."""
    import numpy as np

    counts = np.asarray(state["counts"]).astype(np.int64)
    abs_dev = np.asarray(state["abs_dev"], dtype=np.float64)
    n = np.asarray(n, dtype=np.float64)
    hists = []
    for c in range(counts.shape[0]):
        if np.isfinite(lo[c]) and np.isfinite(hi[c]):
            edges = np.linspace(lo[c], hi[c], bins + 1)
            hists.append((counts[c], edges))
        else:
            hists.append(None)
    with np.errstate(invalid="ignore", divide="ignore"):
        mad = np.where(n > 0, abs_dev / np.maximum(n, 1.0), np.nan)
    return hists, mad
