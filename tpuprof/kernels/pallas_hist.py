"""Pallas TPU kernel: all-column fixed-bin histograms + exact-MAD sums.

Why a custom kernel: XLA lowers the scatter-add in kernels/histogram.py
to a serialized per-element scatter on TPU — the one op in the profile
scan that doesn't vectorize.  Binning is really a *dense* computation:
for bins ≤ ~128, comparing every element against every bin id is only
``bins`` VPU passes over the tile, with all accumulation in registers/
VMEM — no scatter at all.  The MAD numerator Σ|x−mean| rides the same
read (a separate XLA reduction measured as expensive as the histogram
itself on the target device — PERF.md).

Two formulations share the entry points (selected by ``kernel=``, wired
from ``ProfilerConfig.pass_b_kernel`` via the mesh runtime):

* ``legacy`` — per-element bin-index materialization:
  ``idx = clip(floor((x-lo)*scale), 0, nbins-1)`` then one ``idx == b``
  compare+lane-reduce per bin.  The index prologue (floor/clip/astype/
  select) is ~6 extra VPU passes over the full (C, R) tile before any
  bin is counted.
* ``cumulative`` — ≥-edge compares on the raw scaled value: compute
  ``t = (x-lo)*scale`` ONCE (the same two arithmetic ops legacy feeds
  floor), then accumulate CUMULATIVE counts ``cum[b] = #(t >= b)`` —
  one f32 compare+lane-reduce per bin, no floor/clip/astype/int index
  anywhere.  Per-bin counts are recovered OUTSIDE the kernel by
  differencing adjacent cumulative columns
  (``kernels.histogram.counts_from_cumulative``).  Bit-for-bin equality
  with legacy is by construction, not by tolerance: for the SAME
  computed t and an integer threshold b, ``floor(t) >= b  ⇔  t >= b``
  in IEEE arithmetic, so every element lands in the identical bin —
  including ±overflowed t (clip vs compare saturate the same way) and
  NaN/masked elements (compares are False; legacy's -1 sentinel index
  matches no bin).

Layout (per /opt/skills/guides/pallas_guide.md tiling rules, matching
kernels/fused.py): the batch arrives as the mesh ships it — ``xt`` is
(cols, rows), columns on the sublane axis (8-aligned for f32, so
typical column counts need no padding copy), rows on the lane axis,
grid over row tiles; all reductions run along lanes.  Output blocks
have constant index maps so Mosaic keeps them VMEM-resident across the
grid and writes them back once.  ``row_valid`` masks padding in-kernel
(no NaN-masking pre-pass over the batch).

Both kernels are exact (same clip semantics as the XLA path) and are
tested in interpreter mode on CPU against numpy, the scatter version
and each other (tests/test_pallas.py, tests/test_hist_cumulative.py);
the mesh runtime enables them on real TPU only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C_ALIGN = 8             # sublane-axis (column) alignment, f32
MAX_BINS = 128
# ~5 (C, R) f32/int32 temporaries live per block; the row tile shrinks
# with width to stay inside VMEM (empirical compile probe on v5e), and
# the mesh runtime falls back to the XLA scatter beyond MAX_HIST_COLS
MAX_HIST_COLS = 1024
R_TILE = 1024           # lane-axis (row) tile at narrow widths


def _pick_r_tile(C: int) -> int:
    return 1024 if C <= 512 else 256


def hist_tile_legacy(x, finite, lo, scale, nbins: int):
    """(C, R) tile → (C, nbins) per-bin counts, legacy formulation:
    per-element bin-index materialization then one ``idx == b``
    compare+lane-reduce per bin.  Shared by the standalone pass-B
    kernel and the single-pass combined kernel (kernels/fused.py) so
    the two dispatch shapes count bit-for-bin identically."""
    idx = jnp.floor((x - lo) * scale)
    idx = jnp.clip(idx, 0, nbins - 1).astype(jnp.int32)
    idx = jnp.where(finite, idx, -1)          # -1 never matches a bin id
    # dense bin counting: one vectorized compare+lane-reduce per bin
    return jnp.concatenate(
        [jnp.sum((idx == b).astype(jnp.int32), axis=1, keepdims=True)
         for b in range(nbins)], axis=1)      # (C, nbins)


def hist_tile_cumulative(x, finite, lo, scale, nbins: int):
    """(C, R) tile → (C, nbins) CUMULATIVE ≥-edge counts (column 0 =
    the finite count; difference outside the kernel via
    ``histogram.counts_from_cumulative``).  Shared like
    :func:`hist_tile_legacy`."""
    # NaN fails every >= compare, so one select masks invalid elements
    # out of all nbins-1 edge counts at once
    t = jnp.where(finite, (x - lo) * scale, jnp.nan)
    return jnp.concatenate(
        [jnp.sum(finite.astype(jnp.int32), axis=1, keepdims=True)]
        + [jnp.sum((t >= float(b)).astype(jnp.int32), axis=1,
                   keepdims=True)
           for b in range(1, nbins)], axis=1)  # (C, nbins)


def mad_tile(x, finite, mean):
    """(C, R) tile → (C, 1) Σ|x − mean| over finite elements — the MAD
    numerator riding the same read."""
    return jnp.sum(jnp.where(finite, jnp.abs(x - mean), 0.0),
                   axis=1, keepdims=True)


HIST_TILES = {"legacy": hist_tile_legacy,
              "cumulative": hist_tile_cumulative}


def _hist_kernel(xt_ref, rv_ref, lo_ref, scale_ref, mean_ref, out_ref,
                 dev_ref, *, nbins: int):
    i = pl.program_id(0)
    x = xt_ref[...]                           # (C, R)
    rv = rv_ref[...] > 0                      # (1, R)
    lo = lo_ref[...]                          # (C, 1)
    scale = scale_ref[...]                    # (C, 1)
    mean = mean_ref[...]                      # (C, 1)
    finite = rv & jnp.isfinite(x)
    counts = hist_tile_legacy(x, finite, lo, scale, nbins)
    dev = mad_tile(x, finite, mean)           # (C, 1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)
        dev_ref[...] = jnp.zeros_like(dev_ref)

    out_ref[...] += counts
    dev_ref[...] += dev


def _hist_kernel_cumulative(xt_ref, rv_ref, lo_ref, scale_ref, mean_ref,
                            out_ref, dev_ref, *, nbins: int):
    """Cumulative ≥-edge formulation.  ``out_ref`` accumulates
    ``cum[:, b] = #(t >= b)`` (column 0 = the finite count, since every
    finite element clips into SOME bin); per-bin counts are differenced
    outside the kernel.  ``t`` is the SAME ``(x - lo) * scale`` legacy
    feeds ``floor``, and ``floor(t) >= b ⇔ t >= b`` for integer b, so
    the differenced counts are bit-for-bin identical to legacy's —
    without materializing any per-element index (no floor/clip/astype/
    int-select passes over the tile)."""
    i = pl.program_id(0)
    x = xt_ref[...]                           # (C, R)
    rv = rv_ref[...] > 0                      # (1, R)
    lo = lo_ref[...]                          # (C, 1)
    scale = scale_ref[...]                    # (C, 1)
    mean = mean_ref[...]                      # (C, 1)
    finite = rv & jnp.isfinite(x)
    cum = hist_tile_cumulative(x, finite, lo, scale, nbins)
    dev = mad_tile(x, finite, mean)           # (C, 1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)
        dev_ref[...] = jnp.zeros_like(dev_ref)

    out_ref[...] += cum
    dev_ref[...] += dev


_KERNELS = {"legacy": _hist_kernel, "cumulative": _hist_kernel_cumulative}


@functools.partial(jax.jit,
                   static_argnames=("nbins", "interpret", "kernel"))
def histogram_tiles(xt: jnp.ndarray, row_valid: jnp.ndarray,
                    lo: jnp.ndarray, hi: jnp.ndarray, mean: jnp.ndarray,
                    nbins: int, interpret: bool = False,
                    kernel: str = "legacy"):
    """(cols, rows) f32 (NaN = skip; padding rows via ``row_valid``) →
    ((cols, nbins) int32 counts, (cols,) f32 Σ|x−mean|).

    ``lo``/``hi`` are per-column finite ranges (pass-A min/max); values
    land in ``clip(floor((x-lo)/(hi-lo)*nbins), 0, nbins-1)`` — identical
    semantics to kernels/histogram.py and np.histogram's inclusive last
    edge.  ``mean`` is the pass-A mean feeding the exact-MAD numerator.

    ``kernel`` selects the formulation (module docstring): both return
    PER-BIN counts — the cumulative kernel's output is differenced here
    (a (cols, nbins) elementwise op, outside the pallas program), so
    callers and the HistState fold are formulation-blind."""
    if nbins > MAX_BINS:
        raise ValueError(f"pallas histogram supports bins <= {MAX_BINS}")
    if kernel not in _KERNELS:
        raise ValueError(f"unknown pass-B kernel {kernel!r} — use "
                         f"{sorted(_KERNELS)}")
    cols, rows = xt.shape
    cpad = -cols % C_ALIGN
    C = cols + cpad
    r_tile = _pick_r_tile(C)
    rpad = -rows % r_tile
    xt_p = jnp.pad(xt, ((0, cpad), (0, rpad)), constant_values=jnp.nan)
    rv_p = jnp.pad(row_valid.astype(jnp.float32), (0, rpad))[None, :]
    lo_p = jnp.pad(lo.astype(jnp.float32), (0, cpad))[:, None]
    width = jnp.maximum(hi - lo, 1e-30).astype(jnp.float32)
    scale_p = jnp.pad(nbins / width, (0, cpad))[:, None]
    mean_p = jnp.pad(mean.astype(jnp.float32), (0, cpad))[:, None]

    n_rt = (rows + rpad) // r_tile
    counts, dev = pl.pallas_call(
        functools.partial(_KERNELS[kernel], nbins=nbins),
        grid=(n_rt,),
        in_specs=[
            pl.BlockSpec((C, r_tile), lambda i: (0, i)),
            pl.BlockSpec((1, r_tile), lambda i: (0, i)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((C, nbins), lambda i: (0, 0)),
            pl.BlockSpec((C, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((C, nbins), jnp.int32),
            jax.ShapeDtypeStruct((C, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xt_p, rv_p, lo_p, scale_p, mean_p)
    if kernel == "cumulative":
        # differencing lives OUTSIDE the pallas program: (cols, nbins)
        # elementwise work per dispatch, fused by XLA into the epilogue
        from tpuprof.kernels.histogram import counts_from_cumulative
        counts = counts_from_cumulative(counts)
    return counts[:cols], dev[:cols, 0]


def histogram_batch(xt, row_valid, lo, hi, mean, nbins: int,
                    interpret: bool = False, kernel: str = "legacy"):
    """Batch entry point matching kernels/histogram.update semantics;
    ``xt`` is (cols, rows) as the mesh ships batches."""
    return histogram_tiles(xt, row_valid, lo, hi, mean, nbins,
                           interpret=interpret, kernel=kernel)
