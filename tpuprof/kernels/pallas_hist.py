"""Pallas TPU kernel: all-column fixed-bin histograms.

Why a custom kernel: XLA lowers the scatter-add in kernels/histogram.py
to a serialized per-element scatter on TPU — the one op in the profile
scan that doesn't vectorize.  Binning is really a *dense* computation:
for bins ≤ ~64, comparing every element against every bin id is only
``bins`` VPU passes over the tile, with all accumulation in registers/
VMEM — no scatter at all.

Layout (per /opt/skills/guides/pallas_guide.md tiling rules):
* grid = (col_tiles, row_tiles); row tiles iterate fastest so each
  output block stays resident in VMEM while its rows stream through;
* x block (R_TILE=512, C_TILE=128) f32; per-column lo/scale ride along
  as (1, C_TILE) blocks; output block (C_TILE, BINS_PAD=128) int32 is
  zero-initialized at the first row tile and accumulated in place.

The kernel is exact (same clip semantics as the XLA path) and is tested
in interpreter mode on CPU against both numpy and the scatter version
(tests/test_pallas.py); the mesh runtime enables it on real TPU only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R_TILE = 512
C_TILE = 128
BINS_PAD = 128          # lane width; bins <= BINS_PAD


def _hist_kernel(x_ref, lo_ref, scale_ref, mean_ref, out_ref, dev_ref, *,
                 nbins: int):
    i = pl.program_id(1)                      # row tile (fastest)
    x = x_ref[...]                            # (R_TILE, C_TILE)
    lo = lo_ref[...]                          # (1, C_TILE)
    scale = scale_ref[...]                    # (1, C_TILE)
    mean = mean_ref[...]                      # (1, C_TILE)
    finite = jnp.isfinite(x)
    idx = jnp.floor((x - lo) * scale)
    idx = jnp.clip(idx, 0, nbins - 1).astype(jnp.int32)
    idx = jnp.where(finite, idx, -1)          # -1 never matches a bin id

    # dense bin counting: one vectorized compare+reduce per bin
    cols = [jnp.sum((idx == b).astype(jnp.int32), axis=0)
            for b in range(nbins)]            # each (C_TILE,)
    counts = jnp.stack(cols, axis=1)          # (C_TILE, nbins)
    counts = jnp.pad(counts, ((0, 0), (0, BINS_PAD - nbins)))

    # MAD numerator rides the same read: Σ|x − mean| over finite values
    # (a separate XLA reduction measured as expensive as the histogram
    # itself on the target device)
    dev = jnp.sum(jnp.where(finite, jnp.abs(x - mean), 0.0),
                  axis=0)[:, None]            # (C_TILE, 1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)
        dev_ref[...] = jnp.zeros_like(dev_ref)

    out_ref[...] += counts
    dev_ref[...] += dev


@functools.partial(jax.jit,
                   static_argnames=("nbins", "interpret"))
def histogram_tiles(x: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray,
                    mean: jnp.ndarray, nbins: int,
                    interpret: bool = False):
    """(rows, cols) f32 (NaN = skip) → ((cols, nbins) int32 counts,
    (cols,) f32 Σ|x−mean|).

    ``lo``/``hi`` are per-column finite ranges (pass-A min/max); values
    land in ``clip(floor((x-lo)/(hi-lo)*nbins), 0, nbins-1)`` — identical
    semantics to kernels/histogram.py and np.histogram's inclusive last
    edge.  ``mean`` is the pass-A mean feeding the exact-MAD numerator."""
    if nbins > BINS_PAD:
        raise ValueError(f"pallas histogram supports bins <= {BINS_PAD}")
    rows, cols = x.shape
    rpad = -rows % R_TILE
    cpad = -cols % C_TILE
    x = jnp.pad(x, ((0, rpad), (0, cpad)), constant_values=jnp.nan)
    lo_p = jnp.pad(lo.astype(jnp.float32), (0, cpad))[None, :]
    width = jnp.maximum(hi - lo, 1e-30).astype(jnp.float32)
    scale_p = jnp.pad(nbins / width, (0, cpad))[None, :]
    mean_p = jnp.pad(mean.astype(jnp.float32), (0, cpad))[None, :]

    n_ct = (cols + cpad) // C_TILE
    n_rt = (rows + rpad) // R_TILE
    counts, dev = pl.pallas_call(
        functools.partial(_hist_kernel, nbins=nbins),
        grid=(n_ct, n_rt),
        in_specs=[
            pl.BlockSpec((R_TILE, C_TILE), lambda j, i: (i, j)),
            pl.BlockSpec((1, C_TILE), lambda j, i: (0, j)),
            pl.BlockSpec((1, C_TILE), lambda j, i: (0, j)),
            pl.BlockSpec((1, C_TILE), lambda j, i: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((C_TILE, BINS_PAD), lambda j, i: (j, 0)),
            pl.BlockSpec((C_TILE, 1), lambda j, i: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((cols + cpad, BINS_PAD), jnp.int32),
            jax.ShapeDtypeStruct((cols + cpad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, lo_p, scale_p, mean_p)
    return counts[:cols, :nbins], dev[:cols, 0]


def histogram_batch(x, row_valid, lo, hi, mean, nbins: int,
                    interpret: bool = False):
    """Batch entry point matching kernels/histogram.update semantics:
    padding rows masked via ``row_valid``; returns (counts, abs_dev)."""
    x = jnp.where(row_valid[:, None], x, jnp.nan)
    return histogram_tiles(x, lo, hi, mean, nbins, interpret=interpret)
