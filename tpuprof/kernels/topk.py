"""Top-k frequent values: host-side Misra-Gries summaries.

The reference's value-count tables come from one exact
``groupBy(col).count().orderBy(desc)`` Spark job per categorical column
(SURVEY.md §2.2).  TPUs have no hash tables and no strings, so frequency
tracking is deliberately a *host* responsibility (SURVEY §7.2 "Strings on
TPU"): during Arrow decode each batch is dictionary-encoded anyway, and a
Misra-Gries summary per column absorbs the per-batch counts at vectorized
numpy speed.

Guarantees (Agarwal et al., "Mergeable Summaries"): with capacity k, every
kept count is an underestimate by at most n/k, any value with true
frequency > n/k is retained, and the merge below (add counts, subtract the
(k+1)-th largest, drop ≤0) preserves those bounds — so summaries built per
fragment/host can be combined.  When a column's total distinct count never
exceeds the capacity, counts are *exact*.

Exactness parity with Spark's groupBy: pass B recounts the surviving
candidates exactly (tpuprof/backends/tpu.py), so reported top-k rows are
exact whenever the source is rescannable.

Performance: the store keys on the 64-bit value hashes that Arrow decode
already computes for the HLL plane (``HostBatch.cat_hashes`` — the native
C++ buffer hash when available), held in a uint64 pandas ``Index`` whose
``get_indexer`` probes run in C.  The actual values ride in a parallel
object array and are only touched when a NEW key is appended — the hot
per-batch fold never hashes or compares Python strings.  (The old
per-value dict loop was the measured host bottleneck at Criteo-like
cardinality: ~1e5 distinct per batch × dozens of columns.)

Hash caveats, both shared with the HLL plane's existing contract
(ingest/arrow.py ``_hash64_dictionary`` is process-stable, and multi-host
merges assume every process picked the same hash implementation): a
64-bit collision folds two values into one entry with probability
~k²/2⁶⁴ (≈1e-9 at 1e5 keys) — and the pass-B recount is value-keyed, so
reported counts self-heal even then.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np
import pandas as pd


def _fallback_hashes(values: np.ndarray) -> np.ndarray:
    """Hash keys for callers that have no precomputed ingest hashes
    (tests, value-level merges).  A given MisraGries instance must be fed
    from ONE hash source — production always passes ingest hashes."""
    return pd.util.hash_array(
        np.asarray(values, dtype=object)).astype(np.uint64)


class MisraGries:
    """One column's frequent-values summary (value -> count)."""

    __slots__ = ("capacity", "_index", "_counts", "_values", "offset",
                 "overflowed", "_merged")

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._index = pd.Index([], dtype=np.uint64)   # value hashes
        self._counts = np.zeros(0, dtype=np.int64)
        self._values = np.zeros(0, dtype=object)      # aligned with _index
        self.offset = 0          # total decrement applied (error bound)
        self.overflowed = False  # True once any eviction happened
        self._merged = False     # True once a value-keyed merge ran — the
                                 # hash index may then hold foreign keys
                                 # and update_batch must refuse to run

    def update_batch(self, values: np.ndarray, counts: np.ndarray,
                     hashes: Optional[np.ndarray] = None) -> None:
        """Fold pre-aggregated (unique values, counts) from one batch in.

        ``hashes`` is the aligned uint64 key array from Arrow decode;
        computed from ``values`` when omitted."""
        if self._merged:
            # after a value-keyed merge the hash index may hold keys from
            # a DIFFERENT hash implementation; a hash-keyed fold would
            # silently split one value across two entries (corrupting both
            # counts), so the misuse fails loudly instead
            raise RuntimeError(
                "MisraGries.update_batch called after merge(): the store's "
                "hash index is no longer batch-keyable — fold batches "
                "first, merge summaries last")
        counts = np.asarray(counts, dtype=np.int64)
        if hashes is None:
            hashes = _fallback_hashes(values)
        hashes = np.asarray(hashes, dtype=np.uint64)
        if hashes.size > 1:
            # Batches are normally pre-aggregated (unique keys) — verify
            # with one cheap sort; a duplicated key would otherwise lose
            # counts in the fancy add below and corrupt the store's
            # uniqueness invariant.  Duplicates take the aggregate path.
            sh = np.sort(hashes)
            if (sh[1:] == sh[:-1]).any():
                uh, first, inv = np.unique(hashes, return_index=True,
                                           return_inverse=True)
                agg = np.zeros(uh.size, dtype=np.int64)
                np.add.at(agg, inv, counts)
                values = np.asarray(values, dtype=object)[first]
                hashes, counts = uh, agg
        self._update_core(
            hashes, counts,
            lambda src: np.asarray(values, dtype=object)[src])

    def update_hashed(self, hashes: np.ndarray, counts: np.ndarray,
                      resolver) -> None:
        """Fold pre-aggregated UNIQUE (hashes, counts) whose values are
        materialized lazily: ``resolver(src)`` returns the object values
        for positions ``src`` of the hash array, and is called only for
        new entries that SURVIVE compaction — the ingest plain-string
        path hashes rows without ever building a per-batch dictionary,
        so touching O(capacity) values instead of O(distinct) is the
        point (SURVEY §7.2 'Strings on TPU')."""
        if self._merged:
            raise RuntimeError(
                "MisraGries.update_hashed called after merge(): the "
                "store's hash index is no longer batch-keyable — fold "
                "batches first, merge summaries last")
        self._update_core(np.asarray(hashes, dtype=np.uint64),
                          np.asarray(counts, dtype=np.int64), resolver)

    def _update_core(self, hashes: np.ndarray, counts: np.ndarray,
                     resolver) -> None:
        if len(self._index):
            pos = self._index.get_indexer(hashes)
            hit = np.flatnonzero(pos >= 0)
            # per-batch keys are unique, so the fancy add is alias-free
            self._counts[pos[hit]] += counts[hit]
            miss = np.flatnonzero(pos < 0)
        else:
            miss = np.arange(len(counts))
        if not miss.size:
            return
        # Append new keys with value slots DEFERRED: at high cardinality
        # most of this batch's new keys are evicted by the very next
        # compaction, so materializing only the survivors' values keeps
        # the per-batch object traffic at O(capacity), not O(distinct).
        start = len(self._counts)
        self._index = self._index.append(
            pd.Index(hashes[miss], copy=False))
        self._counts = np.concatenate([self._counts, counts[miss]])
        self._values = np.concatenate(
            [self._values, np.empty(miss.size, dtype=object)])
        if len(self._index) > self.capacity:
            kept_new = self._compact(start)
            src = miss[kept_new]        # compaction preserves order, so
        else:                           # survivors of the new chunk are
            src = miss                  # the tail of the store
        n_new = src.size
        if n_new:
            self._values[len(self._values) - n_new:] = resolver(src)

    def _append(self, hashes: np.ndarray, counts: np.ndarray,
                values: np.ndarray) -> None:
        self._index = self._index.append(
            pd.Index(np.asarray(hashes, dtype=np.uint64), copy=False))
        self._counts = np.concatenate([self._counts, counts])
        self._values = np.concatenate([self._values, values])

    def _compact(self, new_start: int = 0) -> np.ndarray:
        """Misra-Gries decrement step, batched: subtract the
        (capacity+1)-th largest count from everyone, drop the
        non-positive.  Returns the keep-mask slice for entries at
        ``new_start:`` (whose value slots the caller fills in)."""
        self.overflowed = True
        arr = self._counts
        kth = np.partition(arr, -(self.capacity + 1))[-(self.capacity + 1)]
        self.offset += int(kth)
        keep = arr > kth
        self._index = self._index[keep]
        self._counts = arr[keep] - kth
        self._values = self._values[keep]
        return keep[new_start:]

    def merge(self, other: "MisraGries") -> None:
        """Fold another summary in, keyed on VALUES rather than hashes:
        the two stores may come from processes whose hash implementations
        differ (native C++ vs pandas fallback — the same heterogeneous
        deployment the HLL host-fold gates on in backends/tpu.py), and a
        hash-keyed fold would then split one value across two entries.
        Cold path: runs once per profile over O(capacity) entries.  After
        a merge the hash index may hold foreign keys, so ``update_batch``
        refuses to run (``_merged`` flag) — in production merges happen
        only after the scan completes."""
        self._merged = True
        if len(other._index):
            vidx = pd.Index(self._values)
            pos = vidx.get_indexer(other._values)
            hit = np.flatnonzero(pos >= 0)
            self._counts[pos[hit]] += other._counts[hit]
            miss = np.flatnonzero(pos < 0)
            if miss.size:
                self._append(other._index.to_numpy()[miss],
                             other._counts[miss], other._values[miss])
                if len(self._index) > self.capacity:
                    self._compact()
        self.offset += other.offset
        self.overflowed |= other.overflowed

    def __getstate__(self) -> Dict[str, object]:
        """Stable pickle layout (checkpoints, cross-host gathers)."""
        return {"capacity": self.capacity, "offset": self.offset,
                "overflowed": self.overflowed, "merged": self._merged,
                "hashes": self._index.to_numpy(),
                "count_arr": self._counts, "values": self._values}

    def __setstate__(self, state) -> None:
        if isinstance(state, tuple):        # default __slots__ protocol
            state = {**(state[0] or {}), **(state[1] or {})}
        self.capacity = int(state["capacity"])
        self.offset = int(state["offset"])
        self.overflowed = bool(state["overflowed"])
        self._merged = bool(state.get("merged", False))
        if "hashes" in state:
            self._index = pd.Index(
                np.asarray(state["hashes"], dtype=np.uint64))
            self._counts = np.asarray(state["count_arr"], dtype=np.int64)
            self._values = np.asarray(state["values"], dtype=object)
        else:
            # legacy dict-backed layout (pre-v4 checkpoints): tolerate it
            # so old artifacts unpickle far enough for the checkpoint
            # version check to reject them cleanly
            d = state.get("counts", {})
            self._values = np.array(list(d.keys()), dtype=object)
            self._counts = np.fromiter(d.values(), dtype=np.int64,
                                       count=len(d))
            self._index = pd.Index(_fallback_hashes(self._values)
                                   if len(d) else
                                   np.zeros(0, dtype=np.uint64))

    @property
    def counts(self) -> Dict[object, int]:
        """Dict view (value -> estimated count); built on demand — the
        hot path never materializes it."""
        return {v: int(c) for v, c in zip(self._values, self._counts)}

    @property
    def exact(self) -> bool:
        """True when every stored count is the true frequency."""
        return not self.overflowed

    def top(self, k: int) -> List[Tuple[object, int]]:
        order = np.argsort(-self._counts, kind="stable")[:k]
        return [(self._values[int(i)], int(self._counts[int(i)]))
                for i in order]

    def distinct_count(self) -> Optional[int]:
        """Exact distinct count, or None if the summary overflowed."""
        return len(self._index) if self.exact else None

    def candidates(self) -> Iterable[object]:
        return list(self._values)
