"""HyperLogLog cardinality registers on TPU.

The reference's distinct counts are ``countDistinct`` /
``approx_count_distinct`` Spark jobs (HLL++ inside Spark, one job per
column — SURVEY.md §2.2).  Here: one (cols, 2^p) int32 register plane for
ALL columns at once, updated per batch with a single flattened
scatter-max, merged across devices with an elementwise ``max`` (the
canonical mergeable sketch — SURVEY §2.3).

Hashing happens host-side during Arrow decode (TPUs don't do strings —
SURVEY §7.2), and the device receives PACKED observations: one uint16
per cell holding ``(register_index << 5) | rho`` with 0 as the
null/padding marker.  Packing matters because host→device bandwidth is
the profile scan's scarcest resource — 2 bytes/cell instead of the 9
(two u32 hash lanes + validity byte) an unpacked design ships, with no
information loss: idx needs p ≤ 11 bits and ρ is capped at 31 (register
saturation at ρ=31 bounds estimates only beyond ~2^41 distincts).

Standard error ≈ 1.04/√(2^p): ~2.3% at the default p=11 — matching the
reference's approx_count_distinct default accuracy class.  Small
cardinalities use linear counting (exact in practice), so CONST/UNIQUE
classification stays reliable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray

RHO_BITS = 5
RHO_MAX = 31          # 5-bit field; 0 is the invalid marker
MAX_PRECISION = 11    # idx (11) + rho (5) = 16 bits


def init(n_cols: int, precision: int) -> Array:
    return jnp.zeros((n_cols, 1 << precision), dtype=jnp.int32)


def pack(h64: np.ndarray, valid: Optional[np.ndarray],
         precision: int) -> np.ndarray:
    """Host-side: 64-bit hashes -> packed uint16 observations.

    idx = top ``precision`` bits; ρ = clz of the next 32 bits + 1
    (capped at 31, floored at 1 so packed == 0 iff invalid).
    ``valid=None`` means every row is valid (skips the final mask)."""
    if precision > MAX_PRECISION:
        raise ValueError(f"hll precision > {MAX_PRECISION} cannot pack "
                         f"into uint16")
    idx = (h64 >> np.uint64(64 - precision)).astype(np.uint32)
    b = ((h64 >> np.uint64(64 - precision - 32))
         & np.uint64(0xFFFFFFFF)).astype(np.uint64)
    # clz32 via exact f64 log2 (uint32 is exact in f64)
    bl = np.floor(np.log2((b | np.uint64(1)).astype(np.float64))).astype(
        np.uint32) + 1
    rho = np.clip(33 - bl, 1, RHO_MAX).astype(np.uint32)
    packed = ((idx << RHO_BITS) | rho).astype(np.uint16)
    if valid is None:
        return packed
    return np.where(valid, packed, np.uint16(0))


def update(regs: Array, packed: Array) -> Array:
    """``packed``: (rows, cols) uint16 observations (0 = null/padding).

    The packing precision is implied by ``regs.shape[1]``; observations
    whose index exceeds the register count (a batch packed with a larger
    precision than the registers were allocated for) are routed to the
    spill slot rather than scattered into neighboring columns."""
    n_cols, m = regs.shape
    if n_cols == 0 or packed.shape[1] == 0:
        # empty observation plane: hash columns absent, or the fold
        # happens host-side this run (kernels/hll.HostRegisters) and the
        # plane was never shipped
        return regs
    p32 = packed.astype(jnp.int32)
    idx = p32 >> RHO_BITS
    rho = p32 & RHO_MAX
    valid = (p32 != 0) & (idx < m)
    col_ids = jnp.arange(n_cols, dtype=jnp.int32)[None, :]
    flat_ids = jnp.where(valid, col_ids * m + idx, n_cols * m)  # spill slot
    flat = jnp.zeros((n_cols * m + 1,), dtype=jnp.int32)
    flat = flat.at[flat_ids.reshape(-1)].max(rho.reshape(-1))
    return jnp.maximum(regs, flat[: n_cols * m].reshape(n_cols, m))


def merge(a: Array, b: Array) -> Array:
    return jnp.maximum(a, b)


class HostRegisters:
    """Host-side HLL registers, updated while the packed observations are
    still in host RAM (via the native C++ fold — tpuprof/native).

    Exists because on the target device the register scatter-max is the
    XLA op that serializes (measured ~37ms/batch at 24 hash columns),
    and the observations originate host-side anyway (hashing happens at
    Arrow decode, SURVEY §7.2).  With host registers the packed plane is
    never shipped to the device at all.  Register contents are
    BIT-IDENTICAL to the device path — same packed format, same max
    fold — so estimates, checkpoints and merges are interchangeable.

    ``update`` uses the native library when available and a numpy
    fallback otherwise (slow but correct).  In production the fallback
    is defensive only: both the backend and the streaming profiler gate
    host registers on ``native.available()``, and checkpoint restore
    separately rejects native/pandas hash mismatches (hashes, not
    register folds, are what differ between the implementations)."""

    def __init__(self, n_cols: int, precision: int):
        self.regs = np.zeros((n_cols, 1 << precision), dtype=np.int32)

    def update(self, packed: np.ndarray, nrows: int) -> None:
        from tpuprof import native
        obs = packed[:nrows]
        if obs.size == 0:
            return
        if not native.hll_update(self.regs, obs):
            p32 = obs.astype(np.int32)
            idx = p32 >> RHO_BITS
            rho = p32 & RHO_MAX
            m = self.regs.shape[1]
            for c in range(self.regs.shape[0]):
                ok = (p32[:, c] != 0) & (idx[:, c] < m)
                np.maximum.at(self.regs[c], idx[ok, c], rho[ok, c])

    def merge(self, other: "HostRegisters") -> "HostRegisters":
        np.maximum(self.regs, other.regs, out=self.regs)
        return self


def finalize(regs) -> "object":
    """Host-side HLL estimator with the standard small-range (linear
    counting) correction; float64 estimates per column."""
    import numpy as np

    regs = np.asarray(regs)
    n_cols, m = regs.shape
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(
        m, 0.7213 / (1.0 + 1.079 / m))
    with np.errstate(divide="ignore"):
        raw = alpha * m * m / np.sum(np.exp2(-regs.astype(np.float64)), axis=1)
    zeros = (regs == 0).sum(axis=1)
    linear = np.where(zeros > 0, m * np.log(m / np.maximum(zeros, 1)), raw)
    est = np.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
    return est
