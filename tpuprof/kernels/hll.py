"""HyperLogLog cardinality registers on TPU.

The reference's distinct counts are ``countDistinct`` /
``approx_count_distinct`` Spark jobs (HLL++ inside Spark, one job per
column — SURVEY.md §2.2).  Here: one (cols, 2^p) int32 register plane for
ALL columns at once, updated per batch with a single flattened
scatter-max, merged across devices with an elementwise ``max`` (the
canonical mergeable sketch — SURVEY §2.3).

Hashing happens host-side during Arrow decode (TPUs don't do strings —
SURVEY §7.2): each value arrives as two independent uint32 lanes of a
64-bit hash.  Lane A supplies the register index (top p bits); lane B
supplies ρ = clz+1 via ``lax.clz``.  Effective hash width p+32 bits, so
the estimator stays unsaturated far beyond 10⁹ distincts.

Standard error ≈ 1.04/√(2^p): ~2.3% at the default p=11 — matching the
reference's approx_count_distinct default accuracy class.  Small
cardinalities use linear counting (exact in practice), so CONST/UNIQUE
classification stays reliable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def init(n_cols: int, precision: int) -> Array:
    return jnp.zeros((n_cols, 1 << precision), dtype=jnp.int32)


def update(regs: Array, hash_a: Array, hash_b: Array, hvalid: Array,
           precision: int) -> Array:
    """``hash_a``/``hash_b``: (rows, cols) uint32 lanes; ``hvalid``:
    (rows, cols) bool (False for nulls and padding)."""
    n_cols, m = regs.shape
    idx = (hash_a >> (32 - precision)).astype(jnp.int32)        # (rows, cols)
    rho = (jax.lax.clz(hash_b.astype(jnp.int32)) + 1).astype(jnp.int32)
    rho = jnp.where(hvalid, rho, 0)
    col_ids = jnp.arange(n_cols, dtype=jnp.int32)[None, :]
    flat_ids = jnp.where(hvalid, col_ids * m + idx, n_cols * m)  # spill slot
    flat = jnp.zeros((n_cols * m + 1,), dtype=jnp.int32)
    flat = flat.at[flat_ids.reshape(-1)].max(rho.reshape(-1))
    return jnp.maximum(regs, flat[: n_cols * m].reshape(n_cols, m))


def merge(a: Array, b: Array) -> Array:
    return jnp.maximum(a, b)


def finalize(regs) -> "object":
    """Host-side HLL estimator with the standard small-range (linear
    counting) correction; float64 estimates per column."""
    import numpy as np

    regs = np.asarray(regs)
    n_cols, m = regs.shape
    alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(
        m, 0.7213 / (1.0 + 1.079 / m))
    with np.errstate(divide="ignore"):
        raw = alpha * m * m / np.sum(np.exp2(-regs.astype(np.float64)), axis=1)
    zeros = (regs == 0).sum(axis=1)
    linear = np.where(zeros > 0, m * np.log(m / np.maximum(zeros, 1)), raw)
    est = np.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
    return est
