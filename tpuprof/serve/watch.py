"""Continuous drift watch inside the serve daemon (ROBUSTNESS.md rung 6,
ROADMAP item 1 remainder (a) / item 4 "next rung").

``tpuprof watch SPOOL SOURCE ...`` turns the resident daemon from
"profiles when asked" into "watches its own data": per watched source,
a :class:`DriftWatcher` re-profiles on a configured cadence THROUGH the
existing scheduler (one warm mesh, the same quota/queue machinery every
`tpuprof submit` job uses), persists each cycle as a ``tpuprof-stats-v1``
artifact (tpuprof/artifact), diffs consecutive cycles with the drift
engine, and raises alerts when PSI/KS/schema bands cross
:class:`~tpuprof.artifact.DriftThresholds`.

Continuous operation is the robustness core — a watch loop that runs
for weeks meets every failure a one-shot profile meets, plus its own:

* **Per-job watchdog** — the scheduler wraps each job body in
  ``guard.watched(job_timeout_s)`` (serve/scheduler.py), so a hung
  profile raises :class:`WatchdogTimeout`, frees the worker, and fails
  THAT job with exit-code-4 semantics instead of wedging the daemon.
* **Crash-safe recovery** — watch state (cycle counter, baseline
  artifact path, alert dedup cursor) persists in a CRC-sealed,
  atomically-written *watch manifest* per source.  A torn/truncated
  manifest is the typed :class:`CorruptManifestError` — never a raw
  JSON error — and the restore path degrades to rebuilding state from
  the retained artifact chain on disk, recording an alert.  (Spool jobs
  with no result are re-run by the daemon itself — serve/server.py.)
* **Artifact retention** — ``artifact_keep`` cycle artifacts per source
  rotate on disk; the drift-baseline read walks past a corrupt head to
  the newest good generation, exactly as checkpoint restore does.
* **Degraded-cycle semantics** — a cycle whose profile fails (poison
  data, watchdog kill, torn artifact, injected fault) records a
  ``failed_cycle`` alert and the watch CONTINUES; the baseline stays at
  the last good cycle, so the next comparison is still meaningful.

What the operator sees: ``drift_alert`` JSONL events,
``tpuprof_drift_alerts_total{severity}`` /
``tpuprof_watch_cycles_total{status}`` metrics, and a pollable
``alerts.json`` per watched source (OBSERVABILITY.md "Continuous drift
watch").

Layout under the spool dir::

    watch/<key>/manifest.json              CRC-sealed watch state
    watch/<key>/cycle_<n>.artifact.json    retained cycle artifacts
    watch/<key>/alerts.json                the operator-pollable
                                           alert feed (newest last,
                                           capped at ALERTS_CAP)

where ``<key>`` is the source basename plus a short path hash — stable
across restarts, collision-free across sources with one name.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence

from tpuprof.errors import (CorruptArtifactError, CorruptManifestError,
                            TYPED_ERRORS, WarehouseUnavailableError,
                            exit_code)
from tpuprof.obs import blackbox
from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.serve.jobs import DONE
from tpuprof.testing import faults as _faults

WATCH_MANIFEST_SCHEMA = "tpuprof-watch-manifest-v1"

# the alert feed is an operator surface, not an archive: the JSONL
# event stream (and the metrics counters) keep the full history
ALERTS_CAP = 256

_CYCLES = _obs_metrics.counter(
    "tpuprof_watch_cycles_total",
    "drift-watch cycles by outcome (ok|warn|drift|failed)")
_ALERTS = _obs_metrics.counter(
    "tpuprof_drift_alerts_total",
    "drift-watch alerts raised, by severity (warn|drift|failed)")
_CYCLE_SECONDS = _obs_metrics.histogram(
    "tpuprof_watch_cycle_seconds",
    "wall seconds per watch cycle (submit -> alert decision)")
_FALLBACKS = _obs_metrics.counter(
    "tpuprof_watch_artifact_fallbacks_total",
    "baseline reads that walked past a corrupt retained artifact head")

# canonical serialization the manifest CRC covers — the artifact
# store's idiom: key-sorted, no whitespace, so any parsed-value change
# changes these bytes
_CANON = {"sort_keys": True, "separators": (",", ":")}

_CYCLE_RE = re.compile(r"cycle_(\d{8})\.artifact\.json$")

#: how many flagged column names ride one drift alert (and its episode
#: dedup key) — the feed is an operator surface, not a column dump
ALERT_COLUMNS_CAP = 16


def drift_alert_shape(drift: Dict[str, Any]):
    """One drift report -> ``(status, flagged_columns)``: the verdict
    plus the capped, sorted column list an alert (and its episode dedup
    key) carries.  The ONE definition the live watch loop and the
    warehouse backtester (tpuprof/warehouse/backtest.py) both speak —
    a replay that derived the shape its own way could never promise to
    reproduce the live alert set exactly."""
    status = drift["summary"]["verdict"]
    flagged = sorted(c for c, e in drift["columns"].items()
                     if e["status"] != "ok")
    return status, flagged[:ALERT_COLUMNS_CAP]


def drift_episode_key(severity: str, columns) -> List[Any]:
    """The episode dedup key: the SAME ongoing drift (same severity,
    same flagged set) alerts once, not every cycle."""
    return ["drift", severity, list(columns or [])]


def source_key(source: Any) -> str:
    """Stable per-source directory name: sanitized basename + a short
    hash of the absolute path (two sources named ``data.parquet`` in
    different directories must not share watch state)."""
    text = str(source)
    base = re.sub(r"[^A-Za-z0-9._-]+", "_",
                  os.path.basename(text.rstrip("/")) or "source")
    digest = hashlib.sha1(os.path.abspath(text).encode()).hexdigest()[:8]
    return f"{base}-{digest}"


def _atomic_write(path: str, data: bytes) -> None:
    # dot-prefixed temp (ISSUE 12 durability invariant): the watch dir
    # is scanned (_CYCLE_RE chain walk, .part stray sweep) and a
    # suffix-named temp would share the scanned prefix
    tmp = os.path.join(os.path.dirname(path) or ".",
                       f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)


def write_manifest(path: str, state: Dict[str, Any]) -> None:
    """Atomically persist one source's watch state, CRC-sealed so a
    torn write can never be mistaken for a valid cursor."""
    core = {"schema": WATCH_MANIFEST_SCHEMA}
    core.update(state)
    doc = dict(core)
    doc["integrity"] = {
        "algorithm": "crc32/canonical-json",
        "crc32": zlib.crc32(json.dumps(core, **_CANON).encode())
        & 0xFFFFFFFF,
    }
    _atomic_write(path, json.dumps(doc, indent=1).encode())


def read_manifest(path: str) -> Dict[str, Any]:
    """Read + integrity-check a watch manifest.  A genuinely missing
    file raises ``FileNotFoundError`` ("first cycle ever"); EVERY other
    failure — truncation at any offset, bit rot, junk, a foreign
    schema — is the typed :class:`CorruptManifestError`."""
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        raise
    except OSError as exc:
        raise CorruptManifestError(
            f"watch manifest {path!r} is unreadable "
            f"({type(exc).__name__}: {exc})") from exc
    try:
        doc = json.loads(data)
    except Exception as exc:
        raise CorruptManifestError(
            f"watch manifest {path!r} is not valid JSON — truncated or "
            f"corrupt ({type(exc).__name__}: {exc})") from exc
    if not isinstance(doc, dict) or doc.get("schema") != WATCH_MANIFEST_SCHEMA:
        raise CorruptManifestError(
            f"watch manifest {path!r} has schema "
            f"{doc.get('schema') if isinstance(doc, dict) else type(doc).__name__!r}; "
            f"this build reads {WATCH_MANIFEST_SCHEMA!r}")
    integrity = doc.pop("integrity", None)
    if not isinstance(integrity, dict) or "crc32" not in integrity:
        raise CorruptManifestError(
            f"watch manifest {path!r} lacks its integrity envelope — "
            "torn or hand-edited")
    canon = json.dumps(doc, **_CANON).encode()
    if zlib.crc32(canon) & 0xFFFFFFFF != integrity["crc32"]:
        raise CorruptManifestError(
            f"watch manifest {path!r} CRC mismatch — corrupt manifest")
    return doc


class SourceWatch:
    """One watched source's durable state: the cycle counter, the
    baseline artifact, the alert dedup cursor, and the retained
    artifact chain on disk."""

    def __init__(self, root: str, source: Any, keep: int):
        self.source = str(source)
        self.key = source_key(source)
        self.dir = os.path.join(root, self.key)
        os.makedirs(self.dir, exist_ok=True)
        self.keep = max(int(keep), 1)
        self.cycle = 0                      # completed (or failed) cycles
        self.last_artifact: Optional[str] = None
        self.alert_seq = 0
        self.last_alert_key: Optional[List[Any]] = None
        self.alerts: List[Dict[str, Any]] = []
        self.recovered: Optional[str] = None   # degraded-restore note

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.dir, "manifest.json")

    @property
    def alerts_path(self) -> str:
        return os.path.join(self.dir, "alerts.json")

    def artifact_path(self, cycle: int) -> str:
        return os.path.join(self.dir, f"cycle_{cycle:08d}.artifact.json")

    def chain(self) -> List[tuple]:
        """Retained ``(cycle, path)`` artifacts, newest first."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            m = _CYCLE_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.dir, name)))
        return sorted(out, reverse=True)

    # -- crash-safe restore -------------------------------------------------

    def restore(self) -> None:
        """Adopt the on-disk state: the manifest when it verifies, else
        (torn manifest — the typed path) a degraded rebuild from the
        retained artifact chain, noted on :attr:`recovered` so the
        watcher records it as an alert."""
        try:
            doc = read_manifest(self.manifest_path)
            self.cycle = int(doc.get("cycle") or 0)
            self.last_artifact = doc.get("last_artifact")
            self.alert_seq = int(doc.get("alert_seq") or 0)
            key = doc.get("last_alert_key")
            self.last_alert_key = list(key) if key is not None else None
        except FileNotFoundError:
            # fresh source — unless artifacts exist with no manifest (a
            # crash before the very first manifest write): adopt the
            # chain so cycle numbers never collide
            self._rebuild_from_chain(reason=None)
        except CorruptManifestError as exc:
            self._rebuild_from_chain(
                reason=f"{type(exc).__name__}: {exc}")
        # the alert feed is advisory: restore best-effort, never fatal
        try:
            with open(self.alerts_path) as fh:
                alerts = json.load(fh)
            if isinstance(alerts, list):
                self.alerts = alerts[-ALERTS_CAP:]
        except (OSError, ValueError):
            pass
        self.alert_seq = max(
            self.alert_seq,
            max((int(a.get("seq") or 0) for a in self.alerts
                 if isinstance(a, dict)), default=0))

    def _rebuild_from_chain(self, reason: Optional[str]) -> None:
        chain = self.chain()
        self.cycle = chain[0][0] if chain else 0
        self.last_artifact = None       # baseline() re-walks the chain
        self.alert_seq = 0              # re-derived from alerts.json
        self.last_alert_key = None
        if reason:
            self.recovered = reason

    def baseline(self, before: Optional[int] = None):
        """The newest READABLE retained artifact (the drift comparison
        base), walking past corrupt heads the way checkpoint restore
        walks its generations.  ``before`` excludes the cycle currently
        being produced.  Returns the Artifact or None (first cycle /
        fully-corrupt chain)."""
        from tpuprof.artifact import read_artifact
        for cyc, path in self.chain():
            if before is not None and cyc >= before:
                continue
            try:
                art = read_artifact(path)
            except (CorruptArtifactError, OSError) as exc:
                _FALLBACKS.inc()
                blackbox.record("watch_artifact_fallback",
                                source=self.source, path=path,
                                error=f"{type(exc).__name__}: {exc}")
                continue
            self.last_artifact = path
            return art
        self.last_artifact = None
        return None

    def rotate(self) -> None:
        """Retention: keep the newest ``keep`` cycle artifacts, and
        sweep stray ``.part`` files left by failed/abandoned cycles
        (only the watcher renames a .part into the chain, so at rotate
        time — a cycle just succeeded — none is in flight)."""
        for _cyc, path in self.chain()[self.keep:]:
            try:
                os.unlink(path)
            except OSError:
                pass
        try:
            strays = [n for n in os.listdir(self.dir)
                      if n.endswith(".artifact.json.part")]
        except OSError:
            strays = []
        for name in strays:
            try:
                os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass


class DriftWatcher:
    """The watch loop: per source, re-profile -> persist -> diff ->
    alert, on a cadence, forever (or ``cycles`` times in CI mode)."""

    def __init__(self, spool: str, sources: Sequence[Any], scheduler,
                 every_s: Optional[float] = None,
                 keep: Optional[int] = None,
                 thresholds=None,
                 job_timeout_s: Optional[float] = None,
                 config_kwargs: Optional[Dict[str, Any]] = None,
                 tenant: str = "watch",
                 warehouse_dir: Optional[str] = None,
                 warehouse_format: Optional[str] = None):
        from tpuprof.artifact import DriftThresholds
        from tpuprof.config import (resolve_artifact_keep,
                                    resolve_job_timeout,
                                    resolve_warehouse_dir,
                                    resolve_warehouse_format,
                                    resolve_watch_every)
        if not sources:
            raise ValueError("watch needs at least one source")
        self.spool = spool
        self.root = os.path.join(spool, "watch")
        os.makedirs(self.root, exist_ok=True)
        self.scheduler = scheduler
        self.every_s = resolve_watch_every(every_s)
        self.keep = resolve_artifact_keep(keep)
        self.thresholds = thresholds or DriftThresholds()
        self.job_timeout_s = resolve_job_timeout(job_timeout_s)
        self.config_kwargs = dict(config_kwargs or {})
        self.tenant = str(tenant)
        # the columnar warehouse (tpuprof/warehouse): the watch loop is
        # its primary feeder, so — unlike the one-shot CLI — the dir
        # defaults ON, under the spool.  warehouse_format=off is the
        # opt-out (and the pyarrow-free mode); a missing pyarrow
        # degrades to off at first use, loudly, without failing cycles.
        if resolve_warehouse_format(warehouse_format) == "off":
            self.warehouse_dir: Optional[str] = None
        else:
            self.warehouse_dir = resolve_warehouse_dir(warehouse_dir) \
                or os.path.join(spool, "warehouse")
        self.stop_event = threading.Event()
        self.counts = {"ok": 0, "warn": 0, "drift": 0, "failed": 0}
        self.watches: List[SourceWatch] = []
        for src in sources:
            w = SourceWatch(self.root, src, self.keep)
            w.restore()
            self.watches.append(w)
            if w.recovered:
                # the manifest was torn: state was rebuilt from the
                # artifact chain — continuity is degraded (the alert
                # cursor restarted), and the operator must know
                self._alert(w, kind="corrupt_manifest",
                            severity="failed", cycle=w.cycle,
                            error=w.recovered)

    # -- one cycle ----------------------------------------------------------

    def run_cycle(self, w: SourceWatch) -> Dict[str, Any]:
        """Profile ``w.source`` once through the scheduler, persist the
        artifact, diff vs the baseline, alert, rotate, seal the
        manifest.  NEVER raises on a failing cycle — degraded-cycle
        semantics: the failure becomes a ``failed_cycle`` alert and the
        watch continues (the daemon's reason to exist is the NEXT
        cycle)."""
        t0 = time.perf_counter()
        cycle = w.cycle + 1
        art_path = w.artifact_path(cycle)
        # the job writes a job-PRIVATE .part file; the artifact enters
        # the retained chain only through the watcher's validate+rename
        # on confirmed success.  A watchdog-abandoned job body that
        # wakes up later and finishes its write can then never
        # resurrect a failed cycle's artifact into the chain (found
        # driving the chaos gauntlet: the abandoned thread's late write
        # landed AFTER the failure path's unlink and became the newest
        # "good" baseline).
        part_path = art_path + ".part"
        status = "ok"
        extra: Dict[str, Any] = {}
        try:
            _faults.hit("watch_cycle", key=cycle)
            kwargs = dict(self.config_kwargs)
            if self.job_timeout_s is not None:
                kwargs.setdefault("job_timeout_s", self.job_timeout_s)
            seed = self._seed_artifact(w)
            if seed is not None:
                # cycle N seeds its provisional bin edges from cycle
                # N−1's artifact (runtime/singlepass.py): with
                # profile_passes=fused an undrifted source's cycle is
                # ONE scan — the watch loop is the hit-rate-1.0 case
                # by construction.  Harmless under two_pass (ignored).
                kwargs.setdefault("seed_edges", seed)
            job = self.scheduler.submit(
                source=w.source, tenant=self.tenant, artifact=part_path,
                config_kwargs=kwargs)
            # the per-job watchdog is the hang protection; this wait
            # deadline only bounds the watcher when one is configured
            wait_s = None if self.job_timeout_s is None \
                else self.job_timeout_s + 600.0
            job = self.scheduler.wait(job, timeout=wait_s)
            if job.state != DONE:
                err = RuntimeError(
                    f"profile job {job.state}: {job.error}")
                err.exit_code = job.exit_code   # type: ignore[attr-defined]
                raise err
            from tpuprof.artifact import compute_drift, read_artifact
            baseline = w.baseline(before=cycle)
            current = read_artifact(part_path)   # torn write -> typed
            os.replace(part_path, art_path)      # admit to the chain
            current.path = art_path
            if baseline is not None:
                drift = compute_drift(baseline, current, self.thresholds)
                s = drift["summary"]
                # the alert shape (verdict + capped flagged set) is the
                # shared definition the warehouse backtester replays
                status, flagged = drift_alert_shape(drift)
                extra = {"n_drift": s["n_drift"], "n_warn": s["n_warn"],
                         "row_delta": s["row_delta"]}
                if status == "ok":
                    # drift cleared: the next episode re-alerts
                    w.last_alert_key = None
                else:
                    self._alert(w, kind="drift", severity=status,
                                cycle=cycle, verdict=status,
                                n_drift=s["n_drift"],
                                n_warn=s["n_warn"],
                                columns=flagged,
                                baseline=baseline.path,
                                artifact=art_path)
            w.cycle = cycle
            w.last_artifact = art_path
            w.rotate()
            # append the columnar generation AFTER the JSON artifact is
            # admitted: the warehouse is derived truth — advisory to
            # the cycle (its failure can never fail a cycle), but the
            # JSON chain rotates at `keep` while this history only grows
            self._warehouse_append(w, current, cycle)
        except Exception as exc:        # noqa: BLE001 — a watch survives
            status = "failed"
            # the failed cycle's .part (absent, partial, or torn) is
            # worthless — drop it; a late write by an abandoned job
            # body leaves only a stray .part, which rotate() sweeps
            try:
                os.unlink(part_path)
            except OSError:
                pass
            code = getattr(exc, "exit_code", None)
            if code is None:
                code = exit_code(exc) if isinstance(exc, TYPED_ERRORS) \
                    else 1
            self._alert(w, kind="failed_cycle", severity="failed",
                        cycle=cycle,
                        error=f"{type(exc).__name__}: {exc}",
                        exit_code=code)
            w.cycle = cycle             # failed cycles count: artifact
                                        # names stay collision-free and
                                        # the cadence accounting honest
        seconds = time.perf_counter() - t0
        self.counts[status] = self.counts.get(status, 0) + 1
        if _obs_metrics.enabled():
            _CYCLES.inc(status=status)
            _CYCLE_SECONDS.observe(seconds)
        _obs_events.emit("watch_cycle", source=w.source, cycle=cycle,
                         status=status, seconds=round(seconds, 4),
                         artifact=w.last_artifact, **extra)
        self._save(w)
        return {"source": w.source, "cycle": cycle, "status": status,
                "seconds": seconds, **extra}

    def _seed_artifact(self, w: SourceWatch) -> Optional[str]:
        """The newest retained artifact path — the edge seed for the
        next cycle's fused profile.  Path-level only (no read here):
        the profile's seeder validates and degrades to the first-batch
        sketch if the file is torn, so a corrupt head can never fail a
        cycle through this seam."""
        if w.last_artifact and os.path.exists(w.last_artifact):
            return w.last_artifact
        chain = w.chain()
        return chain[0][1] if chain else None

    def _warehouse_append(self, w: SourceWatch, artifact,
                          cycle: int) -> None:
        """Feed the columnar warehouse (never raises — the cycle's
        truth is the JSON chain; the warehouse is the queryable twin)."""
        if self.warehouse_dir is None:
            return
        try:
            from tpuprof.warehouse import append_artifact
            append_artifact(self.warehouse_dir, artifact,
                            source=w.source, generation=cycle)
        except WarehouseUnavailableError as exc:
            # no pyarrow on this box: degrade to warehouse_format=off
            # for the rest of the run — once, loudly, cycles unharmed
            blackbox.record("warehouse_unavailable", error=str(exc))
            self.warehouse_dir = None
        except Exception as exc:    # noqa: BLE001 — a watch survives
            blackbox.record("warehouse_write_failed", source=w.source,
                            cycle=cycle,
                            error=f"{type(exc).__name__}: {exc}")

    # -- alerts -------------------------------------------------------------

    def _alert(self, w: SourceWatch, *, kind: str, severity: str,
               cycle: int, **fields) -> Optional[Dict[str, Any]]:
        key = drift_episode_key(severity, fields.get("columns"))
        if kind == "drift" and w.last_alert_key == key:
            # dedup: the SAME ongoing drift episode (same severity, same
            # column set) does not re-alert every cycle — the cycle
            # record still carries the verdict, and any change in shape
            # (new column, warn->drift) is a new alert.  The dedup key
            # rides the manifest, so a restart does not re-fire it.
            return None
        w.alert_seq += 1
        alert = {"seq": w.alert_seq, "ts": round(time.time(), 3),
                 "source": w.source, "cycle": cycle, "kind": kind,
                 "severity": severity}
        alert.update(fields)
        w.alerts.append(alert)
        w.alerts = w.alerts[-ALERTS_CAP:]
        if kind == "drift":
            w.last_alert_key = key
        if _obs_metrics.enabled():
            _ALERTS.inc(severity=severity)
        # the JSONL twin ("kind" is the event discriminator, so the
        # alert's own kind rides as "alert")
        _obs_events.emit("drift_alert", alert=alert["kind"],
                         **{k: v for k, v in alert.items()
                            if k not in ("ts", "kind")})
        try:
            _atomic_write(w.alerts_path,
                          json.dumps(w.alerts, indent=1,
                                     default=str).encode())
        except OSError:
            pass        # the feed is best-effort; events/metrics rule
        return alert

    def _save(self, w: SourceWatch) -> None:
        write_manifest(w.manifest_path, {
            "source": w.source,
            "cycle": w.cycle,
            "last_artifact": w.last_artifact,
            "alert_seq": w.alert_seq,
            "last_alert_key": w.last_alert_key,
            "keep": w.keep,
            "updated_unix": round(time.time(), 3),
        })

    # -- the loop -----------------------------------------------------------

    def run(self, cycles: Optional[int] = None) -> None:
        """Watch until :attr:`stop_event` (or for ``cycles`` rounds over
        every source — the CI/bench mode)."""
        done = 0
        while not self.stop_event.is_set():
            for w in self.watches:
                if self.stop_event.is_set():
                    return
                self.run_cycle(w)
            done += 1
            if cycles is not None and done >= cycles:
                return
            self.stop_event.wait(self.every_s)

    def stats(self) -> Dict[str, Any]:
        return {
            "sources": len(self.watches),
            "cycles": dict(self.counts),
            "alerts": sum(len(w.alerts) for w in self.watches),
            "every_s": self.every_s,
            "keep": self.keep,
        }
