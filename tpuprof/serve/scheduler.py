"""Request scheduler — job lifecycle owner for `tpuprof serve`.

The ROADMAP-named refactor: job orchestration moves OUT of the CLI.
``cmd_profile`` keeps its one-shot path (byte-unchanged), but a
long-lived service admits requests through :class:`ProfileScheduler`:
a bounded multi-tenant queue (serve/jobs.py), N worker threads sharing
ONE warm mesh via the keyed runner cache (serve/cache.py), and the
existing obs/heartbeat machinery as the SLO layer — request counters by
status, queue-depth gauge, an end-to-end latency histogram (p50/p99),
and a ``serve_job`` JSONL event per terminal job.  The CLI becomes one
client among many: `tpuprof submit` (serve/server.py) talks to the same
scheduler a library embedding would.

Fault story: each job runs the SAME ProfileReport path the one-shot CLI
runs, so the PR-4 degradation ladder (retries, quarantine, watchdogs,
checkpoint fallback) applies per job, and a typed failure marks THAT
job failed with its CLI exit code — the daemon and its other tenants
keep serving.  SIGUSR1 postmortems include the live queue snapshot via
the flight recorder's context-provider hook (obs/blackbox.py).
"""

from __future__ import annotations

import collections
import json
import threading
from typing import Any, Dict, List, Optional, Sequence

from tpuprof.obs import blackbox
from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.serve import cache as _cache
from tpuprof.serve.jobs import (DONE, FAILED, QUEUED, REJECTED, RUNNING,
                                TERMINAL, BacklogFull, Job, JobQueue,
                                QueueClosed, QueueFull,
                                TenantQuotaExceeded, percentile)

_REQUESTS = _obs_metrics.counter(
    "tpuprof_serve_requests_total",
    "profile requests by terminal status (done|failed|rejected)")
_QUEUE_DEPTH = _obs_metrics.gauge(
    "tpuprof_serve_queue_depth",
    "jobs waiting in the serve admission queue")
_ACTIVE = _obs_metrics.gauge(
    "tpuprof_serve_active_jobs", "jobs currently profiling on the mesh")
_JOB_SECONDS = _obs_metrics.histogram(
    "tpuprof_serve_job_seconds",
    "end-to-end job latency (enqueue -> terminal), queue wait included "
    "— the p50/p99 SLO series")
_COALESCED = _obs_metrics.counter(
    "tpuprof_coalesced_jobs_total",
    "submits that collapsed onto an in-flight same-key job (read tier "
    "— exactly-once compute, N fanned-out results)")
_SHED = _obs_metrics.counter(
    "tpuprof_requests_shed_total",
    "non-cacheable submits shed at admission because the queued-compute "
    "depth crossed serve_backlog (HTTP 503 + jittered Retry-After) — "
    "overload degrading to reads-only, by design")
_DEADLINE_EXPIRED = _obs_metrics.counter(
    "tpuprof_deadline_expired_total",
    "queued jobs whose client deadline (X-Tpuprof-Deadline-Ms / "
    "--deadline-ms) expired before a worker reached them — never "
    "started, failed with DeadlineExceededError (exit 11)")


class ProfileScheduler:
    """N worker threads draining a bounded multi-tenant job queue
    through one process-wide warm mesh."""

    def __init__(self, workers: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 job_timeout_s: Optional[float] = None,
                 aot_cache_dir: Optional[str] = None,
                 read_cache: Optional[str] = None,
                 read_cache_entries: Optional[int] = None,
                 read_cache_bytes: Optional[int] = None,
                 serve_backlog: Optional[int] = None,
                 devices: Optional[Sequence] = None):
        from tpuprof.config import (resolve_aot_cache_dir,
                                    resolve_job_timeout,
                                    resolve_read_cache,
                                    resolve_read_cache_bytes,
                                    resolve_read_cache_entries,
                                    resolve_serve_backlog,
                                    resolve_serve_queue_depth,
                                    resolve_serve_tenant_quota,
                                    resolve_serve_workers)
        self.workers = resolve_serve_workers(workers)
        # overload shed budget (ISSUE 19): 0 = off — only the hard
        # queue-depth 429 bound applies, the historical behavior
        self.serve_backlog = resolve_serve_backlog(serve_backlog)
        # the read tier (ISSUE 16) is OPT-IN at this layer: a scheduler
        # that was not handed a read_cache mode keeps the historical
        # every-submit-computes behavior (the property every pre-16
        # contention/steal test pins); `tpuprof serve` resolves the
        # product default ("on") at the CLI
        self.read_cache = None
        if read_cache is not None \
                and resolve_read_cache(read_cache) == "on":
            self.read_cache = _cache.ResultCache(
                resolve_read_cache_entries(read_cache_entries),
                resolve_read_cache_bytes(read_cache_bytes))
        # daemon-level AOT executable-cache root (runtime/aot.py): a
        # job that says nothing about its own store inherits it, so
        # every serve/watch job's runner key feeds the same restart-
        # to-warm store; a job's explicit aot_* fields win
        self.aot_cache_dir = resolve_aot_cache_dir(aot_cache_dir)
        # daemon-level default for jobs that say nothing about their
        # own timeout; a job's explicit job_timeout_s override wins
        self.job_timeout_s = resolve_job_timeout(job_timeout_s)
        self._queue = JobQueue(resolve_serve_queue_depth(queue_depth),
                               resolve_serve_tenant_quota(tenant_quota))
        self._devices = devices
        self._lock = threading.Lock()
        self._done_cond = threading.Condition(self._lock)
        self._jobs: Dict[str, Job] = {}
        self._active: Dict[str, Job] = {}
        self._by_key: Dict[Any, Job] = {}   # in-flight coalescing table:
                                            # (source fp, config fp) ->
                                            # the one computing primary
        self._computed = 0          # jobs that actually ran the mesh
        self._coalesced = 0         # submits that rode another's compute
        self._shed = 0              # submits shed past serve_backlog
        self._deadline_expired = 0  # queued jobs dead before a worker
        self._cancelled = 0         # client-disconnect cancellations
        self._released = 0          # drain handoffs to fleet peers
        self._counts = {DONE: 0, FAILED: 0, REJECTED: 0}
        self._latencies: "collections.deque[float]" = \
            collections.deque(maxlen=4096)   # done jobs only (SLO view)
        self._submitted = 0
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"tpuprof-serve-{i}")
            for i in range(self.workers)]
        for t in self._threads:
            t.start()
        # SIGUSR1 postmortems must carry the live queue (ISSUE 9
        # satellite): the provider is invoked at DUMP time, so the
        # snapshot is current, not a stale periodic copy
        self._context_provider = lambda: {"serve_queue": self.snapshot()}
        blackbox.register_context_provider(self._context_provider)

    # -- admission ---------------------------------------------------------

    def submit(self, job: Optional[Job] = None, **kwargs) -> Job:
        """Admit one job (a prebuilt :class:`Job` or its kwargs).
        Admission failures — full queue, tenant over quota, an invalid
        config — return the job in the ``rejected`` state with the
        reason on ``job.error``; they never raise, because a service
        answers requests, it does not crash on them."""
        if job is None:
            job = Job(**kwargs)
        try:
            job._config = self._build_config(job)
            # read tier (ISSUE 16): a side-effect-free repeat answers
            # from the result cache, and a concurrent same-key submit
            # rides the in-flight compute — neither touches the queue
            key = self._coalesce_key(job)
            if key is not None:
                if self._attach_follower(key, job):
                    return job
                hit = self.read_cache.get(key)
                if hit is not None:
                    return self._answer_from_cache(job, hit[0])
                # the probe missed: claim the primary slot atomically
                # with a re-check, so K racing submits elect exactly
                # one computer (the rest attach)
                with self._lock:
                    primary = self._by_key.get(key)
                    if primary is not None \
                            and primary.state not in TERMINAL:
                        return self._attach_locked(primary, key, job)
                    self._by_key[key] = job
                    job._key = key
            # overload shed (ISSUE 19): past the backlog budget a
            # non-cacheable submit is refused BEFORE the queue — the
            # cache-hit and coalescing returns above never reach here,
            # so the read tier keeps answering while compute degrades
            if self.serve_backlog \
                    and len(self._queue) >= self.serve_backlog:
                raise BacklogFull(
                    f"serve backlog budget exhausted "
                    f"({len(self._queue)} queued >= serve_backlog="
                    f"{self.serve_backlog}) — compute admission is "
                    "shedding while reads keep serving; retry after "
                    "the drain")
            self._queue.admit(job)
        except (QueueFull, TenantQuotaExceeded, QueueClosed,
                BacklogFull, ValueError, TypeError) as exc:
            # the admission hook the HTTP edge (serve/http.py) maps to
            # status codes: quota/depth rejections are 429 (retry
            # later), a closing queue is 503, a shed is 503 WITH a
            # Retry-After, everything else is the request's own fault
            # (400)
            if isinstance(exc, BacklogFull):
                with self._lock:
                    self._shed += 1
                _SHED.inc()
            job.reject_kind = type(exc).__name__
            job.to(REJECTED, error=str(exc))
            with self._lock:
                self._submitted += 1
                self._jobs[job.id] = job
                self._counts[REJECTED] += 1
                if job._key is not None \
                        and self._by_key.get(job._key) is job:
                    del self._by_key[job._key]
            self._record_terminal(job)
            # a follower that attached in the claim->admit window must
            # not wait on a job that will never run
            self._fan_out(job)
            return job
        with self._lock:
            self._submitted += 1
            self._jobs[job.id] = job
        _QUEUE_DEPTH.set(len(self._queue))
        return job

    def _coalesce_key(self, job: Job):
        """The read-tier identity of a submit — or None when the tier
        is off or the job has side effects.  A job that writes an
        output/report/artifact must RUN (the write IS the product);
        only pure "profile and answer" submits are cacheable and
        coalescible."""
        if self.read_cache is None:
            return None
        if job.output or job.stats_json or job.artifact \
                or job.config_kwargs.get("artifact_path"):
            return None
        return (_cache.source_fingerprint(job.source),
                job._config.fingerprint())

    def _attach_follower(self, key, job: Job) -> bool:
        with self._lock:
            primary = self._by_key.get(key)
            if primary is None or primary.state in TERMINAL:
                return False
            self._attach_locked(primary, key, job)
            return True

    def _attach_locked(self, primary: Job, key, job: Job) -> Job:
        primary._followers.append(job)
        job.coalesced_with = primary.id
        job._key = key
        self._submitted += 1
        self._jobs[job.id] = job
        self._coalesced += 1
        _COALESCED.inc()
        return job

    def _answer_from_cache(self, job: Job, payload: bytes) -> Job:
        """Terminal bookkeeping for a result-cache hit: the job never
        queues, never runs, never touches a tenant slot — it is DONE at
        admission with the cached answer."""
        job.read_cache = "hit"
        job.result = dict(json.loads(payload.decode()))
        job.to(RUNNING)
        job.to(DONE)
        with self._done_cond:
            self._submitted += 1
            self._jobs[job.id] = job
            self._counts[DONE] += 1
            if job.seconds is not None:
                self._latencies.append(job.seconds)
            self._done_cond.notify_all()
        self._record_terminal(job)
        return job

    def _fan_out(self, job: Job) -> None:
        """Copy the primary's terminal state onto every follower that
        coalesced onto it — N byte-identical answers from one compute.
        Runs after the primary's own terminal bookkeeping; each
        follower gets its own terminal record/event."""
        while True:
            with self._done_cond:
                if not job._followers:
                    return
                followers = job._followers[:]
                del job._followers[:len(followers)]
            for f in followers:
                f.cache_hit = job.cache_hit
                f.to(RUNNING)
                if job.state == DONE:
                    f.result = dict(job.result)
                    f.to(DONE)
                else:
                    f.to(FAILED, error=job.error,
                         exit_code=job.exit_code)
                with self._done_cond:
                    self._counts[f.state] += 1
                    if f.state == DONE and f.seconds is not None:
                        self._latencies.append(f.seconds)
                    self._done_cond.notify_all()
                self._record_terminal(f)

    def _build_config(self, job: Job):
        """Validate the job's config overrides NOW (admission time):
        a typo'd option must reject in milliseconds, not fail a queued
        job minutes later.  Unknown keys reject explicitly — the
        from_kwargs ignore-unknowns tolerance is a library nicety, but
        a service silently dropping an option would profile the wrong
        thing with a straight face."""
        import dataclasses

        from tpuprof.config import ProfilerConfig
        kwargs = dict(job.config_kwargs)
        backend = kwargs.pop("backend", "tpu")
        if backend != "tpu":
            raise ValueError(
                f"serve jobs run the tpu engine (got backend="
                f"{backend!r}): the warm mesh and compiled-program "
                "cache ARE the service; the cpu oracle has nothing to "
                "keep warm")
        known = {f.name for f in dataclasses.fields(ProfilerConfig)}
        unknown = sorted(set(kwargs) - known)
        if unknown:
            raise ValueError(f"unknown config options {unknown}")
        if job.artifact:
            kwargs.setdefault("artifact_path", job.artifact)
        if self.job_timeout_s is not None:
            # the rung-4 ladder extended into serve (ROBUSTNESS.md rung
            # 6): every job inherits the daemon's watchdog unless it
            # names its own deadline
            kwargs.setdefault("job_timeout_s", self.job_timeout_s)
        if self.aot_cache_dir is not None:
            # same inheritance for the AOT executable store (ISSUE
            # 15): the runner key deliberately ignores aot_* fields,
            # so this changes which store warms the build, never which
            # runner answers the job
            kwargs.setdefault("aot_cache_dir", self.aot_cache_dir)
        if "metrics_enabled" not in kwargs:
            # collect() applies each config's obs knobs PROCESS-WIDE
            # (one-shot CLI semantics); a job that says nothing about
            # metrics must inherit the daemon's live state, not switch
            # the daemon's own SLO counters off mid-serve
            from tpuprof.obs import metrics as _m
            if _m.enabled():
                kwargs["metrics_enabled"] = True
        return ProfilerConfig(backend="tpu", **kwargs)

    # -- worker loop -------------------------------------------------------

    def _worker(self) -> None:
        while True:
            job = self._queue.next(timeout=0.5)
            if job is None:
                if self._closed and not len(self._queue):
                    return
                continue
            _QUEUE_DEPTH.set(len(self._queue))
            self._run_job(job)

    def _run_job(self, job: Job) -> None:
        import time as _time

        from tpuprof.errors import (TYPED_ERRORS, DeadlineExceededError,
                                    exit_code)
        # never start a dead job (ISSUE 19): a cancelled submit (client
        # gone, nobody coalesced onto it) or an expired client deadline
        # terminates here, before any mesh time is spent.  A job with
        # followers runs regardless — someone still wants the answer.
        with self._lock:
            has_followers = bool(job._followers)
        if job.cancelled and not has_followers:
            self._terminate_unstarted(
                job, "cancelled: client disconnected before the answer",
                1)
            return
        if job.deadline_unix is not None and not has_followers:
            late = _time.time() - job.deadline_unix
            if late > 0:
                exc = DeadlineExceededError(job.id, late)
                with self._lock:
                    self._deadline_expired += 1
                _DEADLINE_EXPIRED.inc()
                self._terminate_unstarted(
                    job, f"{type(exc).__name__}: {exc}", exit_code(exc))
                return
        config = job._config
        with self._lock:
            self._computed += 1     # actual mesh runs — the read
                                    # tier's exactly-once witness
        # was this shape's runner already compiled? (probe only — the
        # hit itself is counted inside collect's acquire)
        job.cache_hit = self._probe_cache(job, config)
        job.to(RUNNING)
        with self._lock:
            self._active[job.id] = job
        _ACTIVE.inc()
        try:
            def _body() -> None:
                from tpuprof.testing import faults as _faults
                _faults.hit("serve_job", key=job.id)
                from tpuprof import ProfileReport
                report = ProfileReport(job.source, config=config)
                if job.output:
                    report.to_file(job.output)
                if job.stats_json:
                    with open(job.stats_json, "w") as fh:
                        json.dump(report.to_json_dict(), fh, indent=2)
                if config.artifact_path:
                    from tpuprof.artifact import write_artifact
                    write_artifact(config.artifact_path,
                                   stats=report.description,
                                   config=config, source=str(job.source))
                table = report.description["table"]
                job.result = {"rows": int(table["n"]),
                              "cols": int(table["nvar"])}

            # per-job watchdog (ROBUSTNESS.md rung 6): a hung profile
            # raises WatchdogTimeout — THIS job fails with exit-code-4
            # semantics and the worker is freed (the body thread is
            # abandoned), instead of wedging the daemon forever.  With
            # no timeout the body runs unwrapped — zero overhead, the
            # historical path.
            from tpuprof.config import resolve_job_timeout
            from tpuprof.runtime import guard
            guard.watched(
                _body, resolve_job_timeout(config.job_timeout_s),
                site="serve_job",
                heartbeat=lambda: {"job": job.id, "tenant": job.tenant,
                                   "source": str(job.source)})
            job.to(DONE)
        except TYPED_ERRORS as exc:
            # the degradation ladder ran out for THIS job: it fails
            # with its one-shot CLI exit code, the daemon keeps serving
            job.to(FAILED, error=f"{type(exc).__name__}: {exc}",
                   exit_code=exit_code(exc))
            blackbox.dump_postmortem(error=exc, reason="serve_job")
        except Exception as exc:   # noqa: BLE001 — a service survives
            job.to(FAILED, error=f"{type(exc).__name__}: {exc}",
                   exit_code=1)
            blackbox.record("serve_job_crash", job=job.id,
                            error=repr(exc))
        finally:
            _ACTIVE.dec()
            self._queue.release(job)
            if job._key is not None and job.state == DONE \
                    and self.read_cache is not None:
                # publish BEFORE the key leaves the coalescing table:
                # a racing same-key submit either attaches (pre-
                # terminal), or finds the cache warm — never a third
                # compute in the handoff window
                self.read_cache.put(job._key, job.result)
            with self._done_cond:
                self._active.pop(job.id, None)
                self._counts[job.state] += 1
                if job.state == DONE and job.seconds is not None:
                    self._latencies.append(job.seconds)
                if job._key is not None \
                        and self._by_key.get(job._key) is job:
                    del self._by_key[job._key]
                self._done_cond.notify_all()
            self._record_terminal(job)
            self._fan_out(job)

    def _terminate_unstarted(self, job: Job, error: str,
                             code: int) -> None:
        """Terminal bookkeeping for a QUEUED job that must not run
        (expired deadline, cancellation): the queued->failed edge, the
        tenant slot released, the coalescing key freed, followers (if
        any raced in) fanned the failure."""
        job.to(FAILED, error=error, exit_code=code)
        self._queue.release(job)
        with self._done_cond:
            self._counts[FAILED] += 1
            if job._key is not None \
                    and self._by_key.get(job._key) is job:
                del self._by_key[job._key]
            self._done_cond.notify_all()
        self._record_terminal(job)
        self._fan_out(job)

    def cancel(self, job_id: str) -> bool:
        """Client-disconnect cancellation (ISSUE 19): mark a still-
        QUEUED job so the worker skips it.  Returns False — and leaves
        the job alone — once it is running/terminal or has coalesced
        followers riding it (their answer still matters); a running
        job finishes and publishes to the result cache either way."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.state != QUEUED or job._followers:
                return False
            job.cancelled = True
            self._cancelled += 1
        return True

    def release_queued(self, select=None) -> List[Job]:
        """Graceful drain (ISSUE 19): pull still-QUEUED jobs back out
        of the local queue so a closing fleet daemon's peers can steal
        and answer them (the daemon unlinks the spool claims).  Only
        jobs ``select(job)`` picks are released (the daemon passes its
        spool-backed set — a /v1/query compute has no job file and no
        peer, so it must drain HERE); jobs carrying coalesced
        followers stay queued regardless — a local waiter still needs
        their answer from THIS process.  Released jobs keep their
        QUEUED state and get no terminal record here: their job files
        remain in the spool, and the peer that wins the re-claim
        writes the one result."""
        released = self._queue.drain(
            keep=lambda j: bool(j._followers)
            or (select is not None and not select(j)))
        with self._lock:
            for job in released:
                self._jobs.pop(job.id, None)
                if job._key is not None \
                        and self._by_key.get(job._key) is job:
                    del self._by_key[job._key]
            self._released += len(released)
        for job in released:
            self._queue.release(job)
        return released

    def retry_after_s(self) -> float:
        """Shed-response backoff hint: queued depth over the observed
        drain rate (workers x recent p50), jittered so a thousand shed
        clients do not retry in lockstep (the poll_intervals idiom)."""
        import random
        with self._lock:
            lat: List[float] = list(self._latencies)
            depth = len(self._queue)
        per_job = percentile(lat, 50) or 1.0
        base = max(per_job * max(depth, 1) / max(self.workers, 1), 0.5)
        return round(min(base, 300.0) * random.uniform(0.75, 1.25), 2)

    def _probe_cache(self, job: Job, config) -> Optional[bool]:
        """True when the job's (config, shape) key already holds a
        cached runner — i.e. this job pays no compile.  Shape discovery
        needs the source's schema; any failure there returns None and
        lets the real run report the error."""
        if not _cache.cache_enabled():
            return False
        try:
            from tpuprof.ingest.arrow import ArrowIngest
            ingest = ArrowIngest(job.source, config.batch_rows,
                                 columns=config.columns,
                                 nested=config.nested)
            key = _cache.runner_key(config, ingest.plan.n_num,
                                    ingest.plan.n_hash, self._devices)
            with _cache.process_cache()._lock:
                return key in _cache.process_cache()._runners
        except Exception:
            return None

    def _record_terminal(self, job: Job) -> None:
        _REQUESTS.inc(status=job.state)
        if job.seconds is not None:
            _JOB_SECONDS.observe(job.seconds)
        _obs_events.emit("serve_job", id=job.id, tenant=job.tenant,
                         status=job.state,
                         seconds=round(job.seconds or 0.0, 4),
                         queue_seconds=round(job.queue_seconds or 0.0, 4)
                         if job.queue_seconds is not None else None,
                         cache_hit=job.cache_hit,
                         read_cache=job.read_cache,
                         coalesced_with=job.coalesced_with,
                         error=job.error)

    # -- client API --------------------------------------------------------

    def job(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def wait(self, job: "Job | str",
             timeout: Optional[float] = None) -> Job:
        """Block until the job reaches a terminal state (returns it
        either way; raises TimeoutError past the deadline)."""
        import time
        j = job if isinstance(job, Job) else self.job(job)
        if j is None:
            raise KeyError(f"unknown job {job!r}")
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._done_cond:
            while j.state not in TERMINAL:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"job {j.id} still {j.state} after {timeout}s")
                self._done_cond.wait(remaining)
        return j

    def stats(self) -> Dict[str, Any]:
        """The serve bench's scoreboard: request counts by status,
        end-to-end p50/p99 of completed jobs, and the compiled-program
        cache's hit/miss view."""
        with self._lock:
            lat: List[float] = list(self._latencies)
            out = {
                "requests": self._submitted,
                "done": self._counts[DONE],
                "failed": self._counts[FAILED],
                "rejected": self._counts[REJECTED],
                "active": len(self._active),
                "queued": len(self._queue),
                "workers": self.workers,
                "computed": self._computed,
                "coalesced": self._coalesced,
                "shed": self._shed,
                "serve_backlog": self.serve_backlog,
                "deadline_expired": self._deadline_expired,
                "cancelled": self._cancelled,
                "released": self._released,
            }
        out["p50_s"] = round(percentile(lat, 50), 4)
        out["p99_s"] = round(percentile(lat, 99), 4)
        out["cache"] = _cache.cache_stats()
        out["read_cache"] = (self.read_cache.stats()
                             if self.read_cache is not None else None)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Live queue view — the SIGUSR1 postmortem's context card entry
        and the daemon's result-channel status."""
        with self._lock:
            active = [j.to_wire() for j in self._active.values()]
            recent = [j.to_wire() for j in
                      list(self._jobs.values())[-8:]
                      if j.state in TERMINAL]
        snap = self._queue.snapshot()
        snap.update({"active_jobs": active, "recent": recent,
                     "counts": dict(self._counts)})
        return snap

    def heartbeat(self) -> Dict[str, Any]:
        """One cheap liveness read (the StreamingProfiler.heartbeat
        idiom): emitted as a ``serve_heartbeat`` event when a sink is
        configured, and stamped onto the flight-recorder context."""
        st = self.stats()
        hb = {k: st[k] for k in ("requests", "done", "failed",
                                 "rejected", "active", "queued")}
        _obs_events.emit("serve_heartbeat", **hb)
        blackbox.set_context(last_serve_heartbeat=hb)
        return hb

    # -- lifecycle ---------------------------------------------------------

    def shutdown(self, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop admitting; drain queued jobs (workers exit once the
        queue empties); idempotent."""
        self._closed = True
        self._queue.close()
        if wait:
            for t in self._threads:
                t.join(timeout)
        blackbox.unregister_context_provider(self._context_provider)

    def __enter__(self) -> "ProfileScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
