"""Network serving plane: the HTTP edge over the serve fleet
(ROADMAP item 1 — "the transport is the only missing layer").

``tpuprof serve SPOOL --http PORT`` puts a real network front door on
the existing scheduler: a selector-based async stdlib HTTP server (no
new dependency — the repo rule) speaking the ``tpuprof-serve-job-v1``
/ ``tpuprof-serve-result-v1`` schemas over the wire.  The edge OWNS no
job lifecycle: admission, quotas, watchdogs and typed failures all
stay in serve/scheduler.py; HTTP is a second client of the same
machinery the file spool uses — and the spool stays the durability
layer (every HTTP-accepted job is spooled + claimed before it is
admitted, so a SIGKILLed daemon's jobs are stolen and answered by
fleet peers — serve/server.py claim path).

Routes::

    POST /v1/jobs                submit one job -> 202 {"id", ...};
                                 quota/depth rejection -> 429 with the
                                 scheduler's reject reason; malformed
                                 body -> 400 (never a daemon crash);
                                 draining daemon -> 503; backlog shed
                                 (ISSUE 19) -> 503 with a jittered
                                 Retry-After derived from the observed
                                 drain rate — read-tier hits keep
                                 serving while compute degrades
    POST /v1/query               {source, cols, stats} -> the values
                                 doc, answered from the cheapest tier
                                 that is still CORRECT: the edge
                                 result cache, else a column-pruned
                                 read of the newest fresh warehouse
                                 generation, else a narrow (column-
                                 subset) profile job; the serving tier
                                 is on the X-Tpuprof-Provenance header
                                 and the computing tier in the body
    GET  /v1/jobs/<id>           lifecycle view (local live state,
                                 else the spool's terminal record,
                                 else "queued" for a peer's job)
    GET  /v1/results/<id>        the terminal record: 200 when landed
                                 (ETag + If-None-Match -> 304), 202
                                 while pending, 404 unknown
    GET  /v1/watch/<key>/alerts  a watched source's alerts.json feed
                                 (read-only; ISSUE 11 satellite — watch
                                 consumers poll the edge, not the
                                 spool filesystem)
    GET  /v1/history/<key>       warehouse history series (ETag +
                                 If-None-Match -> 304)
    GET  /v1/healthz             daemon readiness for fleet balancers
                                 (unauthenticated, like /metrics):
                                 200 ready, 503 warming (AOT restart
                                 prewarm in progress — keys loaded/
                                 pending in the body), 503 draining;
                                 the body carries read-cache entries/
                                 bytes/hit-rate + computed/coalesced
                                 counts (read-tier health)
    GET  /metrics                Prometheus text exposition of the
                                 process registry (the scrape surface;
                                 unauthenticated by design, like every
                                 /metrics in the fleet)

The transport (ISSUE 16 (d)): one selector event loop owns every
socket — accept, keep-alive reads, response writes — and parsed
requests run on a small bounded worker pool, so thousands of idle
keep-alive connections cost file descriptors, not threads (the
thread-per-socket edge pinned one Python thread per open connection).
Conditional requests ride strong CRC ETags (serve/cache.py
``etag_for``): a balancer or client that re-validates an unchanged
result gets a bodyless 304 instead of a re-serialized answer.

Auth: a ``serve_auth_file`` of ``<token> <tenant>`` lines maps bearer
tokens onto tenants — the tenant id feeds the PR-9 per-tenant quotas,
so one leaked curl loop cannot starve the mesh for everyone else.
With a token file configured, every ``/v1/*`` request must carry
``Authorization: Bearer <token>`` (401 otherwise) and the token's
tenant OVERRIDES anything the body claims: identity comes from the
credential, not the payload.

The client half (`tpuprof submit --url http://host:port src`) lives
here too: submit + poll over HTTP with the same jittered backoff the
file-spool ``wait_result`` uses, and a typed
:class:`~tpuprof.errors.ServeUnavailableError` (exit code 9) when the
edge cannot be reached at all.
"""

from __future__ import annotations

import collections
import io
import json
import os
import re
import selectors
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.client import parse_headers
from http.client import responses as _HTTP_REASONS
from typing import Any, Dict, Optional, Tuple

from tpuprof.errors import (CorruptResultError, InputError,
                            ServeUnavailableError)
from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.serve.server import (JOB_SCHEMA, RESULT_SCHEMA, ServeDaemon,
                                  poll_intervals, read_result)
from tpuprof.testing import faults as _faults

_REQUESTS = _obs_metrics.counter(
    "tpuprof_http_requests_total",
    "HTTP edge requests by status code and route pattern")
_REQUEST_SECONDS = _obs_metrics.histogram(
    "tpuprof_http_request_seconds",
    "HTTP edge request handling latency (receive -> response written) "
    "— does NOT include the job's own runtime, only the edge")
_PUSHDOWN = _obs_metrics.counter(
    "tpuprof_query_pushdown_total",
    "/v1/query answers by serving tier (cache|warehouse|computed)")

MAX_BODY_BYTES = 1 << 20            # a job request is a small JSON doc;
                                    # anything bigger is garbage or abuse

QUERY_SCHEMA = "tpuprof-query-v1"   # the /v1/query answer document

_ID_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def load_auth_file(path: str) -> Dict[str, str]:
    """Parse a bearer-token file: one ``<token> <tenant>`` pair per
    line, blank lines and ``#`` comments ignored.  Every failure is a
    typed :class:`InputError` — a daemon must refuse to start half-
    authenticated, not silently serve an open edge."""
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError as exc:
        raise InputError(
            f"serve_auth_file {path!r} is unreadable "
            f"({type(exc).__name__}: {exc})") from exc
    tokens: Dict[str, str] = {}
    for n, raw in enumerate(lines, 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2:
            raise InputError(
                f"serve_auth_file {path}:{n}: expected '<token> "
                f"<tenant>', got {raw!r}")
        token, tenant = parts
        if token in tokens:
            raise InputError(
                f"serve_auth_file {path}:{n}: token listed twice "
                "(each token maps to exactly one tenant)")
        tokens[token] = tenant
    if not tokens:
        raise InputError(
            f"serve_auth_file {path!r} lists no tokens — an auth file "
            "with nothing in it would lock every client out; remove "
            "the flag for an open edge")
    return tokens


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

MAX_HEADER_BYTES = 64 << 10         # request line + headers cap — a
                                    # buffer that grows past this with
                                    # no complete head is a flood
HTTP_WORKERS = 8                    # bounded handler pool: concurrency
                                    # of request HANDLING, decoupled
                                    # from how many sockets are open


class _Conn:
    """One client connection's loop-owned state."""
    __slots__ = ("sock", "rbuf", "wbuf", "busy", "close_after",
                 "dropped", "events", "deadline", "pending_job")

    def __init__(self, sock):
        self.sock = sock
        self.rbuf = b""             # bytes read, not yet parsed
        self.wbuf = b""             # response bytes not yet written
        self.busy = False           # a request is in flight (no
                                    # pipelining ambiguity: dispatch
                                    # waits for the answer)
        self.close_after = False    # close once wbuf drains
        self.dropped = False
        self.events = 0             # current selector interest mask
        self.deadline = None        # monotonic cutoff for the CURRENT
                                    # I/O obligation (finish sending a
                                    # request / drain a response); a
                                    # trickling client cannot extend it
                                    # — the slow-loris defense (ISSUE
                                    # 19).  None while a handler runs:
                                    # job time is the watchdog's beat.
        self.pending_job = None     # job id this connection is owed an
                                    # answer for — a disconnect before
                                    # the answer cancels it if still
                                    # unclaimed (ISSUE 19)


class _SelectorHttpServer:
    """Selector-based async HTTP/1.1 server (ISSUE 16 (d)): ONE event
    loop thread owns accept + every socket's reads/writes, and parsed
    requests are handled on a bounded :class:`ThreadPoolExecutor` —
    thousands of idle keep-alive connections cost file descriptors,
    not Python threads (the :class:`http.server.ThreadingHTTPServer`
    edge this replaces pinned a thread per open socket for the
    connection's whole lifetime).

    Keeps the stdlib server's driving surface (``server_address``,
    ``serve_forever``/``shutdown``/``server_close``) so
    :class:`HttpEdge` drives either shape identically.  Routing stays
    in :meth:`HttpEdge.handle`; this class only speaks the wire:
    request-line + header parse (:func:`http.client.parse_headers` —
    case-insensitive, exactly what ``handle`` already consumes),
    Content-Length bodies capped at :data:`MAX_BODY_BYTES`, keep-alive
    per HTTP/1.1 semantics, partial writes finished under
    ``EVENT_WRITE``."""

    def __init__(self, address, workers: int = HTTP_WORKERS,
                 max_connections: Optional[int] = None,
                 conn_timeout_s: Optional[float] = None,
                 max_header_bytes: Optional[int] = None,
                 max_body_bytes: Optional[int] = None):
        from tpuprof.config import (resolve_serve_conn_timeout,
                                    resolve_serve_max_body_bytes,
                                    resolve_serve_max_connections,
                                    resolve_serve_max_header_bytes)
        # per-connection abuse caps (ISSUE 19): an open socket is a
        # bounded liability — a ceiling on how many, a deadline on each
        # I/O obligation, and byte caps on what one request may send
        self.max_connections = resolve_serve_max_connections(
            max_connections)
        self.conn_timeout_s = resolve_serve_conn_timeout(conn_timeout_s)
        self.max_header_bytes = resolve_serve_max_header_bytes(
            max_header_bytes)
        self.max_body_bytes = resolve_serve_max_body_bytes(
            max_body_bytes)
        self.edge = None            # set by HttpEdge after construction
        self._listen = socket.create_server(address, backlog=128)
        self._listen.setblocking(False)
        self.server_address = self._listen.getsockname()
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listen, selectors.EVENT_READ,
                           ("listen", None))
        # self-pipe: workers finishing a response (and shutdown) wake
        # the select() so the loop never sleeps on a ready answer
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           ("wake", None))
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(workers), 1),
            thread_name_prefix="tpuprof-http-worker")
        self._lock = threading.Lock()
        self._completed: "collections.deque[Tuple[_Conn, bytes]]" = \
            collections.deque()
        self._conns: set = set()
        self._stop = threading.Event()
        self._stopped = threading.Event()
        self._accepting = True          # loop-thread view
        self._stop_accept = threading.Event()   # cross-thread request

    # -- loop --------------------------------------------------------------

    def serve_forever(self) -> None:
        try:
            while not self._stop.is_set():
                for key, mask in self._sel.select(timeout=0.5):
                    kind, conn = key.data
                    if kind == "listen":
                        self._accept()
                    elif kind == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        if mask & selectors.EVENT_WRITE:
                            self._flush(conn)
                        if mask & selectors.EVENT_READ \
                                and not conn.dropped:
                            self._readable(conn)
                if self._stop_accept.is_set() and self._accepting:
                    self._pause_listener()
                self._drain_completed()
                self._sweep_deadlines()
        finally:
            self._stopped.set()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake()
        self._stopped.wait(timeout=10)

    def stop_accepting(self) -> None:
        """Graceful-drain step 1 (ISSUE 19): close the listening socket
        (the port frees immediately for a replacement daemon) while
        every established connection keeps its reads, its in-flight
        handlers, and its pending writes.  Thread-safe; the loop thread
        does the actual unregister on its next tick."""
        self._stop_accept.set()
        self._wake()

    def _pause_listener(self) -> None:
        self._accepting = False
        try:
            self._sel.unregister(self._listen)
        except (KeyError, OSError):
            pass
        try:
            self._listen.close()
        except OSError:
            pass

    def _sweep_deadlines(self) -> None:
        """Reap connections past their I/O deadline — the slow-loris
        defense: a client trickling header bytes (or never draining its
        response) holds a socket for at most ``conn_timeout_s``, because
        progress does NOT extend the deadline; only completing the
        obligation clears it."""
        now = time.monotonic()
        for conn in [c for c in self._conns
                     if c.deadline is not None and now > c.deadline]:
            self._drop(conn)

    def server_close(self) -> None:
        for sock in (self._listen, self._wake_r, self._wake_w):
            try:
                sock.close()
            except OSError:
                pass
        for conn in list(self._conns):
            try:
                conn.sock.close()
            except OSError:
                pass
        self._conns.clear()
        try:
            self._sel.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass

    # -- socket events (loop thread only) ----------------------------------

    def _accept(self) -> None:
        while self._accepting:
            try:
                # chaos seam (ISSUE 19): an injected accept failure
                # (EMFILE under fd pressure) must skip THIS round and
                # leave the listener registered — the loop survives
                _faults.hit("http_accept")
                sock, _addr = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            except Exception:       # noqa: BLE001 — injected fault
                return
            sock.setblocking(False)
            if len(self._conns) >= self.max_connections:
                # connection ceiling: the newcomer gets a terse 503 and
                # the door — accepting unboundedly would turn every fd
                # the OS grants into loop state an attacker sized
                try:
                    sock.send(b"HTTP/1.1 503 Service Unavailable\r\n"
                              b"Connection: close\r\n"
                              b"Content-Length: 0\r\n\r\n")
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            conn = _Conn(sock)
            conn.deadline = time.monotonic() + self.conn_timeout_s
            self._conns.add(conn)
            self._interest(conn, selectors.EVENT_READ)

    def _interest(self, conn: _Conn, events: int) -> None:
        """Set the selector interest mask for a connection (0 parks it
        — a busy connection with a request in flight is watched for
        NOTHING: reads pause until its answer is written)."""
        if conn.dropped or events == conn.events:
            return
        if conn.events and not events:
            self._sel.unregister(conn.sock)
        elif events and not conn.events:
            self._sel.register(conn.sock, events, ("conn", conn))
        elif events:
            self._sel.modify(conn.sock, events, ("conn", conn))
        conn.events = events

    def _drop(self, conn: _Conn) -> None:
        if conn.dropped:
            return
        conn.dropped = True
        if conn.pending_job is not None:
            # the client this answer was for is gone: cancel the job if
            # no worker claimed it yet (claimed jobs finish and publish
            # to the result cache — coalescing followers still win)
            jid, conn.pending_job = conn.pending_job, None
            if self.edge is not None:
                try:
                    self.edge.client_gone(jid)
                except Exception:   # noqa: BLE001 — dropping must not
                    pass            # take the loop down
        if conn.events:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, OSError):
                pass
            conn.events = 0
        try:
            conn.sock.close()
        except OSError:
            pass
        self._conns.discard(conn)

    def _readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(64 << 10)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not data:
            self._drop(conn)        # peer closed (a busy connection's
            return                  # drop cancels its pending job)
        conn.rbuf += data
        if conn.busy and len(conn.rbuf) > \
                self.max_header_bytes + self.max_body_bytes:
            self._drop(conn)        # flooding while an answer is owed
            return
        self._maybe_dispatch(conn)

    def _maybe_dispatch(self, conn: _Conn) -> None:
        """Parse one complete request off the buffer and hand it to
        the worker pool; incomplete requests wait for more bytes."""
        if conn.busy or conn.dropped:
            return
        head_end = conn.rbuf.find(b"\r\n\r\n")
        if head_end < 0:
            if len(conn.rbuf) > self.max_header_bytes:
                self._drop(conn)    # header flood, no valid request
            return
        head_lines = conn.rbuf[:head_end].split(b"\r\n")
        try:
            request_line = head_lines[0].decode("latin-1")
            method, path, version = request_line.split()
            headers = parse_headers(io.BytesIO(
                b"\r\n".join(head_lines[1:]) + b"\r\n\r\n"))
        except (ValueError, UnicodeDecodeError):
            self._drop(conn)        # not HTTP — no answer owed
            return
        try:
            length = int(headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        body: Optional[bytes] = None
        if 0 <= length <= self.max_body_bytes:
            total = head_end + 4 + length
            if len(conn.rbuf) < total:
                return              # body still arriving
            body = conn.rbuf[head_end + 4:total]
            conn.rbuf = conn.rbuf[total:]
        else:
            # oversized/garbage length: the handler answers 400 (body
            # None), and the connection closes — the unread body bytes
            # make the stream unframeable
            conn.rbuf = b""
            conn.close_after = True
        if version == "HTTP/1.0":
            conn.close_after = conn.close_after or \
                (headers.get("Connection") or "").lower() != "keep-alive"
        else:
            if (headers.get("Connection") or "").lower() == "close":
                conn.close_after = True
        conn.busy = True
        conn.deadline = None        # handler time is the job
                                    # watchdog's business, not the
                                    # transport's
        # reads stay on while answering: a peer that disconnects
        # mid-handling is noticed by the empty recv (and its pending
        # job cancelled) instead of discovered at write time; dispatch
        # of buffered pipelined bytes still waits on `busy`
        self._pool.submit(self._handle, conn, method, path, body,
                          headers)

    def _flush(self, conn: _Conn) -> None:
        if conn.dropped:
            return
        if conn.wbuf:
            try:
                # chaos seam (ISSUE 19): a connection reset mid-
                # response — the client sees a torn answer, the loop
                # drops the socket and keeps serving everyone else
                _faults.hit("http_write")
            except Exception:       # noqa: BLE001 — injected fault
                self._drop(conn)
                return
            try:
                sent = conn.sock.send(conn.wbuf)
                conn.wbuf = conn.wbuf[sent:]
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                self._drop(conn)
                return
        if conn.wbuf:
            self._interest(conn, selectors.EVENT_WRITE)
            return
        if conn.busy:
            return                  # response not queued yet
        if conn.close_after:
            self._drop(conn)
            return
        # response fully delivered: the idle keep-alive clock starts
        conn.deadline = time.monotonic() + self.conn_timeout_s
        self._interest(conn, selectors.EVENT_READ)
        if conn.rbuf:
            # the client already sent its next keep-alive request
            self._maybe_dispatch(conn)

    def _drain_completed(self) -> None:
        while True:
            with self._lock:
                if not self._completed:
                    return
                conn, payload = self._completed.popleft()
            if conn.dropped:
                continue
            conn.pending_job = None     # the answer is on its way out:
                                        # the id is (being) delivered,
                                        # the job is the client's now
            conn.wbuf += payload
            conn.busy = False
            # the write obligation gets its own deadline: a client that
            # never drains its answer is a held fd, not a served one
            conn.deadline = time.monotonic() + self.conn_timeout_s
            self._flush(conn)

    # -- request handling (worker pool) ------------------------------------

    def _handle(self, conn: _Conn, method: str, path: str,
                body: Optional[bytes], headers) -> None:
        t0 = time.perf_counter()
        extra: Optional[Dict[str, str]] = None
        try:
            res = self.edge.handle(method, path, body, headers,
                                   conn=conn)
            code, rbody, route = res[0], res[1], res[2]
            if len(res) > 3:
                extra = res[3]
        except Exception as exc:    # noqa: BLE001 — an edge answers
            code, route = 500, "error"
            rbody, extra = {"error": f"{type(exc).__name__}: {exc}"}, None
        response = self._render(code, rbody, extra,
                                close=conn.close_after)
        with self._lock:
            self._completed.append((conn, response))
        self._wake()
        _REQUESTS.inc(code=str(code), route=route)
        _REQUEST_SECONDS.observe(time.perf_counter() - t0)

    @staticmethod
    def _render(code: int, body, extra: Optional[Dict[str, str]],
                close: bool) -> bytes:
        if isinstance(body, bytes):
            payload = body
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        else:
            payload = json.dumps(body, indent=1, default=str).encode()
            ctype = "application/json"
        headers = dict(extra or {})
        ctype = headers.pop("Content-Type", ctype)
        reason = _HTTP_REASONS.get(code, "Unknown")
        lines = [f"HTTP/1.1 {code} {reason}",
                 "Server: tpuprof-serve"]
        if code == 401:
            lines.append("WWW-Authenticate: Bearer")
        lines.append(f"Content-Type: {ctype}")
        lines.append(f"Content-Length: {len(payload)}")
        for name, value in headers.items():
            lines.append(f"{name}: {value}")
        lines.append("Connection: close" if close
                     else "Connection: keep-alive")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        return head + payload


class HttpEdge:
    """One daemon's HTTP front door: a :class:`_SelectorHttpServer`
    delegating every route to the daemon's spool + scheduler.  Bind
    with ``port=0`` for an ephemeral port (CI — no collisions on a
    busy box); the bound port is on :attr:`port` and advertised in
    ``SPOOL/daemons/http.<daemon-id>`` for fleet-local discovery."""

    def __init__(self, daemon: ServeDaemon, port: int = 0,
                 host: str = "127.0.0.1",
                 auth_file: Optional[str] = None,
                 max_connections: Optional[int] = None,
                 conn_timeout_s: Optional[float] = None,
                 max_header_bytes: Optional[int] = None,
                 max_body_bytes: Optional[int] = None,
                 breaker=None):
        self.daemon = daemon
        self.tokens = load_auth_file(auth_file) if auth_file else None
        self.httpd = _SelectorHttpServer(
            (host, int(port)),
            max_connections=max_connections,
            conn_timeout_s=conn_timeout_s,
            max_header_bytes=max_header_bytes,
            max_body_bytes=max_body_bytes)
        self.httpd.edge = self
        # warehouse-pushdown circuit breaker (ISSUE 19): the daemon's
        # if it built one, else the process-wide default — a rotting
        # source's corrupt-walk tax is paid once, not per query
        if breaker is None:
            breaker = getattr(daemon, "breaker", None)
        if breaker is None:
            from tpuprof.serve.breaker import default_breaker
            breaker = default_breaker()
        self.breaker = breaker
        self.host = host
        self.port = int(self.httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None
        self._advert: Optional[str] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HttpEdge":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"tpuprof-http-{self.port}")
        self._thread.start()
        # advertise the endpoint next to the heartbeats: fleet-local
        # clients (and the bench/CI harness, which binds port 0)
        # discover the edge from the spool instead of parsing stderr
        from tpuprof.runtime import fleet as _fleet
        daemons = os.path.join(self.daemon.spool, "daemons")
        os.makedirs(daemons, exist_ok=True)
        self._advert = os.path.join(
            daemons, f"http.{self.daemon.daemon_id or 'edge'}")
        _fleet.atomic_write(self._advert, (self.url + "\n").encode())
        return self

    def stop_accepting(self) -> None:
        """Graceful-drain step 1 (ISSUE 19): pull the spool advert (no
        new discovery) and close the listening socket, while every
        established connection keeps draining — in-flight answers are
        delivered, not torn."""
        if self._advert:
            try:
                os.unlink(self._advert)
            except OSError:
                pass
            self._advert = None
        self.httpd.stop_accepting()

    def client_gone(self, job_id: str) -> None:
        """The connection owed this job's answer dropped: cancel the
        job if no worker claimed it yet (the scheduler refuses once it
        is running, terminal, or carrying coalesced followers)."""
        self.daemon.scheduler.cancel(job_id)

    def close(self) -> None:
        if self._advert:
            try:
                os.unlink(self._advert)
            except OSError:
                pass
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- routing -----------------------------------------------------------

    def handle(self, method: str, path: str, body: Optional[bytes],
               headers, conn=None) -> Tuple:
        """(status, body, route-pattern[, extra-headers]) for one
        request.  ``body`` as bytes passes through verbatim (the
        /metrics exposition, pre-serialized conditional answers);
        anything else is JSON-encoded by the transport.  The optional
        fourth element is a header dict (ETag, provenance, an
        overriding Content-Type).  ``conn`` is the transport's
        connection record when there is one — the disconnect-
        cancellation hook (ISSUE 19) rides it."""
        path, _, query = path.partition("?")
        if method == "GET" and path == "/metrics":
            return (200,
                    _obs_metrics.registry().render_text().encode(),
                    "/metrics")
        if method == "GET" and path == "/v1/healthz":
            # unauthenticated like /metrics: a fleet balancer's probe
            # carries no tenant credential, and readiness leaks nothing
            # a scrape of /metrics does not already say
            return self._healthz()
        if not path.startswith("/v1/"):
            return 404, {"error": f"no route {path!r}"}, "other"
        tenant = None
        if self.tokens is not None:
            auth = headers.get("Authorization") or ""
            token = auth[len("Bearer "):] if auth.startswith("Bearer ") \
                else None
            tenant = self.tokens.get(token) if token else None
            if tenant is None:
                # unknown and missing tokens answer identically: an
                # auth probe learns nothing about which tokens exist
                return (401, {"error": "missing or unknown bearer "
                                       "token"}, "auth")
        if method == "POST" and path == "/v1/jobs":
            return self._post_job(body, tenant, headers, conn)
        if method == "POST" and path == "/v1/query":
            return self._post_query(body, tenant, headers, conn)
        if method == "GET":
            m = re.match(r"^/v1/jobs/([^/]+)$", path)
            if m:
                return self._get_job(m.group(1))
            m = re.match(r"^/v1/results/([^/]+)$", path)
            if m:
                return self._get_result(m.group(1), headers)
            m = re.match(r"^/v1/watch/([^/]+)/alerts$", path)
            if m:
                return self._get_alerts(m.group(1))
            m = re.match(r"^/v1/history/([^/]+)$", path)
            if m:
                return self._get_history(m.group(1), query, headers)
        return 404, {"error": f"no route {method} {path!r}"}, "other"

    @staticmethod
    def _conditional(doc: Dict[str, Any], route: str, headers,
                     extra: Optional[Dict[str, str]] = None) -> Tuple:
        """Shared conditional-request wrapper (ISSUE 16 satellite):
        serialize the answer canonically, stamp its strong CRC ETag,
        and honor ``If-None-Match`` with a bodyless 304 — the client
        re-validates an unchanged result for ~60 header bytes instead
        of a full re-serialized document."""
        from tpuprof.serve.cache import canonical_body, etag_for
        payload = canonical_body(doc)
        etag = etag_for(payload)
        hdrs = {"ETag": etag, "Content-Type": "application/json"}
        hdrs.update(extra or {})
        inm = (headers.get("If-None-Match") or "") if headers else ""
        if inm and (inm.strip() == "*"
                    or etag in [t.strip() for t in inm.split(",")]):
            return 304, b"", route, hdrs
        return 200, payload, route, hdrs

    def _healthz(self) -> Tuple[int, Any, str]:
        """Daemon readiness + AOT prewarm progress (ISSUE 15): 200
        only when this daemon would answer a job at warm-class
        latency.  A fleet balancer holds traffic on the 503s —
        ``draining`` (graceful stop in progress, the PR-11 QueueClosed
        semantic) or ``warming`` (restart prewarm still deserializing
        its top-K runner keys; the body carries keys loaded/pending so
        dashboards can show progress).  Jobs are ACCEPTED in every
        state short of draining — warming only means the first ones
        may pay a load."""
        route = "/v1/healthz"
        daemon = self.daemon
        prewarmer = getattr(daemon, "prewarmer", None)
        prewarm = prewarmer.status() if prewarmer is not None else None
        body: Dict[str, Any] = {
            "daemon": daemon.daemon_id,
            "aot_cache_dir": getattr(daemon, "aot_cache_dir", None),
            "prewarm": prewarm,
        }
        sched = daemon.scheduler
        with sched._lock:
            body["active"] = len(sched._active)
            # read-tier health (ISSUE 16 satellite): balancers and
            # dashboards see cache size/hit-rate and the exactly-once
            # ledger (computed vs coalesced) next to warming state
            body["computed"] = sched._computed
            body["coalesced"] = sched._coalesced
            # overload ledger (ISSUE 19): submitted = terminal counts +
            # live jobs — the reconciliation the shed bench asserts
            # (nothing lost, nothing double-computed)
            body["requests"] = sched._submitted
            body["counts"] = dict(sched._counts)
            body["shed"] = sched._shed
            body["deadline_expired"] = sched._deadline_expired
            body["cancelled"] = sched._cancelled
            body["released"] = sched._released
        body["serve_backlog"] = sched.serve_backlog
        body["queued"] = len(sched._queue)
        body["connections"] = len(self.httpd._conns)
        body["breaker"] = self.breaker.snapshot() \
            if self.breaker is not None else None
        rc = getattr(sched, "read_cache", None)
        body["read_cache"] = rc.stats() if rc is not None else None
        body["draining"] = daemon.stop_event.is_set()
        if body["draining"]:
            body["status"] = "draining"
            return 503, body, route
        if prewarm is not None and not prewarm["done"]:
            body["status"] = "warming"
            return 503, body, route
        body["status"] = "ready"
        return 200, body, route

    def _post_job(self, body: Optional[bytes],
                  auth_tenant: Optional[str], headers=None,
                  conn=None) -> Tuple[int, Any, str]:
        route = "/v1/jobs"
        # a corrupt request body is the CLIENT's failure: 400 with the
        # parse error, never a daemon crash, never a spooled job
        if body is None:
            return (400, {"error": "missing or oversized request body "
                                   f"(cap {self.httpd.max_body_bytes} "
                                   "bytes)"},
                    route)
        try:
            req = json.loads(body)
        except ValueError as exc:
            return (400, {"error": f"request body is not JSON "
                                   f"({exc})"}, route)
        if not isinstance(req, dict):
            return (400, {"error": "request body must be a JSON "
                                   "object"}, route)
        if req.get("schema") not in (None, JOB_SCHEMA):
            return (400, {"error": f"job schema {req.get('schema')!r} "
                                   f"is not {JOB_SCHEMA}"}, route)
        source = req.get("source")
        if not isinstance(source, str) or not source:
            return 400, {"error": "job needs a 'source' path"}, route
        config = req.get("config")
        if config is not None and not isinstance(config, dict):
            return (400, {"error": "'config' must be a JSON object of "
                                   "ProfilerConfig kwargs"}, route)
        for key in ("output", "stats_json", "artifact", "tenant"):
            v = req.get(key)
            if v is not None and not isinstance(v, str):
                return 400, {"error": f"{key!r} must be a string"}, route
        # identity comes from the credential when auth is on — a body
        # naming someone else's tenant is billing fraud, not a knob
        tenant = auth_tenant if auth_tenant is not None \
            else (req.get("tenant") or "default")
        # client deadline (ISSUE 19): the header is a RELATIVE budget
        # ("answer within N ms of receipt"); the body field is the
        # absolute wire form (deadline_unix_ms) a spool forwarder
        # carries.  The header wins — it is what THIS client asked.
        deadline_unix = None
        hdr = headers.get("X-Tpuprof-Deadline-Ms") if headers else None
        if hdr is not None:
            try:
                ms = int(hdr)
                if ms < 1:
                    raise ValueError
            except (TypeError, ValueError):
                return (400, {"error": "X-Tpuprof-Deadline-Ms must be "
                                       "a positive integer millisecond "
                                       f"budget, got {hdr!r}"}, route)
            deadline_unix = time.time() + ms / 1000.0
        elif req.get("deadline_unix_ms") is not None:
            try:
                deadline_unix = int(req["deadline_unix_ms"]) / 1000.0
            except (TypeError, ValueError):
                return (400, {"error": "'deadline_unix_ms' must be an "
                                       "integer epoch-millisecond "
                                       "deadline"}, route)
        job = self.daemon.submit_local(
            source, output=req.get("output"), tenant=tenant,
            stats_json=req.get("stats_json"),
            artifact=req.get("artifact"), config_kwargs=config,
            deadline_unix=deadline_unix)
        if job.state == "rejected":
            # the scheduler's admission hook decides the status class:
            # resource pressure (full queue / tenant over quota) is
            # 429 retry-later WITH the scheduler's reject reason; a
            # draining daemon is 503; a backlog shed (ISSUE 19) is 503
            # WITH a jittered Retry-After sized to the observed drain
            # rate; a bad config is the request's own fault (400)
            wire = dict(job.to_wire())
            wire["schema"] = RESULT_SCHEMA
            if job.reject_kind == "BacklogFull":
                retry = self.daemon.scheduler.retry_after_s()
                return (503, wire, route,
                        {"Retry-After": f"{retry:g}"})
            if job.reject_kind in ("QueueFull", "TenantQuotaExceeded"):
                code = 429
            elif job.reject_kind == "QueueClosed":
                code = 503
            else:
                code = 400
            return code, wire, route
        if conn is not None:
            # owe this connection the 202: a disconnect before it is
            # written cancels the job if still unclaimed
            conn.pending_job = job.id
        return (202, {"schema": JOB_SCHEMA, "id": job.id,
                      "tenant": job.tenant, "status": job.state},
                route)

    def _get_job(self, jid: str) -> Tuple[int, Any, str]:
        route = "/v1/jobs/<id>"
        if not _ID_RE.match(jid):
            return 400, {"error": f"malformed job id {jid!r}"}, route
        job = self.daemon.scheduler.job(jid)
        if job is not None:
            return 200, dict(job.to_wire()), route
        try:
            res = read_result(self.daemon.spool, jid)
        except CorruptResultError as exc:
            return 500, {"error": f"{type(exc).__name__}: {exc}"}, route
        if res is not None:
            return 200, res, route
        if os.path.exists(os.path.join(self.daemon.dirs["jobs"],
                                       f"{jid}.json")):
            # spooled but not ours: queued on (or stealable from) a
            # fleet peer — the edge answers for the whole fleet
            return 200, {"id": jid, "status": "queued"}, route
        return 404, {"error": f"unknown job {jid!r}"}, route

    def _get_result(self, jid: str, headers=None) -> Tuple:
        route = "/v1/results/<id>"
        if not _ID_RE.match(jid):
            return 400, {"error": f"malformed job id {jid!r}"}, route
        try:
            res = read_result(self.daemon.spool, jid)
        except CorruptResultError as exc:
            # server-side rot: the poller's re-poll contract applies
            # (the writer may still atomically replace it), so answer
            # 500 with the typed name and let the client keep polling
            return 500, {"error": f"{type(exc).__name__}: {exc}"}, route
        if res is not None:
            # terminal records are immutable, so the CRC ETag is a
            # permanent validator: a re-poll with If-None-Match costs
            # a 304, not a re-read + re-serialize
            return self._conditional(res, route, headers)
        if jid in self.daemon.scheduler._jobs \
                or os.path.exists(os.path.join(self.daemon.dirs["jobs"],
                                               f"{jid}.json")):
            return 202, {"id": jid, "status": "pending"}, route
        return 404, {"error": f"unknown job {jid!r}"}, route

    def _get_alerts(self, key: str) -> Tuple[int, Any, str]:
        route = "/v1/watch/<key>/alerts"
        # the key names a directory: the charset check plus the
        # dots-only rejection ("..") keeps reads inside SPOOL/watch/
        if not _ID_RE.match(key) or set(key) <= {"."}:
            return 400, {"error": f"malformed watch key {key!r}"}, route
        path = os.path.join(self.daemon.spool, "watch", key,
                            "alerts.json")
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            return (404, {"error": f"no alert feed for watch key "
                                   f"{key!r}"}, route)
        # the feed is written atomically (watch.py _atomic_write) and
        # is already JSON — stream the bytes; no parse, no copy drift
        return 200, data or b"[]", route

    def _get_history(self, key: str, query: str,
                     headers=None) -> Tuple:
        """The warehouse history feed off the edge (ISSUE 13 (c)):
        ``GET /v1/history/<key>?col=price&stat=mean`` answers the stat
        series, ``?trend=1[&col=price]`` the PSI/KS-over-time series —
        both the same ``tpuprof-history-v1`` document `tpuprof history`
        prints, read from the spool's warehouse the watch loop feeds.
        Answers carry the shared CRC ETag and honor If-None-Match
        (an unchanged warehouse re-poll costs a 304)."""
        from urllib.parse import parse_qs
        route = "/v1/history/<key>"
        if not _ID_RE.match(key) or set(key) <= {"."}:
            return (400, {"error": f"malformed warehouse key {key!r}"},
                    route)
        params = parse_qs(query or "")

        def one(name, default=None):
            vals = params.get(name)
            return vals[0] if vals else default

        dirpath = os.path.join(self.daemon.spool, "warehouse", key)
        if not os.path.isdir(dirpath):
            return (404, {"error": f"no warehouse history for key "
                                   f"{key!r}"}, route)
        from tpuprof.errors import (CorruptWarehouseError, InputError,
                                    WarehouseUnavailableError)
        from tpuprof.warehouse import query_stat, query_trend
        try:
            if one("trend") in ("1", "true", "yes"):
                doc = query_trend(dirpath, col=one("col"))
            else:
                col = one("col")
                if not col:
                    return (400, {"error": "history needs ?col=<name> "
                                          "(or ?trend=1)"}, route)
                doc = query_stat(dirpath, col, one("stat", "mean"))
        except InputError as exc:
            return 404, {"error": str(exc)}, route
        except WarehouseUnavailableError as exc:
            # the daemon's own environment lacks pyarrow: the edge is
            # honest about it — 501 "not implemented here", not a 500
            return 501, {"error": str(exc)}, route
        except CorruptWarehouseError as exc:
            return 500, {"error": f"{type(exc).__name__}: {exc}"}, route
        return self._conditional(doc, route, headers)

    # -- query pushdown (ISSUE 16 (c)) -------------------------------------

    def _post_query(self, body: Optional[bytes],
                    auth_tenant: Optional[str], headers,
                    conn=None) -> Tuple:
        """``POST /v1/query {source, cols, stats}``: answer column
        statistics from the CHEAPEST tier that is still correct —

        1. **cache**: the edge result cache holds this exact answer
           (byte-identical repeat, sub-millisecond, no I/O);
        2. **warehouse**: the newest readable warehouse generation
           post-dates the source — a column-pruned Parquet read (only
           the requested stat chunks materialize, the PR-13 169×
           cheaper path);
        3. **computed**: the source is stale/absent in the warehouse —
           a NARROW profile (``columns=cols``, PR-14's column-subset
           path) runs through the ordinary scheduler admission.

        The serving tier rides the ``X-Tpuprof-Provenance`` header (so
        a cache hit stays byte-identical to the answer it cached,
        whose body names the tier that COMPUTED it)."""
        route = "/v1/query"
        t0 = time.perf_counter()
        if body is None:
            return (400, {"error": "missing or oversized request body "
                                   f"(cap {self.httpd.max_body_bytes} "
                                   "bytes)"},
                    route)
        try:
            req = json.loads(body)
        except ValueError as exc:
            return (400, {"error": f"request body is not JSON "
                                   f"({exc})"}, route)
        if not isinstance(req, dict):
            return (400, {"error": "request body must be a JSON "
                                   "object"}, route)
        source = req.get("source")
        if not isinstance(source, str) or not source:
            return 400, {"error": "query needs a 'source' path"}, route
        cols = req.get("cols")
        if not isinstance(cols, list) or not cols \
                or not all(isinstance(c, str) for c in cols):
            return (400, {"error": "'cols' must be a non-empty list "
                                   "of column names"}, route)
        stats = req.get("stats") or ["mean"]
        if not isinstance(stats, list) \
                or not all(isinstance(s, str) for s in stats):
            return (400, {"error": "'stats' must be a list of stat "
                                   "names"}, route)
        config = req.get("config")
        if config is not None and not isinstance(config, dict):
            return (400, {"error": "'config' must be a JSON object of "
                                   "ProfilerConfig kwargs"}, route)
        tenant = auth_tenant if auth_tenant is not None \
            else (req.get("tenant") or "default")
        source = os.path.abspath(source)

        sched = self.daemon.scheduler
        rc = getattr(sched, "read_cache", None)
        key = None
        if rc is not None:
            from tpuprof.serve.cache import source_fingerprint
            key = ("query", source_fingerprint(source), tuple(cols),
                   tuple(stats),
                   json.dumps(config or {}, sort_keys=True))
            hit = rc.get(key)
            if hit is not None:
                payload, etag = hit
                return self._query_response(
                    payload, etag, "cache", route, headers,
                    source, cols, stats, t0)

        # warehouse tier: the newest readable generation, column-pruned
        # — gated by the per-source circuit breaker (ISSUE 19): a
        # source whose generations keep reading corrupt pays the
        # corrupt-walk disk tax ONCE per cooldown, not per query
        from tpuprof.errors import WarehouseUnavailableError
        from tpuprof.warehouse import store as _store
        from tpuprof.warehouse.history import query_columns
        dirpath = _store.source_dir(
            os.path.join(self.daemon.spool, "warehouse"), source)
        breaker = self.breaker
        breaker_open = breaker is not None \
            and not breaker.allow(source)
        gen_doc = None
        corrupt_reads: list = []
        if not breaker_open:
            try:
                gen_doc = query_columns(
                    dirpath, cols, stats,
                    on_corrupt=(
                        lambda path, exc:
                        (corrupt_reads.append(path),
                         breaker.record_failure(source))
                        if breaker is not None
                        else corrupt_reads.append(path)))
            except WarehouseUnavailableError:
                gen_doc = None      # no pyarrow here: compute answers
                                    # (environment, not rot — the
                                    # breaker does not count it)
            if breaker is not None and gen_doc is not None \
                    and not corrupt_reads:
                # a clean walk (no corrupt skips) is the probe/success
                # signal that closes a half-open breaker
                breaker.record_success(source)
        fresh = False
        if gen_doc is not None and not gen_doc["missing"]:
            created = gen_doc.get("created_unix")
            try:
                fresh = created is not None \
                    and created >= os.stat(source).st_mtime
            except OSError:
                # the source is gone: the warehouse is all there is,
                # and "stale" has nothing fresher to defer to
                fresh = True
        if fresh:
            doc = {"schema": QUERY_SCHEMA, "source": source,
                   "provenance": "warehouse",
                   "generation": gen_doc["generation"],
                   "rows": gen_doc.get("rows"),
                   "columns": gen_doc["columns"]}
            return self._query_answer(doc, key, rc, route, headers,
                                      cols, stats, t0)

        # computed tier: a NARROW profile — only the requested columns
        # run the mesh (PR-14 column-subset re-bin path via columns=)
        from tpuprof.serve.jobs import Job, new_job_id
        jid = new_job_id()
        tmp_stats = os.path.join(self.daemon.dirs["tmp"],
                                 f".query.{jid}.json")
        kwargs = dict(config or {})
        kwargs["columns"] = list(cols)
        job = sched.submit(Job(source=source, tenant=tenant,
                               job_id=jid, stats_json=tmp_stats,
                               config_kwargs=kwargs))
        if job.state == "rejected":
            wire = dict(job.to_wire())
            wire["schema"] = RESULT_SCHEMA
            if job.reject_kind == "BacklogFull":
                retry = sched.retry_after_s()
                return (503, wire, route,
                        {"Retry-After": f"{retry:g}"})
            if job.reject_kind in ("QueueFull", "TenantQuotaExceeded"):
                code = 429
            elif job.reject_kind == "QueueClosed":
                code = 503
            else:
                code = 400
            return code, wire, route
        if conn is not None:
            # this handler blocks on the answer: a client that
            # disconnects mid-wait cancels the job if no worker
            # claimed it yet (ISSUE 19)
            conn.pending_job = job.id
        try:
            sched.wait(job, timeout=3600)
        except TimeoutError:
            return (504, {"error": f"query profile {job.id} still "
                                   f"{job.state} after 3600s"}, route)
        finally:
            if conn is not None:
                conn.pending_job = None
        if job.state != "done":
            code = 400 if job.exit_code == 2 else 500
            return (code, {"error": job.error,
                           "exit_code": job.exit_code}, route)
        try:
            with open(tmp_stats) as fh:
                stats_doc = json.load(fh)
        except (OSError, ValueError) as exc:
            return (500, {"error": f"query stats unreadable: "
                                   f"{type(exc).__name__}: {exc}"},
                    route)
        finally:
            try:
                os.unlink(tmp_stats)
            except OSError:
                pass
        variables = stats_doc.get("variables") or {}
        columns: Dict[str, Any] = {}
        for col in cols:
            var = variables.get(col) or {}
            columns[col] = {s: var.get(s) for s in stats}
        doc = {"schema": QUERY_SCHEMA, "source": source,
               # "breaker_open": computed BECAUSE the warehouse is
               # tripped for this source — operators see the detour
               "provenance": ("breaker_open" if breaker_open
                              else "computed"),
               "generation": None,
               "rows": job.result.get("rows"), "columns": columns}
        return self._query_answer(doc, key, rc, route, headers,
                                  cols, stats, t0)

    def _query_answer(self, doc: Dict[str, Any], key, rc, route: str,
                      headers, cols, stats, t0: float) -> Tuple:
        """Publish a freshly produced query answer to the result cache
        (repeats then serve byte-identically from tier 1) and frame
        the response."""
        from tpuprof.serve.cache import canonical_body, etag_for
        payload = canonical_body(doc)
        etag = etag_for(payload)
        if rc is not None and key is not None:
            rc.put(key, doc)
        return self._query_response(payload, etag, doc["provenance"],
                                    route, headers, doc["source"],
                                    cols, stats, t0)

    def _query_response(self, payload: bytes, etag: str, tier: str,
                        route: str, headers, source, cols, stats,
                        t0: float) -> Tuple:
        _PUSHDOWN.inc(tier=tier)
        if _obs_metrics.enabled():
            _obs_events.emit("query_pushdown", source=str(source),
                             provenance=tier, cols=len(cols),
                             stats=len(stats),
                             seconds=round(time.perf_counter() - t0, 4))
        hdrs = {"ETag": etag, "Content-Type": "application/json",
                "X-Tpuprof-Provenance": tier}
        inm = (headers.get("If-None-Match") or "") if headers else ""
        if inm and etag in [t.strip() for t in inm.split(",")]:
            return 304, b"", route, hdrs
        return 200, payload, route, hdrs


# ---------------------------------------------------------------------------
# client side (`tpuprof submit --url`)
# ---------------------------------------------------------------------------

def _request(url: str, method: str = "GET",
             payload: Optional[Dict[str, Any]] = None,
             token: Optional[str] = None,
             timeout: float = 30.0,
             extra_headers: Optional[Dict[str, str]] = None
             ) -> Tuple[int, Dict[str, Any]]:
    """One HTTP exchange -> (status, decoded JSON body).  An HTTP
    error status is a NORMAL return (the daemon answered); only
    failing to reach the daemon at all raises, and it raises the typed
    :class:`ServeUnavailableError` automation can branch on."""
    import urllib.error
    import urllib.request
    data = None
    headers = {"Accept": "application/json"}
    if payload is not None:
        data = json.dumps(payload).encode()
        headers["Content-Type"] = "application/json"
    if token:
        headers["Authorization"] = f"Bearer {token}"
    headers.update(extra_headers or {})
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            status = resp.status
    except urllib.error.HTTPError as exc:
        raw = exc.read()
        status = exc.code
    except (urllib.error.URLError, OSError) as exc:
        reason = getattr(exc, "reason", exc)
        raise ServeUnavailableError(
            f"cannot reach tpuprof serve at {url}: {reason} — is the "
            "daemon running with --http?") from exc
    try:
        doc = json.loads(raw) if raw else {}
    except ValueError:
        doc = {"error": raw.decode("utf-8", "replace")[:500]}
    if not isinstance(doc, dict):
        doc = {"body": doc}
    return status, doc


def submit_job(base_url: str, source: str, output: Optional[str] = None,
               tenant: Optional[str] = None,
               stats_json: Optional[str] = None,
               artifact: Optional[str] = None,
               config_kwargs: Optional[Dict[str, Any]] = None,
               token: Optional[str] = None,
               timeout: float = 30.0,
               deadline_ms: Optional[int] = None
               ) -> Tuple[int, Dict[str, Any]]:
    """POST one job to an HTTP edge.  Paths resolve to absolute
    client-side, exactly like the spool transport's ``write_job`` —
    the daemon's cwd is not the client's (the edge and its clients
    share storage the way spool clients do).  ``deadline_ms`` rides
    the ``X-Tpuprof-Deadline-Ms`` header (ISSUE 19): a relative
    answer-within budget the daemon enforces — a job still queued past
    it is never started and fails typed (exit code 11)."""
    payload: Dict[str, Any] = {
        "schema": JOB_SCHEMA,
        "source": os.path.abspath(source),
        "output": os.path.abspath(output) if output else None,
        "stats_json": os.path.abspath(stats_json) if stats_json else None,
        "artifact": os.path.abspath(artifact) if artifact else None,
        "config": dict(config_kwargs or {}),
    }
    if tenant is not None:
        payload["tenant"] = str(tenant)
    extra = {"X-Tpuprof-Deadline-Ms": str(int(deadline_ms))} \
        if deadline_ms is not None else None
    return _request(base_url.rstrip("/") + "/v1/jobs", method="POST",
                    payload=payload, token=token, timeout=timeout,
                    extra_headers=extra)


def wait_result_http(base_url: str, job_id: str,
                     timeout: Optional[float] = None,
                     poll_interval: float = 0.1,
                     token: Optional[str] = None) -> Dict[str, Any]:
    """Poll ``GET /v1/results/<id>`` until the terminal record lands —
    the HTTP twin of the spool's ``wait_result``, sharing its jittered
    exponential backoff (ISSUE 11 satellite) and its corrupt-record
    contract: a 500 naming ``CorruptResultError`` is re-polled and
    surfaces TYPED at the deadline."""
    deadline = None if timeout is None else time.monotonic() + timeout
    backoff = poll_intervals(poll_interval)
    corrupt: Optional[CorruptResultError] = None
    url = f"{base_url.rstrip('/')}/v1/results/{job_id}"
    while True:
        status, doc = _request(url, token=token)
        if status == 200:
            return doc
        if status == 401:
            raise InputError(
                f"result poll for job {job_id} rejected: "
                f"{doc.get('error', 'unauthorized')}")
        corrupt = CorruptResultError(doc.get("error") or "corrupt") \
            if status == 500 and "CorruptResultError" in \
            str(doc.get("error")) else None
        if deadline is not None and time.monotonic() > deadline:
            if corrupt is not None:
                raise corrupt
            raise TimeoutError(
                f"no result for job {job_id} after {timeout}s at "
                f"{base_url} — the job may still be running "
                "server-side")
        sleep = next(backoff)
        if deadline is not None:
            sleep = min(sleep, max(deadline - time.monotonic(), 0.0)
                        + 0.001)
        time.sleep(sleep)


def discover_edges(spool: str) -> Dict[str, str]:
    """{daemon_id: url} from the spool's endpoint advertisements —
    how the bench harness (and fleet-local tooling) finds ephemeral-
    port edges without parsing daemon stderr."""
    daemons = os.path.join(spool, "daemons")
    out: Dict[str, str] = {}
    try:
        names = os.listdir(daemons)
    except OSError:
        return out
    for name in names:
        if not name.startswith("http.") or name.startswith(".tmp."):
            continue
        try:
            with open(os.path.join(daemons, name),
                      encoding="utf-8") as fh:
                url = fh.read().strip()
        except OSError:
            continue
        if url:
            out[name[len("http."):]] = url
    return out
