"""Network serving plane: the HTTP edge over the serve fleet
(ROADMAP item 1 — "the transport is the only missing layer").

``tpuprof serve SPOOL --http PORT`` puts a real network front door on
the existing scheduler: a threaded stdlib HTTP server (no new
dependency — the repo rule) speaking the ``tpuprof-serve-job-v1`` /
``tpuprof-serve-result-v1`` schemas over the wire.  The edge OWNS no
job lifecycle: admission, quotas, watchdogs and typed failures all
stay in serve/scheduler.py; HTTP is a second client of the same
machinery the file spool uses — and the spool stays the durability
layer (every HTTP-accepted job is spooled + claimed before it is
admitted, so a SIGKILLed daemon's jobs are stolen and answered by
fleet peers — serve/server.py claim path).

Routes::

    POST /v1/jobs                submit one job -> 202 {"id", ...};
                                 quota/depth rejection -> 429 with the
                                 scheduler's reject reason; malformed
                                 body -> 400 (never a daemon crash);
                                 draining daemon -> 503
    GET  /v1/jobs/<id>           lifecycle view (local live state,
                                 else the spool's terminal record,
                                 else "queued" for a peer's job)
    GET  /v1/results/<id>        the terminal record: 200 when landed,
                                 202 while pending, 404 unknown
    GET  /v1/watch/<key>/alerts  a watched source's alerts.json feed
                                 (read-only; ISSUE 11 satellite — watch
                                 consumers poll the edge, not the
                                 spool filesystem)
    GET  /v1/healthz             daemon readiness for fleet balancers
                                 (unauthenticated, like /metrics):
                                 200 ready, 503 warming (AOT restart
                                 prewarm in progress — keys loaded/
                                 pending in the body), 503 draining
    GET  /metrics                Prometheus text exposition of the
                                 process registry (the scrape surface;
                                 unauthenticated by design, like every
                                 /metrics in the fleet)

Auth: a ``serve_auth_file`` of ``<token> <tenant>`` lines maps bearer
tokens onto tenants — the tenant id feeds the PR-9 per-tenant quotas,
so one leaked curl loop cannot starve the mesh for everyone else.
With a token file configured, every ``/v1/*`` request must carry
``Authorization: Bearer <token>`` (401 otherwise) and the token's
tenant OVERRIDES anything the body claims: identity comes from the
credential, not the payload.

The client half (`tpuprof submit --url http://host:port src`) lives
here too: submit + poll over HTTP with the same jittered backoff the
file-spool ``wait_result`` uses, and a typed
:class:`~tpuprof.errors.ServeUnavailableError` (exit code 9) when the
edge cannot be reached at all.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from tpuprof.errors import (CorruptResultError, InputError,
                            ServeUnavailableError)
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.serve.server import (JOB_SCHEMA, RESULT_SCHEMA, ServeDaemon,
                                  poll_intervals, read_result)

_REQUESTS = _obs_metrics.counter(
    "tpuprof_http_requests_total",
    "HTTP edge requests by status code and route pattern")
_REQUEST_SECONDS = _obs_metrics.histogram(
    "tpuprof_http_request_seconds",
    "HTTP edge request handling latency (receive -> response written) "
    "— does NOT include the job's own runtime, only the edge")

MAX_BODY_BYTES = 1 << 20            # a job request is a small JSON doc;
                                    # anything bigger is garbage or abuse

_ID_RE = re.compile(r"^[A-Za-z0-9._-]+$")


def load_auth_file(path: str) -> Dict[str, str]:
    """Parse a bearer-token file: one ``<token> <tenant>`` pair per
    line, blank lines and ``#`` comments ignored.  Every failure is a
    typed :class:`InputError` — a daemon must refuse to start half-
    authenticated, not silently serve an open edge."""
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError as exc:
        raise InputError(
            f"serve_auth_file {path!r} is unreadable "
            f"({type(exc).__name__}: {exc})") from exc
    tokens: Dict[str, str] = {}
    for n, raw in enumerate(lines, 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2:
            raise InputError(
                f"serve_auth_file {path}:{n}: expected '<token> "
                f"<tenant>', got {raw!r}")
        token, tenant = parts
        if token in tokens:
            raise InputError(
                f"serve_auth_file {path}:{n}: token listed twice "
                "(each token maps to exactly one tenant)")
        tokens[token] = tenant
    if not tokens:
        raise InputError(
            f"serve_auth_file {path!r} lists no tokens — an auth file "
            "with nothing in it would lock every client out; remove "
            "the flag for an open edge")
    return tokens


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

class _EdgeHandler(BaseHTTPRequestHandler):
    server_version = "tpuprof-serve"
    protocol_version = "HTTP/1.1"

    # BaseHTTPRequestHandler logs every request to stderr; the edge's
    # audit trail is the metrics + serve_job events, not daemon noise
    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        pass

    def do_POST(self) -> None:
        self._route("POST")

    def do_GET(self) -> None:
        self._route("GET")

    def _route(self, method: str) -> None:
        edge: "HttpEdge" = self.server.edge  # type: ignore[attr-defined]
        t0 = time.perf_counter()
        try:
            code, body, route = edge.handle(method, self.path,
                                            self._read_body(),
                                            self.headers)
        except Exception as exc:    # noqa: BLE001 — an edge answers
            code, route = 500, "error"
            body = {"error": f"{type(exc).__name__}: {exc}"}
        try:
            payload = body if isinstance(body, bytes) \
                else json.dumps(body, indent=1, default=str).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8" \
                if isinstance(body, bytes) else "application/json"
            self.send_response(code)
            if code == 401:
                self.send_header("WWW-Authenticate", "Bearer")
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass                    # client went away mid-answer
        _REQUESTS.inc(code=str(code), route=route)
        _REQUEST_SECONDS.observe(time.perf_counter() - t0)

    def _read_body(self) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            return None
        if length < 0 or length > MAX_BODY_BYTES:
            return None
        return self.rfile.read(length) if length else b""


class HttpEdge:
    """One daemon's HTTP front door: a :class:`ThreadingHTTPServer`
    delegating every route to the daemon's spool + scheduler.  Bind
    with ``port=0`` for an ephemeral port (CI — no collisions on a
    busy box); the bound port is on :attr:`port` and advertised in
    ``SPOOL/daemons/http.<daemon-id>`` for fleet-local discovery."""

    def __init__(self, daemon: ServeDaemon, port: int = 0,
                 host: str = "127.0.0.1",
                 auth_file: Optional[str] = None):
        self.daemon = daemon
        self.tokens = load_auth_file(auth_file) if auth_file else None
        self.httpd = ThreadingHTTPServer((host, int(port)), _EdgeHandler)
        self.httpd.edge = self      # type: ignore[attr-defined]
        self.httpd.daemon_threads = True
        self.host = host
        self.port = int(self.httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None
        self._advert: Optional[str] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "HttpEdge":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"tpuprof-http-{self.port}")
        self._thread.start()
        # advertise the endpoint next to the heartbeats: fleet-local
        # clients (and the bench/CI harness, which binds port 0)
        # discover the edge from the spool instead of parsing stderr
        from tpuprof.runtime import fleet as _fleet
        daemons = os.path.join(self.daemon.spool, "daemons")
        os.makedirs(daemons, exist_ok=True)
        self._advert = os.path.join(
            daemons, f"http.{self.daemon.daemon_id or 'edge'}")
        _fleet.atomic_write(self._advert, (self.url + "\n").encode())
        return self

    def close(self) -> None:
        if self._advert:
            try:
                os.unlink(self._advert)
            except OSError:
                pass
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    # -- routing -----------------------------------------------------------

    def handle(self, method: str, path: str, body: Optional[bytes],
               headers) -> Tuple[int, Any, str]:
        """(status, body, route-pattern) for one request.  ``body`` as
        bytes passes through verbatim (the /metrics exposition);
        anything else is JSON-encoded by the handler."""
        path, _, query = path.partition("?")
        if method == "GET" and path == "/metrics":
            return (200,
                    _obs_metrics.registry().render_text().encode(),
                    "/metrics")
        if method == "GET" and path == "/v1/healthz":
            # unauthenticated like /metrics: a fleet balancer's probe
            # carries no tenant credential, and readiness leaks nothing
            # a scrape of /metrics does not already say
            return self._healthz()
        if not path.startswith("/v1/"):
            return 404, {"error": f"no route {path!r}"}, "other"
        tenant = None
        if self.tokens is not None:
            auth = headers.get("Authorization") or ""
            token = auth[len("Bearer "):] if auth.startswith("Bearer ") \
                else None
            tenant = self.tokens.get(token) if token else None
            if tenant is None:
                # unknown and missing tokens answer identically: an
                # auth probe learns nothing about which tokens exist
                return (401, {"error": "missing or unknown bearer "
                                       "token"}, "auth")
        if method == "POST" and path == "/v1/jobs":
            return self._post_job(body, tenant)
        if method == "GET":
            m = re.match(r"^/v1/jobs/([^/]+)$", path)
            if m:
                return self._get_job(m.group(1))
            m = re.match(r"^/v1/results/([^/]+)$", path)
            if m:
                return self._get_result(m.group(1))
            m = re.match(r"^/v1/watch/([^/]+)/alerts$", path)
            if m:
                return self._get_alerts(m.group(1))
            m = re.match(r"^/v1/history/([^/]+)$", path)
            if m:
                return self._get_history(m.group(1), query)
        return 404, {"error": f"no route {method} {path!r}"}, "other"

    def _healthz(self) -> Tuple[int, Any, str]:
        """Daemon readiness + AOT prewarm progress (ISSUE 15): 200
        only when this daemon would answer a job at warm-class
        latency.  A fleet balancer holds traffic on the 503s —
        ``draining`` (graceful stop in progress, the PR-11 QueueClosed
        semantic) or ``warming`` (restart prewarm still deserializing
        its top-K runner keys; the body carries keys loaded/pending so
        dashboards can show progress).  Jobs are ACCEPTED in every
        state short of draining — warming only means the first ones
        may pay a load."""
        route = "/v1/healthz"
        daemon = self.daemon
        prewarmer = getattr(daemon, "prewarmer", None)
        prewarm = prewarmer.status() if prewarmer is not None else None
        body: Dict[str, Any] = {
            "daemon": daemon.daemon_id,
            "aot_cache_dir": getattr(daemon, "aot_cache_dir", None),
            "prewarm": prewarm,
        }
        with daemon.scheduler._lock:
            body["active"] = len(daemon.scheduler._active)
        body["queued"] = len(daemon.scheduler._queue)
        if daemon.stop_event.is_set():
            body["status"] = "draining"
            return 503, body, route
        if prewarm is not None and not prewarm["done"]:
            body["status"] = "warming"
            return 503, body, route
        body["status"] = "ready"
        return 200, body, route

    def _post_job(self, body: Optional[bytes],
                  auth_tenant: Optional[str]) -> Tuple[int, Any, str]:
        route = "/v1/jobs"
        # a corrupt request body is the CLIENT's failure: 400 with the
        # parse error, never a daemon crash, never a spooled job
        if body is None:
            return (400, {"error": "missing or oversized request body "
                                   f"(cap {MAX_BODY_BYTES} bytes)"},
                    route)
        try:
            req = json.loads(body)
        except ValueError as exc:
            return (400, {"error": f"request body is not JSON "
                                   f"({exc})"}, route)
        if not isinstance(req, dict):
            return (400, {"error": "request body must be a JSON "
                                   "object"}, route)
        if req.get("schema") not in (None, JOB_SCHEMA):
            return (400, {"error": f"job schema {req.get('schema')!r} "
                                   f"is not {JOB_SCHEMA}"}, route)
        source = req.get("source")
        if not isinstance(source, str) or not source:
            return 400, {"error": "job needs a 'source' path"}, route
        config = req.get("config")
        if config is not None and not isinstance(config, dict):
            return (400, {"error": "'config' must be a JSON object of "
                                   "ProfilerConfig kwargs"}, route)
        for key in ("output", "stats_json", "artifact", "tenant"):
            v = req.get(key)
            if v is not None and not isinstance(v, str):
                return 400, {"error": f"{key!r} must be a string"}, route
        # identity comes from the credential when auth is on — a body
        # naming someone else's tenant is billing fraud, not a knob
        tenant = auth_tenant if auth_tenant is not None \
            else (req.get("tenant") or "default")
        job = self.daemon.submit_local(
            source, output=req.get("output"), tenant=tenant,
            stats_json=req.get("stats_json"),
            artifact=req.get("artifact"), config_kwargs=config)
        if job.state == "rejected":
            # the scheduler's admission hook decides the status class:
            # resource pressure (full queue / tenant over quota) is
            # 429 retry-later WITH the scheduler's reject reason; a
            # draining daemon is 503; a bad config is the request's
            # own fault (400)
            if job.reject_kind in ("QueueFull", "TenantQuotaExceeded"):
                code = 429
            elif job.reject_kind == "QueueClosed":
                code = 503
            else:
                code = 400
            wire = dict(job.to_wire())
            wire["schema"] = RESULT_SCHEMA
            return code, wire, route
        return (202, {"schema": JOB_SCHEMA, "id": job.id,
                      "tenant": job.tenant, "status": job.state},
                route)

    def _get_job(self, jid: str) -> Tuple[int, Any, str]:
        route = "/v1/jobs/<id>"
        if not _ID_RE.match(jid):
            return 400, {"error": f"malformed job id {jid!r}"}, route
        job = self.daemon.scheduler.job(jid)
        if job is not None:
            return 200, dict(job.to_wire()), route
        try:
            res = read_result(self.daemon.spool, jid)
        except CorruptResultError as exc:
            return 500, {"error": f"{type(exc).__name__}: {exc}"}, route
        if res is not None:
            return 200, res, route
        if os.path.exists(os.path.join(self.daemon.dirs["jobs"],
                                       f"{jid}.json")):
            # spooled but not ours: queued on (or stealable from) a
            # fleet peer — the edge answers for the whole fleet
            return 200, {"id": jid, "status": "queued"}, route
        return 404, {"error": f"unknown job {jid!r}"}, route

    def _get_result(self, jid: str) -> Tuple[int, Any, str]:
        route = "/v1/results/<id>"
        if not _ID_RE.match(jid):
            return 400, {"error": f"malformed job id {jid!r}"}, route
        try:
            res = read_result(self.daemon.spool, jid)
        except CorruptResultError as exc:
            # server-side rot: the poller's re-poll contract applies
            # (the writer may still atomically replace it), so answer
            # 500 with the typed name and let the client keep polling
            return 500, {"error": f"{type(exc).__name__}: {exc}"}, route
        if res is not None:
            return 200, res, route
        if jid in self.daemon.scheduler._jobs \
                or os.path.exists(os.path.join(self.daemon.dirs["jobs"],
                                               f"{jid}.json")):
            return 202, {"id": jid, "status": "pending"}, route
        return 404, {"error": f"unknown job {jid!r}"}, route

    def _get_alerts(self, key: str) -> Tuple[int, Any, str]:
        route = "/v1/watch/<key>/alerts"
        # the key names a directory: the charset check plus the
        # dots-only rejection ("..") keeps reads inside SPOOL/watch/
        if not _ID_RE.match(key) or set(key) <= {"."}:
            return 400, {"error": f"malformed watch key {key!r}"}, route
        path = os.path.join(self.daemon.spool, "watch", key,
                            "alerts.json")
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            return (404, {"error": f"no alert feed for watch key "
                                   f"{key!r}"}, route)
        # the feed is written atomically (watch.py _atomic_write) and
        # is already JSON — stream the bytes; no parse, no copy drift
        return 200, data or b"[]", route

    def _get_history(self, key: str, query: str) -> Tuple[int, Any, str]:
        """The warehouse history feed off the edge (ISSUE 13 (c)):
        ``GET /v1/history/<key>?col=price&stat=mean`` answers the stat
        series, ``?trend=1[&col=price]`` the PSI/KS-over-time series —
        both the same ``tpuprof-history-v1`` document `tpuprof history`
        prints, read from the spool's warehouse the watch loop feeds."""
        from urllib.parse import parse_qs
        route = "/v1/history/<key>"
        if not _ID_RE.match(key) or set(key) <= {"."}:
            return (400, {"error": f"malformed warehouse key {key!r}"},
                    route)
        params = parse_qs(query or "")

        def one(name, default=None):
            vals = params.get(name)
            return vals[0] if vals else default

        dirpath = os.path.join(self.daemon.spool, "warehouse", key)
        if not os.path.isdir(dirpath):
            return (404, {"error": f"no warehouse history for key "
                                   f"{key!r}"}, route)
        from tpuprof.errors import (CorruptWarehouseError, InputError,
                                    WarehouseUnavailableError)
        from tpuprof.warehouse import query_stat, query_trend
        try:
            if one("trend") in ("1", "true", "yes"):
                doc = query_trend(dirpath, col=one("col"))
            else:
                col = one("col")
                if not col:
                    return (400, {"error": "history needs ?col=<name> "
                                          "(or ?trend=1)"}, route)
                doc = query_stat(dirpath, col, one("stat", "mean"))
        except InputError as exc:
            return 404, {"error": str(exc)}, route
        except WarehouseUnavailableError as exc:
            # the daemon's own environment lacks pyarrow: the edge is
            # honest about it — 501 "not implemented here", not a 500
            return 501, {"error": str(exc)}, route
        except CorruptWarehouseError as exc:
            return 500, {"error": f"{type(exc).__name__}: {exc}"}, route
        return 200, doc, route


# ---------------------------------------------------------------------------
# client side (`tpuprof submit --url`)
# ---------------------------------------------------------------------------

def _request(url: str, method: str = "GET",
             payload: Optional[Dict[str, Any]] = None,
             token: Optional[str] = None,
             timeout: float = 30.0) -> Tuple[int, Dict[str, Any]]:
    """One HTTP exchange -> (status, decoded JSON body).  An HTTP
    error status is a NORMAL return (the daemon answered); only
    failing to reach the daemon at all raises, and it raises the typed
    :class:`ServeUnavailableError` automation can branch on."""
    import urllib.error
    import urllib.request
    data = None
    headers = {"Accept": "application/json"}
    if payload is not None:
        data = json.dumps(payload).encode()
        headers["Content-Type"] = "application/json"
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(url, data=data, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            status = resp.status
    except urllib.error.HTTPError as exc:
        raw = exc.read()
        status = exc.code
    except (urllib.error.URLError, OSError) as exc:
        reason = getattr(exc, "reason", exc)
        raise ServeUnavailableError(
            f"cannot reach tpuprof serve at {url}: {reason} — is the "
            "daemon running with --http?") from exc
    try:
        doc = json.loads(raw) if raw else {}
    except ValueError:
        doc = {"error": raw.decode("utf-8", "replace")[:500]}
    if not isinstance(doc, dict):
        doc = {"body": doc}
    return status, doc


def submit_job(base_url: str, source: str, output: Optional[str] = None,
               tenant: Optional[str] = None,
               stats_json: Optional[str] = None,
               artifact: Optional[str] = None,
               config_kwargs: Optional[Dict[str, Any]] = None,
               token: Optional[str] = None,
               timeout: float = 30.0) -> Tuple[int, Dict[str, Any]]:
    """POST one job to an HTTP edge.  Paths resolve to absolute
    client-side, exactly like the spool transport's ``write_job`` —
    the daemon's cwd is not the client's (the edge and its clients
    share storage the way spool clients do)."""
    payload: Dict[str, Any] = {
        "schema": JOB_SCHEMA,
        "source": os.path.abspath(source),
        "output": os.path.abspath(output) if output else None,
        "stats_json": os.path.abspath(stats_json) if stats_json else None,
        "artifact": os.path.abspath(artifact) if artifact else None,
        "config": dict(config_kwargs or {}),
    }
    if tenant is not None:
        payload["tenant"] = str(tenant)
    return _request(base_url.rstrip("/") + "/v1/jobs", method="POST",
                    payload=payload, token=token, timeout=timeout)


def wait_result_http(base_url: str, job_id: str,
                     timeout: Optional[float] = None,
                     poll_interval: float = 0.1,
                     token: Optional[str] = None) -> Dict[str, Any]:
    """Poll ``GET /v1/results/<id>`` until the terminal record lands —
    the HTTP twin of the spool's ``wait_result``, sharing its jittered
    exponential backoff (ISSUE 11 satellite) and its corrupt-record
    contract: a 500 naming ``CorruptResultError`` is re-polled and
    surfaces TYPED at the deadline."""
    deadline = None if timeout is None else time.monotonic() + timeout
    backoff = poll_intervals(poll_interval)
    corrupt: Optional[CorruptResultError] = None
    url = f"{base_url.rstrip('/')}/v1/results/{job_id}"
    while True:
        status, doc = _request(url, token=token)
        if status == 200:
            return doc
        if status == 401:
            raise InputError(
                f"result poll for job {job_id} rejected: "
                f"{doc.get('error', 'unauthorized')}")
        corrupt = CorruptResultError(doc.get("error") or "corrupt") \
            if status == 500 and "CorruptResultError" in \
            str(doc.get("error")) else None
        if deadline is not None and time.monotonic() > deadline:
            if corrupt is not None:
                raise corrupt
            raise TimeoutError(
                f"no result for job {job_id} after {timeout}s at "
                f"{base_url} — the job may still be running "
                "server-side")
        sleep = next(backoff)
        if deadline is not None:
            sleep = min(sleep, max(deadline - time.monotonic(), 0.0)
                        + 0.001)
        time.sleep(sleep)


def discover_edges(spool: str) -> Dict[str, str]:
    """{daemon_id: url} from the spool's endpoint advertisements —
    how the bench harness (and fleet-local tooling) finds ephemeral-
    port edges without parsing daemon stderr."""
    daemons = os.path.join(spool, "daemons")
    out: Dict[str, str] = {}
    try:
        names = os.listdir(daemons)
    except OSError:
        return out
    for name in names:
        if not name.startswith("http.") or name.startswith(".tmp."):
            continue
        try:
            with open(os.path.join(daemons, name),
                      encoding="utf-8") as fh:
                url = fh.read().strip()
        except OSError:
            continue
        if url:
            out[name[len("http."):]] = url
    return out
