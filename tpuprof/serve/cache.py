"""Keyed compiled-program cache — the warm-mesh half of `tpuprof serve`.

Every profile today builds a fresh :class:`~tpuprof.runtime.mesh.MeshRunner`
whose jit wrappers are new objects, so the in-memory XLA executable cache
never carries across runs: each fresh build re-pays the ~20-40 s compile
on first dispatch (PERF.md, ROADMAP item 1).  This module makes runner
construction a cache lookup instead: runners are keyed on exactly the
fields the compiled programs depend on — the config's program-relevant
knobs plus the shape signature ``(n_num, n_hash)`` and the device set —
so a repeat-fingerprint job reuses the SAME runner object, whose jit
wrappers already hold their compiled executables.  Reuse is result-safe:
the cached wrappers resolve to the same executables a fresh build's
first calls would compile, so outputs are byte-identical (the same
determinism place_state's byte-stability guarantee rests on).

The cache is process-wide and default-ON (``TPUPROF_RUNNER_CACHE=0``
restores a build per call; an integer sets the LRU capacity, default 8).
One-shot CLI profiles see no difference — one build either way; the
`tpuprof serve` daemon and any in-process re-profile loop (benchmarks,
notebooks, incremental resume) get sub-second warm starts.

Per-process persistent-compile-cache gate (the PR-6 `drift`-leg fix):
this box's jaxlib intermittently aborts (abseil mutex / segv) when the
persistent compilation cache stays enabled across repeated MeshRunner
builds in one process.  Runner reuse removes most rebuilds; for the
rest (genuinely new shapes in a long-lived process) the gate lets the
FIRST cache-enabled build keep the persistent cache — that is the
cold-start the disk cache exists to amortize across process restarts —
and disables it before every later build.  ``TPUPROF_COMPILE_CACHE_
REBUILDS=1`` opts back into the old always-on behavior.

The read tier (ISSUE 16) lives here too: :class:`ResultCache` is the
edge's ANSWER cache — canonical serialized response bodies keyed by
(source fingerprint, config fingerprint), bytes-capped LRU, CRC-checked
on every read with a typed loud demote (:class:`~tpuprof.errors.
CorruptReadCacheError`) so a rotten entry costs a recompute, never a
wrong answer.  A hit never touches the mesh, the spool, or even the
scheduler queue — the request is answered at admission.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
import zlib
from typing import Any, Dict, Optional, Sequence, Tuple

from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics

_CACHE_HITS = _obs_metrics.counter(
    "tpuprof_serve_compile_cache_hits_total",
    "profile runs that reused a cached MeshRunner (compiled programs "
    "warm — no recompile)")
_CACHE_MISSES = _obs_metrics.counter(
    "tpuprof_serve_compile_cache_misses_total",
    "profile runs that had to build (and later compile) a fresh "
    "MeshRunner")

_ENV = "TPUPROF_RUNNER_CACHE"
DEFAULT_CAPACITY = 8


def _env_capacity() -> int:
    """``TPUPROF_RUNNER_CACHE``: unset/empty -> default capacity;
    ``0``/``false``/``no`` -> caching off (a build per call, the
    pre-serve behavior); any other integer -> that LRU capacity."""
    raw = os.environ.get(_ENV)
    if raw in (None, ""):
        return DEFAULT_CAPACITY
    if raw.strip().lower() in ("0", "false", "no"):
        return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        return DEFAULT_CAPACITY


def runner_key(config, n_num: int, n_hash: int,
               devices: Optional[Sequence] = None) -> Tuple:
    """The cache key: every config field MeshRunner's compiled programs
    read — nothing more (so a job differing only in paths/telemetry/
    budgets still hits) and nothing less (so two keys never share a
    runner whose programs would differ).  Env-resolved knobs
    (``pass_b_kernel``, ``profile_passes``) are resolved NOW: the key
    must capture what a build at this moment would produce, not the
    raw field.

    ``profile_passes`` is the pass-STRUCTURE field (ISSUE 14): a fused
    runner compiles step_ab/scan_ab programs a two-pass runner never
    builds, so the two must never share a cache slot.  The seeded-edge
    values themselves are deliberately NOT keyed: provisional edges
    are runtime ``put_replicated`` inputs to the compiled programs,
    never compiled structure — keying them (or the ``seed_edges``
    artifact path, which changes every watch cycle) would rebuild the
    warm mesh per cycle and destroy exactly the steady state fused
    mode exists to serve."""
    import jax

    from tpuprof.config import (resolve_pass_b_kernel,
                                resolve_profile_passes)
    devs = list(devices) if devices is not None else jax.devices()
    if config.mesh_devices:
        devs = devs[: config.mesh_devices]
    return (
        int(n_num), int(n_hash),
        tuple((d.platform, d.id) for d in devs),
        int(config.batch_rows),
        config.mesh_devices,
        int(config.hll_precision),
        int(config.bins),
        config.use_pallas,
        resolve_pass_b_kernel(getattr(config, "pass_b_kernel", None)),
        config.use_fused,
        resolve_profile_passes(getattr(config, "profile_passes", None)),
    )


class RunnerCache:
    """Bounded LRU of live MeshRunner instances, keyed by
    :func:`runner_key`.  Thread-safe; the build itself runs under the
    lock — MeshRunner.__init__ only creates jit *wrappers* (compilation
    is deferred to first dispatch), so a build is milliseconds and two
    racing workers resolve to ONE shared runner instead of compiling
    the same programs twice."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._runners: "collections.OrderedDict[Tuple, Any]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, config, n_num: int, n_hash: int,
            devices: Optional[Sequence] = None):
        key = runner_key(config, n_num, n_hash, devices)
        with self._lock:
            runner = self._runners.get(key)
            if runner is not None:
                self._runners.move_to_end(key)
                self.hits += 1
                _CACHE_HITS.inc()
                return runner
            _note_build_with_cache()
            from tpuprof.runtime.mesh import MeshRunner
            runner = MeshRunner(config, n_num, n_hash, devices=devices)
            # AOT executable cache (runtime/aot.py, ISSUE 15): before
            # the first dispatch compiles anything, try deserializing
            # this key's stored executables — a restarted daemon warms
            # in seconds; on a store miss the entry is compiled +
            # published by a background thread, off this hot path.
            # Never raises: a rotten store demotes loudly to the fresh
            # compile the runner already is.
            from tpuprof.runtime import aot as _aot
            _aot.on_runner_miss(runner, config, key, n_num, n_hash,
                                devices=devices)
            self._runners[key] = runner
            while len(self._runners) > self.capacity:
                self._runners.popitem(last=False)
            self.misses += 1
            _CACHE_MISSES.inc()
            return runner

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {"runners": len(self._runners),
                    "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses,
                    "hit_rate": self.hits / total if total else 0.0}

    def clear(self) -> None:
        with self._lock:
            self._runners.clear()
            self.hits = 0
            self.misses = 0


# ---------------------------------------------------------------------------
# process-wide cache + acquire seam (backends/tpu.py, runtime/stream.py)
# ---------------------------------------------------------------------------

_process_cache = RunnerCache(_env_capacity() or 1)


def process_cache() -> RunnerCache:
    return _process_cache


def cache_enabled() -> bool:
    return _env_capacity() > 0


def acquire_runner(config, n_num: int, n_hash: int,
                   devices: Optional[Sequence] = None):
    """The ONE seam every profile path builds runners through
    (``TPUStatsBackend.collect``, ``StreamingProfiler.__init__``, the
    serve scheduler's jobs).  Cached by default; with the cache
    disabled it still routes through the compile-cache gate so repeated
    builds stay abort-safe."""
    if not cache_enabled():
        _CACHE_MISSES.inc()
        _note_build_with_cache()
        from tpuprof.runtime.mesh import MeshRunner
        return MeshRunner(config, n_num, n_hash, devices=devices)
    return _process_cache.get(config, n_num, n_hash, devices=devices)


def cache_stats() -> Dict[str, Any]:
    """Hit/miss view of the process cache — the serve bench's
    ``serve_cache_hit_rate`` and the scheduler's stats() read this."""
    return _process_cache.stats()


# ---------------------------------------------------------------------------
# per-process persistent-compile-cache gate (PR-6 drift-leg crash fix)
# ---------------------------------------------------------------------------

_cached_builds = [0]        # MeshRunner builds with the persistent cache on
_gate_warned = [False]


def _note_build_with_cache() -> None:
    """Called immediately before every MeshRunner construction.  The
    first build in a process with jax's persistent compilation cache
    enabled keeps it; any LATER build disables the cache first —
    repeated rebuilds with the cache on are the observed jaxlib abort
    trigger (benchmarks PR 6), and a long-lived daemon must never trade
    a second shape's compile time for a process abort."""
    if os.environ.get("TPUPROF_COMPILE_CACHE_REBUILDS") \
            in ("1", "true", "yes"):
        return
    try:
        import jax
        current = getattr(jax.config, "jax_compilation_cache_dir", None)
    except Exception:
        return
    if not current:
        return
    _cached_builds[0] += 1
    if _cached_builds[0] <= 1:
        return
    from tpuprof.backends.tpu import disable_compile_cache
    disable_compile_cache()
    if not _gate_warned[0]:
        _gate_warned[0] = True
        from tpuprof.utils.trace import logger
        logger.info(
            "persistent compilation cache gated off for this process's "
            "further program builds (first build kept it): repeated "
            "MeshRunner rebuilds with the cache enabled intermittently "
            "abort jaxlib.  Warm starts come from the in-process runner "
            "cache, and CROSS-RESTART warmth from the app-level AOT "
            "executable cache (aot_cache_dir / TPUPROF_AOT_CACHE_DIR — "
            "the supported path; serve/watch daemons default it to "
            "SPOOL/aot).  Set TPUPROF_COMPILE_CACHE_REBUILDS=1 to opt "
            "out of the gate.")


# ---------------------------------------------------------------------------
# edge result/answer cache — the read tier (ISSUE 16 (a))
# ---------------------------------------------------------------------------

_READ_HITS = _obs_metrics.counter(
    "tpuprof_read_cache_hits_total",
    "read-tier requests answered from the edge result cache (no "
    "scheduler admission, no mesh)")
_READ_MISSES = _obs_metrics.counter(
    "tpuprof_read_cache_misses_total",
    "read-tier lookups that found no (or a rotten) cached answer")
_READ_EVICTIONS = _obs_metrics.counter(
    "tpuprof_read_cache_evictions_total",
    "read-cache entries dropped to respect the entry/bytes caps")
_READ_DEMOTES = _obs_metrics.counter(
    "tpuprof_read_cache_demotes_total",
    "read-cache entries dropped because their payload failed its CRC "
    "check (CorruptReadCacheError demoted to a miss)")
_READ_BYTES = _obs_metrics.gauge(
    "tpuprof_read_cache_bytes",
    "payload bytes currently held by the edge result cache")
_READ_ENTRIES = _obs_metrics.gauge(
    "tpuprof_read_cache_entries",
    "entries currently held by the edge result cache")


def source_fingerprint(source: Any) -> str:
    """The read-tier's identity for a source: path + mtime_ns + size,
    hashed short.  Touching (or rewriting) the file changes the
    fingerprint, so cached answers invalidate NATURALLY — no TTL knob,
    no stale-read window wider than one stat() — while repeat requests
    against an unchanged file share one key.  A source that cannot be
    stat'ed (not a local file: a URL, a just-deleted path) falls back
    to the path text alone, which still coalesces concurrent repeats."""
    text = os.path.abspath(str(source))
    try:
        st = os.stat(text)
        raw = f"{text}|{st.st_mtime_ns}|{st.st_size}"
    except OSError:
        raw = text
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def etag_for(payload: bytes) -> str:
    """A strong ETag for a serialized response body: the CRC32 of the
    exact bytes on the wire, quoted per RFC 9110.  The same CRC the
    artifact envelope uses, so a result's ETag doubles as its
    integrity token — byte-identical answers (the coalescing/read-tier
    guarantee) always carry byte-identical ETags."""
    return '"crc32-%08x"' % (zlib.crc32(payload) & 0xFFFFFFFF)


def canonical_body(doc: Dict[str, Any]) -> bytes:
    """The ONE serialization of a cached answer — matching the HTTP
    edge's JSON framing (indent=1, default=str) so a cache hit's bytes
    are indistinguishable from the miss path that stored them."""
    return (json.dumps(doc, indent=1, default=str) + "\n").encode()


class ResultCache:
    """Bounded LRU of serialized answer bodies, capped on BOTH entry
    count and total payload bytes (a handful of 100-MB wide-table
    answers must not silently pin the edge's memory).  Thread-safe.

    Entries store ``(payload bytes, crc32)``; every :meth:`get`
    re-hashes the payload and compares — a mismatch raises nothing to
    the caller: the entry is demoted LOUDLY (logged + counted on
    ``tpuprof_read_cache_demotes_total``) and the lookup reports a
    miss, the same never-wrong-only-slower discipline the AOT store
    uses (:class:`~tpuprof.errors.CorruptReadCacheError`)."""

    def __init__(self, capacity: int = 512,
                 max_bytes: int = 64 << 20):
        self.capacity = max(int(capacity), 1)
        self.max_bytes = max(int(max_bytes), 1)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Any, Tuple[bytes, int]]" \
            = collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.demotes = 0

    def put(self, key: Any, doc: Dict[str, Any]) -> str:
        """Serialize ``doc`` canonically, store it under ``key``, and
        return the payload's ETag.  An oversized single answer (larger
        than the whole bytes cap) is not stored — the ETag is still
        returned so the caller's response carries it."""
        payload = canonical_body(doc)
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        etag = '"crc32-%08x"' % crc
        if len(payload) > self.max_bytes:
            return etag
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[0])
            self._entries[key] = (payload, crc)
            self._bytes += len(payload)
            while (len(self._entries) > self.capacity
                   or self._bytes > self.max_bytes):
                _, (dropped, _c) = self._entries.popitem(last=False)
                self._bytes -= len(dropped)
                self.evictions += 1
                _READ_EVICTIONS.inc()
            _READ_BYTES.set(self._bytes)
            _READ_ENTRIES.set(len(self._entries))
        if _obs_metrics.enabled():
            _obs_events.emit("read_cache", status="store",
                             bytes=len(payload),
                             entries=len(self._entries))
        return etag

    def get(self, key: Any) -> Optional[Tuple[bytes, str]]:
        """``(payload, etag)`` for a fresh entry, ``None`` on a miss.
        A CRC mismatch demotes the entry and reports the miss."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                _READ_MISSES.inc()
                return None
            payload, crc = entry
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                # typed demote: never serve bytes that fail their own
                # integrity envelope — drop, count, miss (the caller
                # recomputes; CorruptReadCacheError documents the shape
                # for anyone probing entries directly)
                self._entries.pop(key, None)
                self._bytes -= len(payload)
                self.demotes += 1
                self.misses += 1
                _READ_DEMOTES.inc()
                _READ_MISSES.inc()
                _READ_BYTES.set(self._bytes)
                _READ_ENTRIES.set(len(self._entries))
                from tpuprof.errors import CorruptReadCacheError
                from tpuprof.utils.trace import logger
                exc = CorruptReadCacheError(
                    f"read-cache entry {key!r} failed its CRC check — "
                    "dropped; this request recomputes")
                logger.warning(str(exc))
                if _obs_metrics.enabled():
                    _obs_events.emit("read_cache", status="demote",
                                     bytes=len(payload),
                                     entries=len(self._entries))
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            _READ_HITS.inc()
            return payload, '"crc32-%08x"' % crc

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {"entries": len(self._entries),
                    "bytes": self._bytes,
                    "capacity": self.capacity,
                    "max_bytes": self.max_bytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "demotes": self.demotes,
                    "hit_rate": self.hits / total if total else 0.0}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self.hits = 0
            self.misses = 0
            self.evictions = 0
            self.demotes = 0
            _READ_BYTES.set(0)
            _READ_ENTRIES.set(0)
