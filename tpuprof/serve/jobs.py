"""Job state machine + bounded admission queue for `tpuprof serve`.

A job is one profile request: a source, an output, a tenant, and a dict
of ProfilerConfig overrides.  Its lifecycle is a small explicit state
machine — ``queued -> running -> done|failed`` with ``rejected`` as the
admission-time terminal — because a daemon serving many tenants must
never lose track of what a request is doing, and an illegal transition
(finishing a job that never ran, re-running a finished one) is a
scheduler bug worth crashing on, not papering over.

Admission control is the queue's job: a bounded depth (`serve_queue_depth`)
keeps a burst from buffering unbounded work, and a per-tenant quota
(`serve_tenant_quota`, counting queued+running) keeps one tenant from
starving the rest of the mesh.  Over-limit submissions REJECT loudly at
admit time — sub-second feedback beats a silently growing backlog.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
REJECTED = "rejected"

TERMINAL = (DONE, FAILED, REJECTED)

_TRANSITIONS = {
    # QUEUED -> FAILED is the never-started terminal: a client deadline
    # that expired in the queue, or a cancellation before any worker
    # claimed the job (ISSUE 19) — the work must not reach the mesh
    QUEUED: {RUNNING, REJECTED, FAILED},
    RUNNING: {DONE, FAILED},
    DONE: set(),
    FAILED: set(),
    REJECTED: set(),
}

_id_counter = itertools.count()


def new_job_id() -> str:
    """Sortable, collision-free within and across processes:
    nanosecond timestamp + pid + a process-local counter."""
    return f"j{time.time_ns():x}-{os.getpid()}-{next(_id_counter)}"


class Job:
    """One profile request and its lifecycle record."""

    def __init__(self, source: Any, output: Optional[str] = None,
                 tenant: str = "default", job_id: Optional[str] = None,
                 stats_json: Optional[str] = None,
                 artifact: Optional[str] = None,
                 config_kwargs: Optional[Dict[str, Any]] = None,
                 deadline_unix: Optional[float] = None):
        self.id = job_id or new_job_id()
        self.source = source
        self.output = output
        self.tenant = str(tenant)
        self.stats_json = stats_json
        self.artifact = artifact
        self.config_kwargs = dict(config_kwargs or {})
        self.deadline_unix = (float(deadline_unix)
                              if deadline_unix is not None else None)
                                                # client deadline, epoch
                                                # seconds: expired jobs
                                                # are never started
        self.cancelled = False                  # client gone before the
                                                # answer; honored only
                                                # while still QUEUED
        self.state = QUEUED
        self.error: Optional[str] = None
        self.exit_code: Optional[int] = None
        self.reject_kind: Optional[str] = None  # admission-failure class
                                                # name (QueueFull, ...)
                                                # — the HTTP edge's
                                                # status-code hook
        self.result: Dict[str, Any] = {}
        self.enqueued_at = time.monotonic()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.cache_hit: Optional[bool] = None
        self.coalesced_with: Optional[str] = None  # primary job id this
                                                # submit collapsed onto
                                                # (read tier, ISSUE 16)
        self.read_cache: Optional[str] = None   # "hit" when the result
                                                # cache answered at
                                                # admission (no queue,
                                                # no mesh)
        self._config = None          # validated ProfilerConfig (scheduler)
        self._key = None             # read-tier coalescing key (source
                                     # fingerprint, config fingerprint)
        self._followers: List["Job"] = []   # same-key submits riding
                                            # this job's one compute

    def to(self, state: str, error: Optional[str] = None,
           exit_code: Optional[int] = None) -> "Job":
        if state not in _TRANSITIONS.get(self.state, ()):
            raise ValueError(
                f"job {self.id}: illegal transition "
                f"{self.state!r} -> {state!r}")
        self.state = state
        if state == RUNNING:
            self.started_at = time.monotonic()
        if state in TERMINAL:
            self.finished_at = time.monotonic()
        if error is not None:
            self.error = str(error)
        if exit_code is not None:
            self.exit_code = int(exit_code)
        return self

    @property
    def seconds(self) -> Optional[float]:
        """End-to-end latency (enqueue -> terminal) — what the p50/p99
        SLO tracks (queue wait included: a user waits it too)."""
        if self.finished_at is None:
            return None
        return self.finished_at - self.enqueued_at

    @property
    def queue_seconds(self) -> Optional[float]:
        if self.started_at is None:
            return None
        return self.started_at - self.enqueued_at

    def to_wire(self) -> Dict[str, Any]:
        """JSON-ready lifecycle record — the result-file body and the
        SIGUSR1 queue snapshot's per-job entry."""
        out = {
            "id": self.id, "tenant": self.tenant, "status": self.state,
            "source": str(self.source), "output": self.output,
        }
        if self.seconds is not None:
            out["seconds"] = round(self.seconds, 4)
        if self.queue_seconds is not None:
            out["queue_seconds"] = round(self.queue_seconds, 4)
        if self.error is not None:
            out["error"] = self.error
        if self.exit_code is not None:
            out["exit_code"] = self.exit_code
        if self.reject_kind is not None:
            out["reject_kind"] = self.reject_kind
        if self.deadline_unix is not None:
            out["deadline_unix_ms"] = int(self.deadline_unix * 1000)
        if self.cancelled:
            out["cancelled"] = True
        if self.cache_hit is not None:
            out["cache_hit"] = self.cache_hit
        if self.coalesced_with is not None:
            out["coalesced_with"] = self.coalesced_with
        if self.read_cache is not None:
            out["read_cache"] = self.read_cache
        out.update(self.result)
        return out


class JobQueue:
    """Bounded FIFO with per-tenant quotas.

    ``admit`` either enqueues or raises :class:`QueueFull`/
    :class:`TenantQuotaExceeded`; a tenant's count covers queued AND
    running jobs (released by :meth:`release`), so a quota of 2 means
    "at most 2 of this tenant's profiles occupy the mesh or its queue
    at any moment"."""

    def __init__(self, depth: int = 32, tenant_quota: int = 0):
        self.depth = max(int(depth), 1)
        self.tenant_quota = max(int(tenant_quota), 0)   # 0 = unlimited
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._queue: "collections.deque[Job]" = collections.deque()
        self._tenant_live: Dict[str, int] = {}
        self._closed = False

    def admit(self, job: Job) -> None:
        with self._lock:
            if self._closed:
                raise QueueClosed("serve queue is shut down")
            if len(self._queue) >= self.depth:
                raise QueueFull(
                    f"serve queue is full ({self.depth} jobs queued) — "
                    "retry later or raise --serve-queue-depth")
            live = self._tenant_live.get(job.tenant, 0)
            if self.tenant_quota and live >= self.tenant_quota:
                raise TenantQuotaExceeded(
                    f"tenant {job.tenant!r} already has {live} jobs "
                    f"queued or running (quota {self.tenant_quota}) — "
                    "wait for one to finish or raise "
                    "--serve-tenant-quota")
            self._tenant_live[job.tenant] = live + 1
            self._queue.append(job)
            self._not_empty.notify()

    def next(self, timeout: Optional[float] = None) -> Optional[Job]:
        """Pop the oldest queued job; None on timeout or when closed
        with an empty queue (the worker-shutdown signal)."""
        with self._lock:
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while not self._queue:
                if self._closed:
                    return None
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._not_empty.wait(remaining)
            return self._queue.popleft()

    def release(self, job: Job) -> None:
        """A job left the live set (terminal state) — free its tenant
        slot."""
        with self._lock:
            live = self._tenant_live.get(job.tenant, 0)
            if live <= 1:
                self._tenant_live.pop(job.tenant, None)
            else:
                self._tenant_live[job.tenant] = live - 1

    def drain(self, keep=None) -> List[Job]:
        """Graceful-drain helper (ISSUE 19): pop and return every job
        still waiting in the queue — jobs ``keep(job)`` selects stay
        queued (a closing fleet daemon keeps follower-laden jobs, whose
        local waiters still need the answer HERE)."""
        with self._lock:
            kept: "collections.deque[Job]" = collections.deque()
            out: List[Job] = []
            while self._queue:
                job = self._queue.popleft()
                if keep is not None and keep(job):
                    kept.append(job)
                else:
                    out.append(job)
            self._queue.extend(kept)
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "depth": self.depth,
                "queued": len(self._queue),
                "tenant_quota": self.tenant_quota,
                "tenants_live": dict(self._tenant_live),
                "queued_jobs": [j.id for j in self._queue],
            }


class QueueFull(RuntimeError):
    """Admission rejected: the bounded queue is at depth."""


class BacklogFull(RuntimeError):
    """Admission shed: the queued-compute depth crossed the
    ``serve_backlog`` budget (ISSUE 19).  Softer than :class:`QueueFull`
    — the queue still has room, the daemon is deliberately degrading to
    "reads only"; the HTTP edge answers 503 with a jittered
    ``Retry-After`` derived from the observed drain rate."""


class TenantQuotaExceeded(RuntimeError):
    """Admission rejected: this tenant's queued+running quota is used."""


class QueueClosed(RuntimeError):
    """Admission rejected: the scheduler is shutting down."""


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile on a small latency list (no numpy on the
    admission path; the scheduler's stats() is host-cheap)."""
    if not values:
        return 0.0
    vs = sorted(values)
    k = min(max(int(round(q / 100.0 * (len(vs) - 1))), 0), len(vs) - 1)
    return vs[k]
