"""`tpuprof serve` daemon + `tpuprof submit` client transport.

Transport is a spool DIRECTORY, not a socket: the repo's coordination
idiom (runtime/fleet.py) and the right fit for the deployment shape —
one resident daemon per host holding the mesh, with clients on the same
host (or shared storage) handing it work.  No ports, no auth surface,
no new dependency; requests and results are plain JSON files written
atomically (tmp + rename), so a crashed client or daemon never leaves a
torn message.

Layout under the spool dir::

    jobs/<id>.json      one request (schema tpuprof-serve-job-v1),
                        written atomically by `tpuprof submit`
    results/<id>.json   the terminal record (tpuprof-serve-result-v1),
                        written atomically by the daemon; the request
                        file is unlinked after the result lands, so a
                        daemon restart re-runs only jobs with no result
    tmp/                atomic-write staging

The daemon is a thin shell: scanning the spool and writing results; job
lifecycle itself lives in serve/scheduler.py, which `tpuprof submit`,
the bench harness and library embeddings share.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from tpuprof.serve.jobs import TERMINAL, Job
from tpuprof.serve.scheduler import ProfileScheduler

JOB_SCHEMA = "tpuprof-serve-job-v1"
RESULT_SCHEMA = "tpuprof-serve-result-v1"


def _spool_dirs(spool: str) -> Dict[str, str]:
    dirs = {name: os.path.join(spool, name)
            for name in ("jobs", "results", "tmp")}
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)
    return dirs


def _atomic_write_json(dirs: Dict[str, str], path: str,
                       payload: Dict[str, Any]) -> None:
    tmp = os.path.join(dirs["tmp"],
                       f".{os.path.basename(path)}.{os.getpid()}")
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1, default=str)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# client side (`tpuprof submit`)
# ---------------------------------------------------------------------------

def write_job(spool: str, source: str, output: Optional[str] = None,
              tenant: str = "default",
              stats_json: Optional[str] = None,
              artifact: Optional[str] = None,
              config_kwargs: Optional[Dict[str, Any]] = None,
              job_id: Optional[str] = None) -> str:
    """Drop one request into the spool; returns the job id.  Paths in
    the request are resolved to absolute here — the daemon's cwd is not
    the client's."""
    from tpuprof.serve.jobs import new_job_id
    dirs = _spool_dirs(spool)
    jid = job_id or new_job_id()
    payload = {
        "schema": JOB_SCHEMA, "id": jid, "tenant": str(tenant),
        "source": os.path.abspath(source),
        "output": os.path.abspath(output) if output else None,
        "stats_json": os.path.abspath(stats_json) if stats_json else None,
        "artifact": os.path.abspath(artifact) if artifact else None,
        "config": dict(config_kwargs or {}),
    }
    _atomic_write_json(dirs, os.path.join(dirs["jobs"], f"{jid}.json"),
                       payload)
    return jid


def read_result(spool: str, job_id: str) -> Optional[Dict[str, Any]]:
    """One result read: the record dict, None when it has not landed
    yet, or a typed :class:`~tpuprof.errors.CorruptResultError` when a
    file EXISTS but does not parse — never a raw ``JSONDecodeError``
    (the daemon writes atomically, so a torn record means a non-atomic
    filesystem crash or on-disk rot, which the caller must be able to
    tell apart from "the daemon has not answered")."""
    from tpuprof.errors import CorruptResultError
    path = os.path.join(spool, "results", f"{job_id}.json")
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError:
        return None                     # not answered yet
    try:
        doc = json.loads(data)
    except ValueError as exc:
        raise CorruptResultError(
            f"result file {path!r} is torn or corrupt "
            f"({type(exc).__name__}: {exc})") from exc
    if not isinstance(doc, dict):
        raise CorruptResultError(
            f"result file {path!r} decodes to {type(doc).__name__}, "
            "not a result record")
    return doc


def wait_result(spool: str, job_id: str, timeout: Optional[float] = None,
                poll_interval: float = 0.1) -> Dict[str, Any]:
    """Poll the results dir until the job's terminal record lands.

    A torn result file is re-polled, not fatal — on a non-atomic
    filesystem the writer's rename may still land a whole record — but
    at the deadline the typed :class:`CorruptResultError` surfaces
    instead of a misleading "is the daemon running?" timeout."""
    from tpuprof.errors import CorruptResultError
    deadline = None if timeout is None else time.monotonic() + timeout
    corrupt: Optional[CorruptResultError] = None
    while True:
        try:
            res = read_result(spool, job_id)
            corrupt = None
        except CorruptResultError as exc:
            res, corrupt = None, exc
        if res is not None:
            return res
        if deadline is not None and time.monotonic() > deadline:
            if corrupt is not None:
                raise corrupt
            raise TimeoutError(
                f"no result for job {job_id} after {timeout}s — is "
                f"`tpuprof serve {spool}` running?")
        time.sleep(poll_interval)


# ---------------------------------------------------------------------------
# daemon side (`tpuprof serve`)
# ---------------------------------------------------------------------------

class ServeDaemon:
    """Spool watcher around a :class:`ProfileScheduler`."""

    def __init__(self, spool: str,
                 scheduler: Optional[ProfileScheduler] = None,
                 poll_interval: float = 0.2, **scheduler_kwargs):
        self.spool = spool
        self.dirs = _spool_dirs(spool)
        self.poll_interval = max(float(poll_interval), 0.01)
        self.scheduler = scheduler if scheduler is not None \
            else ProfileScheduler(**scheduler_kwargs)
        self._pending: Dict[str, Job] = {}   # submitted, result not yet out
        self._seen: set = set()
        self.stop_event = threading.Event()

    # -- one scan ----------------------------------------------------------

    def poll_once(self) -> int:
        """Pick up new job files, flush finished jobs' results.
        Returns how many jobs are still in flight (queued/running with
        no result written)."""
        for name in sorted(os.listdir(self.dirs["jobs"])):
            if not name.endswith(".json") or name in self._seen:
                continue
            self._seen.add(name)
            self._ingest_job_file(name)
        for jid, job in list(self._pending.items()):
            if job.state in TERMINAL:
                self._write_result(job)
                del self._pending[jid]
        return len(self._pending)

    def _ingest_job_file(self, name: str) -> None:
        path = os.path.join(self.dirs["jobs"], name)
        # crash-safe restart idempotence: a daemon killed between
        # writing the result and unlinking the request must not re-run
        # (and re-answer) the job on restart — exactly-once results
        jid = name[: -len(".json")]
        if os.path.exists(os.path.join(self.dirs["results"],
                                       f"{jid}.json")):
            self._unlink_job(name)
            return
        try:
            with open(path) as fh:
                req = json.load(fh)
            if req.get("schema") != JOB_SCHEMA:
                raise ValueError(
                    f"job schema {req.get('schema')!r} is not "
                    f"{JOB_SCHEMA}")
            job = Job(source=req["source"], output=req.get("output"),
                      tenant=req.get("tenant") or "default",
                      job_id=req.get("id") or name[: -len(".json")],
                      stats_json=req.get("stats_json"),
                      artifact=req.get("artifact"),
                      config_kwargs=req.get("config") or {})
        except (OSError, ValueError, KeyError, TypeError) as exc:
            # a torn/garbage request file must answer, not rot silently
            # in the spool: synthesize a rejected result under the
            # filename's id so the submitter's wait() terminates
            jid = name[: -len(".json")]
            self._write_result_payload(jid, {
                "schema": RESULT_SCHEMA, "id": jid, "status": "rejected",
                "error": f"unreadable job file: {exc}"})
            self._unlink_job(name)
            return
        job = self.scheduler.submit(job)
        if job.state in TERMINAL:       # rejected at admission
            self._write_result(job)
        else:
            self._pending[job.id] = job

    def _write_result(self, job: Job) -> None:
        payload = {"schema": RESULT_SCHEMA}
        payload.update(job.to_wire())
        self._write_result_payload(job.id, payload)
        self._unlink_job(f"{job.id}.json")

    def _write_result_payload(self, jid: str,
                              payload: Dict[str, Any]) -> None:
        _atomic_write_json(
            self.dirs, os.path.join(self.dirs["results"], f"{jid}.json"),
            payload)

    def _unlink_job(self, name: str) -> None:
        try:
            os.unlink(os.path.join(self.dirs["jobs"], name))
        except OSError:
            pass
        self._seen.discard(name)

    # -- loop --------------------------------------------------------------

    def run(self, once: bool = False) -> None:
        """Serve until :attr:`stop_event` (or, with ``once``, until the
        spool's current jobs are all answered — the CI/test mode)."""
        while not self.stop_event.is_set():
            in_flight = self.poll_once()
            if once and not in_flight \
                    and not os.listdir(self.dirs["jobs"]):
                return
            self.stop_event.wait(self.poll_interval)

    def close(self, timeout: Optional[float] = 30.0) -> None:
        self.stop_event.set()
        self.scheduler.shutdown(wait=True, timeout=timeout)
        # flush results of anything that finished during shutdown
        for jid, job in list(self._pending.items()):
            if job.state in TERMINAL:
                self._write_result(job)
                del self._pending[jid]
