"""`tpuprof serve` daemon + `tpuprof submit` client transport.

Transport is a spool DIRECTORY, not a socket: the repo's coordination
idiom (runtime/fleet.py) and the right fit for the deployment shape —
one resident daemon per host holding the mesh, with clients on the same
host (or shared storage) handing it work.  No ports, no auth surface,
no new dependency; requests and results are plain JSON files written
atomically (tmp + rename), so a crashed client or daemon never leaves a
torn message.

Layout under the spool dir::

    jobs/<id>.json      one request (schema tpuprof-serve-job-v1),
                        written atomically by `tpuprof submit`
    results/<id>.json   the terminal record (tpuprof-serve-result-v1),
                        written atomically by the daemon; the request
                        file is unlinked after the result lands, so a
                        daemon restart re-runs only jobs with no result
    tmp/                atomic-write staging
    claims/             multi-daemon job claims (claim mode only):
                        <id>.claim owned-by content, <id>.steal.<g>
                        generation-g takeovers — the runtime/fleet.py
                        arbiters applied to whole jobs
    daemons/            one mtime heartbeat (hb.<daemon-id>) per live
                        daemon, plus http.<daemon-id> endpoint
                        advertisements from the HTTP edge

**Fleet mode** (``claim_jobs=True`` — `tpuprof serve --http` /
`--claim-jobs`): N daemons share ONE spool.  Exactly one daemon
executes each job — the atomic-create claim is the only arbiter, a
dead daemon's heartbeat goes stale and survivors steal its
claimed-but-unanswered jobs at the next steal generation
(runtime/fleet.py's claim/steal/heartbeat machinery, reused on jobs
instead of fragments).  Results stay exactly-once per id by
construction: they are keyed files written atomically, and every
ingest path skips jobs whose result already landed.  The default
single-daemon spool (`tpuprof serve SPOOL`) takes none of these paths.

The daemon is a thin shell: scanning the spool and writing results; job
lifecycle itself lives in serve/scheduler.py, which `tpuprof submit`,
the bench harness and library embeddings share.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, Iterator, Optional

from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.serve.jobs import TERMINAL, Job
from tpuprof.serve.scheduler import ProfileScheduler

JOB_SCHEMA = "tpuprof-serve-job-v1"
RESULT_SCHEMA = "tpuprof-serve-result-v1"

_CLAIMED = _obs_metrics.gauge(
    "tpuprof_serve_jobs_claimed",
    "spool jobs this daemon has claimed and not yet answered, by "
    "daemon id (fleet mode)")
_STOLEN = _obs_metrics.counter(
    "tpuprof_serve_jobs_stolen_total",
    "spool jobs taken over from dead fleet daemons, by daemon id")
_DRAIN_SECONDS = _obs_metrics.histogram(
    "tpuprof_serve_drain_seconds",
    "graceful-drain duration (stop signal -> daemon closed): in-flight "
    "jobs finished, unstarted claims released to fleet peers, results "
    "flushed (ISSUE 19)")


def poll_intervals(initial: float = 0.1, cap: float = 2.0,
                   factor: float = 2.0,
                   jitter: float = 0.25) -> Iterator[float]:
    """Jittered exponential backoff for result polling — shared by
    :func:`wait_result` (file spool) and the HTTP client poll loop
    (serve/http.py).  Yields sleep durations starting at ``initial``,
    doubling to ``cap``, each scattered by ±``jitter`` so a burst of
    waiting clients never polls in lockstep against one daemon (the
    fixed 0.1 s busy-poll this replaced hammered shared-storage spools
    with N synchronized stat calls per second per client)."""
    delay = max(float(initial), 0.001)
    cap = max(float(cap), delay)
    while True:
        yield delay * (1.0 + random.uniform(-jitter, jitter))
        delay = min(delay * factor, cap)


def _spool_dirs(spool: str) -> Dict[str, str]:
    dirs = {name: os.path.join(spool, name)
            for name in ("jobs", "results", "tmp")}
    for d in dirs.values():
        os.makedirs(d, exist_ok=True)
    return dirs


def _atomic_write_json(dirs: Dict[str, str], path: str,
                       payload: Dict[str, Any]) -> None:
    tmp = os.path.join(dirs["tmp"],
                       f".{os.path.basename(path)}.{os.getpid()}")
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1, default=str)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# client side (`tpuprof submit`)
# ---------------------------------------------------------------------------

def write_job(spool: str, source: str, output: Optional[str] = None,
              tenant: str = "default",
              stats_json: Optional[str] = None,
              artifact: Optional[str] = None,
              config_kwargs: Optional[Dict[str, Any]] = None,
              job_id: Optional[str] = None,
              deadline_unix_ms: Optional[int] = None) -> str:
    """Drop one request into the spool; returns the job id.  Paths in
    the request are resolved to absolute here — the daemon's cwd is not
    the client's.  ``deadline_unix_ms`` (absolute epoch milliseconds —
    relative budgets resolve client-side, where "now" is the submit
    instant) rides the wire so whichever daemon ingests the job —
    including a fleet peer that steals it — enforces the same cutoff
    (ISSUE 19)."""
    from tpuprof.serve.jobs import new_job_id
    dirs = _spool_dirs(spool)
    jid = job_id or new_job_id()
    payload = {
        "schema": JOB_SCHEMA, "id": jid, "tenant": str(tenant),
        "source": os.path.abspath(source),
        "output": os.path.abspath(output) if output else None,
        "stats_json": os.path.abspath(stats_json) if stats_json else None,
        "artifact": os.path.abspath(artifact) if artifact else None,
        "config": dict(config_kwargs or {}),
    }
    if deadline_unix_ms is not None:
        payload["deadline_unix_ms"] = int(deadline_unix_ms)
    _atomic_write_json(dirs, os.path.join(dirs["jobs"], f"{jid}.json"),
                       payload)
    return jid


def read_result(spool: str, job_id: str) -> Optional[Dict[str, Any]]:
    """One result read: the record dict, None when it has not landed
    yet, or a typed :class:`~tpuprof.errors.CorruptResultError` when a
    file EXISTS but does not parse — never a raw ``JSONDecodeError``
    (the daemon writes atomically, so a torn record means a non-atomic
    filesystem crash or on-disk rot, which the caller must be able to
    tell apart from "the daemon has not answered")."""
    from tpuprof.errors import CorruptResultError
    path = os.path.join(spool, "results", f"{job_id}.json")
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError:
        return None                     # not answered yet
    try:
        doc = json.loads(data)
    except ValueError as exc:
        raise CorruptResultError(
            f"result file {path!r} is torn or corrupt "
            f"({type(exc).__name__}: {exc})") from exc
    if not isinstance(doc, dict):
        raise CorruptResultError(
            f"result file {path!r} decodes to {type(doc).__name__}, "
            "not a result record")
    return doc


def wait_result(spool: str, job_id: str, timeout: Optional[float] = None,
                poll_interval: float = 0.1) -> Dict[str, Any]:
    """Poll the results dir until the job's terminal record lands.

    ``poll_interval`` seeds a jittered exponential backoff
    (:func:`poll_intervals`): tight while a warm answer is plausible,
    backing off to a capped cadence for the long waits, never past the
    deadline.  A torn result file is re-polled, not fatal — on a
    non-atomic filesystem the writer's rename may still land a whole
    record — but at the deadline the typed :class:`CorruptResultError`
    surfaces instead of a misleading "is the daemon running?"
    timeout."""
    from tpuprof.errors import CorruptResultError
    deadline = None if timeout is None else time.monotonic() + timeout
    corrupt: Optional[CorruptResultError] = None
    backoff = poll_intervals(poll_interval)
    while True:
        try:
            res = read_result(spool, job_id)
            corrupt = None
        except CorruptResultError as exc:
            res, corrupt = None, exc
        if res is not None:
            return res
        if deadline is not None and time.monotonic() > deadline:
            if corrupt is not None:
                raise corrupt
            raise TimeoutError(
                f"no result for job {job_id} after {timeout}s — is "
                f"`tpuprof serve {spool}` running?")
        sleep = next(backoff)
        if deadline is not None:
            # land ON the deadline, not one full backoff past it
            sleep = min(sleep, max(deadline - time.monotonic(), 0.0)
                        + 0.001)
        time.sleep(sleep)


# ---------------------------------------------------------------------------
# daemon side (`tpuprof serve`)
# ---------------------------------------------------------------------------

class ServeDaemon:
    """Spool watcher around a :class:`ProfileScheduler`.

    With ``claim_jobs=True`` the daemon is one member of a serve
    fleet: it heartbeats under ``daemons/hb.<daemon_id>``, ingests
    only the spool jobs it wins the atomic claim for, and steals a
    dead peer's claimed-but-unanswered jobs once the peer's heartbeat
    goes stale (``liveness_timeout_s``).  The default (False) is the
    historical single-daemon spool, byte-path untouched."""

    def __init__(self, spool: str,
                 scheduler: Optional[ProfileScheduler] = None,
                 poll_interval: float = 0.2,
                 claim_jobs: bool = False,
                 daemon_id: Optional[str] = None,
                 liveness_timeout_s: Optional[float] = None,
                 drain_timeout_s: Optional[float] = None,
                 aot_cache_dir: Optional[str] = None,
                 aot_cache: Optional[str] = None,
                 aot_prewarm: Optional[int] = None,
                 **scheduler_kwargs):
        self.spool = spool
        self.dirs = _spool_dirs(spool)
        self.poll_interval = max(float(poll_interval), 0.01)
        # graceful-drain budget (ISSUE 19): how long close() lets
        # in-flight jobs finish before giving up the wait — the
        # SIGTERM-to-exit bound `tpuprof serve` promises its operator
        from tpuprof.config import resolve_serve_drain_timeout
        self.drain_timeout_s = resolve_serve_drain_timeout(
            drain_timeout_s)
        # AOT executable cache (runtime/aot.py, ISSUE 15): the daemon's
        # restart-to-warm store.  The CLI defaults it to SPOOL/aot;
        # library embeddings opt in by passing a dir (or the env twin).
        from tpuprof.config import (resolve_aot_cache,
                                    resolve_aot_cache_dir,
                                    resolve_aot_prewarm)
        self.aot_cache_dir = None
        if resolve_aot_cache(aot_cache) == "on":
            self.aot_cache_dir = resolve_aot_cache_dir(aot_cache_dir)
        if scheduler is None and self.aot_cache_dir:
            scheduler_kwargs.setdefault("aot_cache_dir",
                                        self.aot_cache_dir)
        self.scheduler = scheduler if scheduler is not None \
            else ProfileScheduler(**scheduler_kwargs)
        # restart prewarm: deserialize the manifest's hottest runner
        # keys in the background while the poll loop below is already
        # accepting jobs; /v1/healthz reports the progress so a fleet
        # balancer can hold traffic until this daemon is warm
        self.prewarmer = None
        if self.aot_cache_dir:
            from tpuprof.runtime import aot as _aot
            self.prewarmer = _aot.Prewarmer(
                self.aot_cache_dir,
                resolve_aot_prewarm(aot_prewarm)).start()
        self._pending: Dict[str, Job] = {}   # submitted, result not yet out
        self._seen: set = set()
        self.stop_event = threading.Event()
        self.claim_jobs = bool(claim_jobs)
        self.daemon_id = None
        self._hb_thread = None
        if self.claim_jobs:
            from tpuprof.config import (resolve_fleet_host_id,
                                        resolve_liveness_timeout)
            self.daemon_id = resolve_fleet_host_id(daemon_id)
            if "/" in self.daemon_id:
                raise ValueError(
                    f"daemon_id {self.daemon_id!r} must be a plain "
                    "filename token (it names heartbeat/claim files)")
            self.liveness_timeout_s = \
                resolve_liveness_timeout(liveness_timeout_s)
            for name in ("claims", "daemons"):
                self.dirs[name] = os.path.join(spool, name)
                os.makedirs(self.dirs[name], exist_ok=True)
            # heartbeat BEFORE the first claim: a claim by a daemon
            # with no heartbeat file would read as instantly dead
            from tpuprof.runtime import fleet as _fleet
            self._hb_path = os.path.join(self.dirs["daemons"],
                                         f"hb.{self.daemon_id}")
            _fleet.atomic_write(self._hb_path, b"alive\n")
            self._hb_thread = threading.Thread(
                target=self._beat, daemon=True,
                name=f"tpuprof-serve-hb-{self.daemon_id}")
            self._hb_thread.start()
            _obs_events.emit("serve_fleet_join", daemon=self.daemon_id,
                             spool=self.spool)

    # -- fleet membership (claim mode only) --------------------------------

    def _beat(self) -> None:
        # mtime refresh is the liveness signal, exactly the
        # runtime/fleet.py heartbeat contract; a SIGKILL stops the
        # refresh and the file goes stale, a graceful close() deletes
        # it so peers steal leftovers immediately
        interval = min(max(self.liveness_timeout_s / 4.0, 0.05), 2.0)
        from tpuprof.runtime import fleet as _fleet
        while not self.stop_event.wait(interval):
            try:
                os.utime(self._hb_path)
            except OSError:
                try:
                    _fleet.atomic_write(self._hb_path, b"alive\n")
                except OSError:
                    pass

    def _daemon_alive(self, daemon_id: str) -> bool:
        try:
            mtime = os.path.getmtime(
                os.path.join(self.dirs["daemons"], f"hb.{daemon_id}"))
        except OSError:
            return False            # no heartbeat file = dead
        return time.time() - mtime < self.liveness_timeout_s

    def _scan_claims(self) -> Dict[str, tuple]:
        """One directory read -> {job_id: (generation, owner_path)};
        the owner's NAME is read lazily (only for jobs we might act
        on).  Generation 0 is the original claim, g >= 1 are steals —
        highest generation owns the job."""
        out: Dict[str, tuple] = {}
        try:
            names = os.listdir(self.dirs["claims"])
        except OSError:
            return out
        for name in names:
            if name.startswith("."):
                continue            # in-flight atomic-write temps
            if name.endswith(".claim"):
                jid, gen = name[: -len(".claim")], 0
            else:
                jid, _, g = name.rpartition(".steal.")
                if not jid or not g.isdigit():
                    continue
                gen = int(g)
            cur = out.get(jid)
            if cur is None or gen > cur[0]:
                out[jid] = (gen,
                            os.path.join(self.dirs["claims"], name))
        return out

    def _try_own(self, jid: str,
                 claims: Dict[str, tuple]) -> bool:
        """Claim-mode arbiter for one spooled job: True exactly when
        THIS daemon owns it now (fresh claim won, already ours from a
        restart, or stolen from a dead peer)."""
        from tpuprof.runtime import fleet as _fleet
        claim_path = os.path.join(self.dirs["claims"], f"{jid}.claim")
        cur = claims.get(jid)
        if cur is None:
            # unclaimed: the atomic hardlink create is the whole
            # arbiter — exactly one winner, losers see EEXIST
            return _fleet.excl_create(claim_path, self.daemon_id)
        gen, owner_path = cur
        owner = _fleet.read_small(owner_path)
        if owner == self.daemon_id:
            # ours — either the HTTP edge claimed it synchronously
            # (already in _seen) or a restart with the same daemon_id
            # is adopting its predecessor's unanswered claims
            return True
        if owner and self._daemon_alive(owner):
            return False            # a live peer's job
        # owner dead (or claim unreadable): take generation g+1.
        # Thieves are subject to liveness like anyone else, so a dead
        # thief's loot is re-stealable at g+2 — runtime/fleet.py's
        # steal-generation contract on jobs
        steal_path = os.path.join(self.dirs["claims"],
                                  f"{jid}.steal.{gen + 1}")
        if _fleet.excl_create(steal_path, self.daemon_id):
            _STOLEN.inc(daemon=self.daemon_id)
            _obs_events.emit("serve_job_stolen", job=jid,
                             daemon=self.daemon_id,
                             from_daemon=owner, generation=gen + 1)
            return True
        return False

    def _cleanup_claims(self, jid: str) -> None:
        if not self.claim_jobs:
            return
        try:
            names = os.listdir(self.dirs["claims"])
        except OSError:
            return
        for name in names:
            if name == f"{jid}.claim" \
                    or name.startswith(f"{jid}.steal."):
                try:
                    os.unlink(os.path.join(self.dirs["claims"], name))
                except OSError:
                    pass

    # -- one scan ----------------------------------------------------------

    def poll_once(self) -> int:
        """Pick up new job files, flush finished jobs' results.
        Returns how many jobs are still in flight (queued/running with
        no result written)."""
        claims = self._scan_claims() if self.claim_jobs else None
        # pull, don't hoard: a fleet daemon claims only what its
        # workers can soon run (workers x2 of prefetch) — claiming the
        # whole spool up front would serialize a burst onto whichever
        # daemon's scan ran first and starve its peers (the fleet
        # scheduler's "a slow host claims less" contract, on jobs)
        claim_budget = self.scheduler.workers * 2 - len(self._pending) \
            if claims is not None else 0
        for name in sorted(os.listdir(self.dirs["jobs"])):
            if not name.endswith(".json") or name in self._seen:
                continue
            jid = name[: -len(".json")]
            if claims is not None:
                if os.path.exists(os.path.join(self.dirs["results"],
                                               f"{jid}.json")):
                    # answered (possibly by a peer): consume the
                    # request so no daemon ever re-runs it
                    self._unlink_job(name)
                    self._cleanup_claims(jid)
                    continue
                if claim_budget <= 0:
                    continue
                if not self._try_own(jid, claims):
                    # a peer's job — NOT added to _seen: it is
                    # re-examined every poll so a stale owner's jobs
                    # become stealable
                    continue
                claim_budget -= 1
                _CLAIMED.set(float(len(self._pending) + 1),
                             daemon=self.daemon_id)
            self._seen.add(name)
            self._ingest_job_file(name)
        for jid, job in list(self._pending.items()):
            if job.state in TERMINAL:
                self._write_result(job)
                del self._pending[jid]
        if claims is not None:
            _CLAIMED.set(float(len(self._pending)),
                         daemon=self.daemon_id)
        return len(self._pending)

    def _ingest_job_file(self, name: str) -> None:
        path = os.path.join(self.dirs["jobs"], name)
        # crash-safe restart idempotence: a daemon killed between
        # writing the result and unlinking the request must not re-run
        # (and re-answer) the job on restart — exactly-once results
        jid = name[: -len(".json")]
        if os.path.exists(os.path.join(self.dirs["results"],
                                       f"{jid}.json")):
            self._unlink_job(name)
            self._cleanup_claims(jid)
            return
        try:
            with open(path) as fh:
                req = json.load(fh)
            if req.get("schema") != JOB_SCHEMA:
                raise ValueError(
                    f"job schema {req.get('schema')!r} is not "
                    f"{JOB_SCHEMA}")
            deadline_ms = req.get("deadline_unix_ms")
            job = Job(source=req["source"], output=req.get("output"),
                      tenant=req.get("tenant") or "default",
                      job_id=req.get("id") or name[: -len(".json")],
                      stats_json=req.get("stats_json"),
                      artifact=req.get("artifact"),
                      config_kwargs=req.get("config") or {},
                      deadline_unix=(int(deadline_ms) / 1000.0
                                     if deadline_ms is not None
                                     else None))
        except (OSError, ValueError, KeyError, TypeError) as exc:
            # a torn/garbage request file must answer, not rot silently
            # in the spool: synthesize a rejected result under the
            # filename's id so the submitter's wait() terminates
            jid = name[: -len(".json")]
            self._write_result_payload(jid, {
                "schema": RESULT_SCHEMA, "id": jid, "status": "rejected",
                "error": f"unreadable job file: {exc}"})
            self._unlink_job(name)
            self._cleanup_claims(jid)
            return
        job = self.scheduler.submit(job)
        if job.state in TERMINAL:       # rejected at admission
            self._write_result(job)
        else:
            self._pending[job.id] = job

    def _write_result(self, job: Job) -> None:
        payload = {"schema": RESULT_SCHEMA}
        payload.update(job.to_wire())
        if self.daemon_id:
            payload["daemon"] = self.daemon_id
        self._write_result_payload(job.id, payload)
        self._unlink_job(f"{job.id}.json")
        self._cleanup_claims(job.id)

    def _write_result_payload(self, jid: str,
                              payload: Dict[str, Any]) -> None:
        _atomic_write_json(
            self.dirs, os.path.join(self.dirs["results"], f"{jid}.json"),
            payload)

    def _unlink_job(self, name: str) -> None:
        try:
            os.unlink(os.path.join(self.dirs["jobs"], name))
        except OSError:
            pass
        self._seen.discard(name)

    # -- HTTP-edge admission (serve/http.py) -------------------------------

    def submit_local(self, source: str, output: Optional[str] = None,
                     tenant: str = "default",
                     stats_json: Optional[str] = None,
                     artifact: Optional[str] = None,
                     config_kwargs: Optional[Dict[str, Any]] = None,
                     deadline_unix: Optional[float] = None
                     ) -> Job:
        """Admit one job through THIS daemon's scheduler, durably.

        The job file lands in the shared spool BEFORE admission and is
        claimed by this daemon, so an HTTP-accepted job survives its
        accepting daemon: a SIGKILL mid-run leaves a spooled request
        whose claim goes stale, and any surviving fleet peer steals
        and answers it (the PR-10 exactly-once result contract, now
        fleet-wide).  Admission REJECTIONS answer synchronously (the
        HTTP 4xx) and also spool a result record so a polling client
        sees the same terminal state either way."""
        from tpuprof.serve.jobs import new_job_id
        jid = new_job_id()
        if self.claim_jobs:
            # claim BEFORE the job file lands: a peer's scan between
            # spool-write and claim would otherwise win the claim and
            # run the job a second time next to our local admission
            from tpuprof.runtime import fleet as _fleet
            _fleet.excl_create(
                os.path.join(self.dirs["claims"], f"{jid}.claim"),
                self.daemon_id)
        write_job(self.spool, source, output=output, tenant=tenant,
                  stats_json=stats_json, artifact=artifact,
                  config_kwargs=config_kwargs, job_id=jid,
                  deadline_unix_ms=(int(deadline_unix * 1000)
                                    if deadline_unix is not None
                                    else None))
        self._seen.add(f"{jid}.json")   # the poll loop must not re-ingest
        job = Job(source=os.path.abspath(source),
                  output=os.path.abspath(output) if output else None,
                  tenant=tenant, job_id=jid,
                  stats_json=os.path.abspath(stats_json)
                  if stats_json else None,
                  artifact=os.path.abspath(artifact)
                  if artifact else None,
                  config_kwargs=dict(config_kwargs or {}),
                  deadline_unix=deadline_unix)
        job = self.scheduler.submit(job)
        if job.state in TERMINAL:       # rejected at admission
            self._write_result(job)
        else:
            self._pending[job.id] = job
        return job

    # -- loop --------------------------------------------------------------

    def run(self, once: bool = False) -> None:
        """Serve until :attr:`stop_event` (or, with ``once``, until the
        spool's current jobs are all answered — the CI/test mode)."""
        while not self.stop_event.is_set():
            in_flight = self.poll_once()
            if once and not in_flight \
                    and not os.listdir(self.dirs["jobs"]):
                return
            self.stop_event.wait(self.poll_interval)

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful drain (ISSUE 19): finish what is RUNNING, hand back
        what is not.  Queued jobs this daemon claimed but never started
        are released — pulled from the local queue, their spool claims
        unlinked — so fleet peers steal and answer them immediately
        (the job files stay; no result is written here, so the peer's
        is the one result).  In-flight jobs get up to the drain budget
        to finish and flush; then the heartbeat departs.  ``timeout``
        overrides the daemon's ``drain_timeout_s`` when given."""
        t0 = time.monotonic()
        drain_budget = self.drain_timeout_s if timeout is None \
            else float(timeout)
        self.stop_event.set()
        released = []
        if self.claim_jobs:
            # release BEFORE the queue closes: peers must win these,
            # not this daemon's exiting workers.  Only spool-backed
            # jobs qualify — an HTTP /v1/query compute has no job file
            # for a peer to steal and a local handler blocked on it,
            # so it must drain here instead.
            released = self.scheduler.release_queued(
                select=lambda j: j.id in self._pending)
            for job in released:
                self._pending.pop(job.id, None)
                self._seen.discard(f"{job.id}.json")
                self._cleanup_claims(job.id)
        self.scheduler.shutdown(wait=True, timeout=drain_budget)
        # flush results of anything that finished during shutdown
        for jid, job in list(self._pending.items()):
            if job.state in TERMINAL:
                self._write_result(job)
                del self._pending[jid]
        if self.claim_jobs:
            # graceful depart: delete the heartbeat so fleet peers
            # steal any leftover claims immediately instead of waiting
            # out the liveness timeout (the fleet.depart idiom); a
            # SIGKILL skips this and the mtime goes stale instead
            try:
                os.unlink(self._hb_path)
            except OSError:
                pass
            if self._hb_thread is not None:
                self._hb_thread.join(timeout=5)
            _obs_events.emit("serve_fleet_depart",
                             daemon=self.daemon_id,
                             unanswered=len(self._pending))
        seconds = time.monotonic() - t0
        _DRAIN_SECONDS.observe(seconds)
        if _obs_metrics.enabled():
            _obs_events.emit("serve_drain", daemon=self.daemon_id,
                             seconds=round(seconds, 4),
                             released=len(released),
                             unanswered=len(self._pending))
