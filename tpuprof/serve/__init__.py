"""Profile-as-a-service (`tpuprof serve`) — ROADMAP item 1.

One resident process per host holds the device mesh and a keyed
compiled-program cache, so the 20-40 s process-startup + JIT cold start
is paid once and every warm profile answers in sub-seconds:

* serve/cache.py      keyed MeshRunner cache (config fingerprint fields
                      + shape signature) + the per-process persistent-
                      compile-cache gate
* serve/jobs.py       job state machine + bounded multi-tenant queue
* serve/scheduler.py  worker pool, SLO metrics, job lifecycle
* serve/server.py     spool-directory daemon + submit client transport

The CLI (`tpuprof serve` / `tpuprof submit`) is one client of this
package; embed :class:`ProfileScheduler` directly for in-process use
(the serve bench does).
"""

from tpuprof.serve.cache import (RunnerCache, acquire_runner, cache_stats,
                                 process_cache, runner_key)
from tpuprof.serve.jobs import (Job, JobQueue, QueueClosed, QueueFull,
                                TenantQuotaExceeded)
from tpuprof.serve.scheduler import ProfileScheduler
from tpuprof.serve.server import (ServeDaemon, read_result, wait_result,
                                  write_job)

__all__ = [
    "Job", "JobQueue", "ProfileScheduler", "QueueClosed", "QueueFull",
    "RunnerCache", "ServeDaemon", "TenantQuotaExceeded",
    "acquire_runner", "cache_stats", "process_cache", "read_result",
    "runner_key", "wait_result", "write_job",
]
