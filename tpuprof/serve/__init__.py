"""Profile-as-a-service (`tpuprof serve`) — ROADMAP item 1.

One resident process per host holds the device mesh and a keyed
compiled-program cache, so the 20-40 s process-startup + JIT cold start
is paid once and every warm profile answers in sub-seconds:

* serve/cache.py      keyed MeshRunner cache (config fingerprint fields
                      + shape signature) + the per-process persistent-
                      compile-cache gate
* serve/jobs.py       job state machine + bounded multi-tenant queue
* serve/scheduler.py  worker pool, SLO metrics, job lifecycle,
                      per-job watchdog (job_timeout_s)
* serve/server.py     spool-directory daemon + submit client transport
* serve/watch.py      continuous drift watch: scheduled re-profiles,
                      artifact retention, alerting, crash-safe
                      watch-manifest recovery (ROBUSTNESS.md rung 6)

The CLI (`tpuprof serve` / `tpuprof submit`) is one client of this
package; embed :class:`ProfileScheduler` directly for in-process use
(the serve bench does).
"""

from tpuprof.serve.cache import (RunnerCache, acquire_runner, cache_stats,
                                 process_cache, runner_key)
from tpuprof.serve.jobs import (Job, JobQueue, QueueClosed, QueueFull,
                                TenantQuotaExceeded)
from tpuprof.serve.scheduler import ProfileScheduler
from tpuprof.serve.server import (ServeDaemon, read_result, wait_result,
                                  write_job)
from tpuprof.serve.watch import (DriftWatcher, SourceWatch,
                                 WATCH_MANIFEST_SCHEMA, read_manifest,
                                 write_manifest)

__all__ = [
    "DriftWatcher", "Job", "JobQueue", "ProfileScheduler",
    "QueueClosed", "QueueFull", "RunnerCache", "ServeDaemon",
    "SourceWatch", "TenantQuotaExceeded", "WATCH_MANIFEST_SCHEMA",
    "acquire_runner", "cache_stats", "process_cache", "read_manifest",
    "read_result", "runner_key", "wait_result", "write_job",
    "write_manifest",
]
