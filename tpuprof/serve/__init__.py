"""Profile-as-a-service (`tpuprof serve`) — ROADMAP item 1.

One resident process per host holds the device mesh and a keyed
compiled-program cache, so the 20-40 s process-startup + JIT cold start
is paid once and every warm profile answers in sub-seconds:

* serve/cache.py      keyed MeshRunner cache (config fingerprint fields
                      + shape signature) + the per-process persistent-
                      compile-cache gate + the edge ResultCache (read
                      tier: terminal answers keyed by source/config
                      fingerprints, CRC-checked, LRU-bounded)
* serve/jobs.py       job state machine + bounded multi-tenant queue
* serve/scheduler.py  worker pool, SLO metrics, job lifecycle,
                      per-job watchdog (job_timeout_s)
* serve/server.py     spool-directory daemon + submit client transport,
                      plus the fleet claim path (N daemons, one spool:
                      atomic job claims, heartbeats, stale-claim steal)
* serve/http.py       the network edge: selector-based async HTTP
                      server on the same scheduler (POST /v1/jobs,
                      results with ETag/304, POST /v1/query pushdown,
                      metrics, watch alert feeds; bearer-token ->
                      tenant auth) + the `tpuprof submit --url` client
* serve/watch.py      continuous drift watch: scheduled re-profiles,
                      artifact retention, alerting, crash-safe
                      watch-manifest recovery (ROBUSTNESS.md rung 6)

The CLI (`tpuprof serve` / `tpuprof submit`) is one client of this
package; embed :class:`ProfileScheduler` directly for in-process use
(the serve bench does).
"""

from tpuprof.serve.cache import (ResultCache, RunnerCache, acquire_runner,
                                 cache_stats, canonical_body, etag_for,
                                 process_cache, runner_key,
                                 source_fingerprint)
from tpuprof.serve.http import (HttpEdge, discover_edges, load_auth_file,
                                submit_job, wait_result_http)
from tpuprof.serve.jobs import (Job, JobQueue, QueueClosed, QueueFull,
                                TenantQuotaExceeded)
from tpuprof.serve.scheduler import ProfileScheduler
from tpuprof.serve.server import (ServeDaemon, poll_intervals,
                                  read_result, wait_result, write_job)
from tpuprof.serve.watch import (DriftWatcher, SourceWatch,
                                 WATCH_MANIFEST_SCHEMA, read_manifest,
                                 write_manifest)

__all__ = [
    "DriftWatcher", "HttpEdge", "Job", "JobQueue", "ProfileScheduler",
    "QueueClosed", "QueueFull", "ResultCache", "RunnerCache",
    "ServeDaemon", "SourceWatch", "TenantQuotaExceeded",
    "WATCH_MANIFEST_SCHEMA", "acquire_runner", "cache_stats",
    "canonical_body", "discover_edges", "etag_for", "load_auth_file",
    "poll_intervals", "process_cache", "read_manifest", "read_result",
    "runner_key", "source_fingerprint", "submit_job", "wait_result",
    "wait_result_http", "write_job", "write_manifest",
]
