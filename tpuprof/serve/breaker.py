"""Circuit breaker on the warehouse-pushdown read tier (ISSUE 19).

A rotting warehouse chain makes every ``POST /v1/query`` pay a full
walk over corrupt generations before falling through to compute —
under read-heavy load that is a disk-scan tax on EVERY query of the
rotten source.  The breaker makes that tax one-time: consecutive
failed/corrupt generation reads per source open the breaker, and an
open breaker routes queries straight to the compute tier (the answer
labeled ``provenance:"breaker_open"`` so operators can see the detour
in the wild).  After ``breaker_cooldown_s`` the breaker goes half-open
and lets exactly ONE probe back through the warehouse: a fresh answer
closes it, another failure re-opens it for another cooldown.

States are per source key, transitions are events + metrics
(``breaker_transition`` / ``tpuprof_breaker_transitions_total``), and
the whole thing is process-local by design: a breaker is a latency
shield, not a correctness gate — the compute tier behind it is always
right, so the worst cost of a wrong state is one wasted walk or one
delayed warehouse answer.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_TRANSITIONS = _obs_metrics.counter(
    "tpuprof_breaker_transitions_total",
    "warehouse-pushdown circuit-breaker state transitions by the state "
    "entered (open = a source's warehouse reads keep failing, queries "
    "detour to compute; half_open = one probe allowed; closed = the "
    "probe answered, the warehouse is trusted again)")


class _State:
    __slots__ = ("state", "failures", "opened_at", "probing")

    def __init__(self) -> None:
        self.state = CLOSED
        self.failures = 0            # consecutive, reset on any success
        self.opened_at = 0.0
        self.probing = False         # a half-open probe is in flight


class CircuitBreaker:
    """Per-key consecutive-failure breaker (closed/open/half-open)."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self._lock = threading.Lock()
        self._states: Dict[str, _State] = {}

    def _transition(self, key: str, st: _State, state: str) -> None:
        st.state = state
        if _obs_metrics.enabled():
            _TRANSITIONS.inc(state=state)
            _obs_events.emit("breaker_transition", source=key,
                             state=state, failures=st.failures)

    def allow(self, key: str) -> bool:
        """May a warehouse read for ``key`` proceed?  Open -> no (skip
        to compute).  Half-open admits exactly one probe per cooldown
        window; concurrent queries during the probe stay on compute."""
        with self._lock:
            st = self._states.get(key)
            if st is None or st.state == CLOSED:
                return True
            if st.state == OPEN \
                    and time.monotonic() - st.opened_at >= self.cooldown_s:
                self._transition(key, st, HALF_OPEN)
            if st.state == HALF_OPEN and not st.probing:
                st.probing = True
                return True
            return False

    def record_success(self, key: str) -> None:
        with self._lock:
            st = self._states.get(key)
            if st is None:
                return
            st.failures = 0
            st.probing = False
            if st.state != CLOSED:
                self._transition(key, st, CLOSED)

    def record_failure(self, key: str) -> None:
        with self._lock:
            st = self._states.setdefault(key, _State())
            st.failures += 1
            st.probing = False
            if st.state == HALF_OPEN \
                    or (st.state == CLOSED
                        and st.failures >= self.threshold):
                st.opened_at = time.monotonic()
                self._transition(key, st, OPEN)
            elif st.state == OPEN:
                # a failure while open (racing walker): push the
                # cooldown out — the source is still rotten
                st.opened_at = time.monotonic()

    def state(self, key: str) -> str:
        with self._lock:
            st = self._states.get(key)
            return st.state if st is not None else CLOSED

    def snapshot(self) -> Dict[str, Any]:
        """Healthz view: every non-closed source plus totals."""
        with self._lock:
            open_keys = {k: {"state": s.state, "failures": s.failures}
                         for k, s in self._states.items()
                         if s.state != CLOSED}
            return {"tracked": len(self._states),
                    "open": open_keys}


_default: Optional[CircuitBreaker] = None
_default_lock = threading.Lock()


def default_breaker() -> CircuitBreaker:
    """The process-wide breaker the HTTP edge consults when the daemon
    did not build its own (library embeddings, tests)."""
    global _default
    with _default_lock:
        if _default is None:
            from tpuprof.config import (resolve_breaker_cooldown,
                                        resolve_breaker_threshold)
            _default = CircuitBreaker(
                threshold=resolve_breaker_threshold(),
                cooldown_s=resolve_breaker_cooldown())
        return _default


def reset_default() -> None:
    """Test hook: forget the process-wide breaker's state."""
    global _default
    with _default_lock:
        _default = None
