"""Shared parse state for one lint run.

Checkers never touch the filesystem directly: the context loads the
package tree ONCE (ast.parse per module, parent links, docs, the
EVENT_SCHEMA test contract) and every checker reads from it.  That is
both the speed contract (the whole suite must stay under the bench
guard's 5 s so it can live in tier-1 forever) and the seam that lets
tests lint SYNTHETIC trees: point :class:`AnalysisContext` at a tmp dir
holding a doctored ``tpuprof/`` + docs and the checkers see only that.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Tuple

#: docs the checkers parse, looked up root-relative
DOC_NAMES = ("README.md", "OBSERVABILITY.md", "ROBUSTNESS.md",
             "ARTIFACTS.md", "ANALYSIS.md")

#: where the JSONL event contract lives (tests/test_obs_smoke.py keeps
#: the runtime validator; the lint obs checker reads the same dict so
#: there is exactly one schema)
EVENT_SCHEMA_FILE = os.path.join("tests", "test_obs_smoke.py")


class SourceFile:
    """One parsed module: root-relative path, source text, AST, and a
    child->parent node map (built lazily — most files never need it)."""

    def __init__(self, relpath: str, text: str, tree: ast.Module):
        self.relpath = relpath
        self.text = text
        self.tree = tree
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents.get(node)


def call_name(node: ast.AST) -> str:
    """Best-effort dotted name of a call target: ``os.path.join``,
    ``faults.hit``, ``open``.  Unresolvable pieces render as ``?`` so
    ``endswith`` checks still work on the known tail."""
    if isinstance(node, ast.Call):
        node = node.func
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def literal_head(node: ast.AST) -> Optional[str]:
    """The LEADING literal text of a string-producing expression — the
    part of a filename a prefix scan would see.  Handles plain
    constants, f-strings (first chunk), ``"." + x`` concatenations and
    ``os.path.join(..., tail)`` (delegates to the last arg).  None =
    the expression starts with runtime data (nothing provable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        return literal_head(node.values[0])
    if isinstance(node, ast.FormattedValue):
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return literal_head(node.left)
    if isinstance(node, ast.Call) and call_name(node).endswith("join") \
            and node.args:
        return literal_head(node.args[-1])
    return None


class AnalysisContext:
    """Parsed view of one repo tree rooted at ``root``.

    ``package`` is the package directory name under the root (always
    ``tpuprof`` for the real tree; synthetic test trees mirror it).
    Modules that fail to parse surface as findings from every checker's
    caller (``parse_errors``) rather than crashing the run.
    """

    def __init__(self, root: str, package: str = "tpuprof"):
        self.root = os.path.abspath(root)
        self.package = package
        self.files: List[SourceFile] = []
        self.parse_errors: List[Tuple[str, str]] = []
        self._docs: Dict[str, Optional[str]] = {}
        pkg_dir = os.path.join(self.root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, name)
                relpath = os.path.relpath(abspath, self.root)
                try:
                    with open(abspath, encoding="utf-8") as fh:
                        text = fh.read()
                    tree = ast.parse(text, filename=relpath)
                except (OSError, SyntaxError) as exc:
                    self.parse_errors.append((relpath, str(exc)))
                    continue
                self.files.append(SourceFile(relpath, text, tree))

    # -- lookups ------------------------------------------------------------

    def file(self, *suffixes: str) -> Optional[SourceFile]:
        """The first package module whose root-relative path ends with
        one of ``suffixes`` (``/``-normalized)."""
        for sf in self.files:
            norm = sf.relpath.replace(os.sep, "/")
            if any(norm.endswith(s) for s in suffixes):
                return sf
        return None

    def doc_text(self, name: str) -> Optional[str]:
        if name not in self._docs:
            try:
                with open(os.path.join(self.root, name),
                          encoding="utf-8") as fh:
                    self._docs[name] = fh.read()
            except OSError:
                self._docs[name] = None
        return self._docs[name]

    def doc_line(self, name: str, needle: str) -> int:
        """1-based first line of ``needle`` in doc ``name`` (0 = not
        found / no doc) — findings point at the drifted doc row."""
        text = self.doc_text(name)
        if not text:
            return 0
        for i, line in enumerate(text.splitlines(), 1):
            if needle in line:
                return i
        return 0

    # -- cross-file AST sweeps (shared by several checkers) -----------------

    def iter_calls(self) -> Iterator[Tuple[SourceFile, ast.Call]]:
        for sf in self.files:
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    yield sf, node

    def string_literals(self) -> Iterator[Tuple[SourceFile, str]]:
        for sf in self.files:
            for node in ast.walk(sf.tree):
                v = const_str(node)
                if v is not None:
                    yield sf, v

    def event_schema_keys(self) -> Optional[Dict[str, int]]:
        """kind -> line of the ``EVENT_SCHEMA`` dict in the obs smoke
        test — the one JSONL event contract.  None = the contract file
        is missing or holds no EVENT_SCHEMA (itself a finding)."""
        path = os.path.join(self.root, EVENT_SCHEMA_FILE)
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=EVENT_SCHEMA_FILE)
        except (OSError, SyntaxError):
            return None
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "EVENT_SCHEMA"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Dict):
                out = {}
                for k in node.value.keys:
                    v = const_str(k)
                    if v is not None:
                        out[v] = k.lineno
                return out
        return None
