"""Checker registry + the one entrypoint (`run_lint`).

A checker is a function ``(AnalysisContext) -> List[Finding]``
registered under a stable id with :func:`checker`.  Adding a checker is
three steps (ANALYSIS.md "Adding a checker"): write the function in
``tpuprof/analysis/checkers/``, decorate it, import the module from
``checkers/__init__`` so registration runs.  The registry is ordered —
checkers run (and report) in registration order, so output stays diff-
stable across runs.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

from tpuprof.analysis import suppress
from tpuprof.analysis.context import AnalysisContext
from tpuprof.analysis.model import Finding, LintReport

CheckerFn = Callable[[AnalysisContext], List[Finding]]

_CHECKERS: "Dict[str, CheckerFn]" = {}
_DOCS: Dict[str, str] = {}


def checker(checker_id: str, doc: str) -> Callable[[CheckerFn], CheckerFn]:
    """Register ``fn`` under ``checker_id`` (one line of ``doc`` feeds
    ``tpuprof lint --list`` and the ANALYSIS.md catalogue test)."""

    def _register(fn: CheckerFn) -> CheckerFn:
        if checker_id in _CHECKERS:
            raise ValueError(f"duplicate checker id {checker_id!r}")
        _CHECKERS[checker_id] = fn
        _DOCS[checker_id] = doc
        return fn

    return _register


def checker_ids() -> List[str]:
    _ensure_loaded()
    return list(_CHECKERS)


def checker_doc(checker_id: str) -> str:
    _ensure_loaded()
    return _DOCS[checker_id]


def _ensure_loaded() -> None:
    # importing the subpackage runs every @checker decorator exactly
    # once; lazy so `import tpuprof` never pays the checker imports
    from tpuprof.analysis import checkers  # noqa: F401


def run_lint(root: str, only: Optional[Sequence[str]] = None,
             suppressions: Optional[str] = None,
             strict: bool = False,
             package: str = "tpuprof") -> LintReport:
    """Run the invariant suite over the tree at ``root``.

    ``only`` limits to the named checker ids (unknown ids raise — a CI
    job invoking a misspelled checker must fail loudly, not pass
    empty).  ``strict`` ignores the suppression file entirely: every
    finding reports, none absorb.  Suppression bookkeeping (malformed
    + stale entries) reports through the pseudo-checker id
    ``suppressions``.
    """
    _ensure_loaded()
    t0 = time.perf_counter()
    if only:
        unknown = [c for c in only if c not in _CHECKERS]
        if unknown:
            raise ValueError(
                f"unknown checker id(s) {unknown} — known: "
                f"{list(_CHECKERS)}")
        run_ids = [c for c in _CHECKERS if c in set(only)]
    else:
        run_ids = list(_CHECKERS)

    ctx = AnalysisContext(root, package=package)
    findings: List[Finding] = [
        Finding(checker="parse", path=relpath, line=0,
                ident=f"parse:{relpath}",
                message=f"module failed to parse: {err}")
        for relpath, err in ctx.parse_errors
    ]
    for cid in run_ids:
        found = _CHECKERS[cid](ctx)
        # checker order is registration order; within a checker, sort
        # by location so output is stable across dict-iteration quirks
        findings.extend(sorted(found,
                               key=lambda f: (f.path, f.line, f.ident)))

    report = LintReport(root=ctx.root, findings=findings,
                        checkers_run=run_ids)
    if not strict:
        entries, bad = suppress.load(root, suppressions)
        suppressed, stale = suppress.apply(
            findings, entries, suppressions or suppress.DEFAULT_FILE)
        report.suppressed = suppressed
        # a partial run (--only) cannot judge staleness: entries for
        # checkers that did not run are legitimately un-hit
        report.findings = report.findings + bad \
            + (stale if only is None else [])
    report.wall_s = time.perf_counter() - t0
    return report
