"""tpuprof/analysis — the AST-enforced invariant suite (ANALYSIS.md).

The profiler's correctness rests on conventions — atomic tmp+rename
publication, dot-prefixed tmp names, the config⇄env⇄CLI⇄doc surface,
metric/event names synced to their docs, the error⇄exit-code taxonomy,
the locked runner seam — that used to be enforced by scattered
doc-sync tests and live incident response (two real tmp-name races
shipped before this suite existed: the ``part....tmp.<pid>``
prefix-scan race in PR 7 and the shared-pid tmp-unlink race in PR 11).
`tpuprof lint` machine-checks them on every PR instead.

Public surface::

    from tpuprof.analysis import run_lint
    report = run_lint("/path/to/repo")        # LintReport
    report.unsuppressed()                     # [] = clean tree
    report.to_json()                          # tpuprof-lint-v1

Exit-code contract (CLI ``tpuprof lint``): clean tree → 0, any
unsuppressed finding → 2 (:class:`tpuprof.errors.LintFindingsError`).
"""

from tpuprof.analysis.model import LINT_SCHEMA, Finding, LintReport
from tpuprof.analysis.registry import (checker, checker_doc, checker_ids,
                                       run_lint)
from tpuprof.obs import metrics as _obs_metrics

#: one count per unsuppressed finding, labelled by checker id — a CI
#: lint run with metrics on exposes drift the same way every other
#: subsystem exposes failure (OBSERVABILITY.md "Lint")
FINDINGS_TOTAL = _obs_metrics.counter(
    "tpuprof_lint_findings_total",
    "unsuppressed lint findings by checker id (tpuprof/analysis)")


def observe(report: LintReport) -> None:
    """Record a finished run's findings on the process registry (the
    CLI calls this; library callers may too)."""
    for f in report.unsuppressed():
        FINDINGS_TOTAL.inc(checker=f.checker)


__all__ = ["Finding", "LintReport", "LINT_SCHEMA", "run_lint",
           "checker", "checker_ids", "checker_doc", "observe",
           "FINDINGS_TOTAL"]
