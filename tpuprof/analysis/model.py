"""Findings model for `tpuprof lint` (ANALYSIS.md).

A :class:`Finding` is one violated invariant at one location.  Its
``ident`` is the STABLE identity the suppression file matches against —
never a line number (line numbers churn on every edit; a suppression
keyed to one would silently stop matching).  The JSON export
(``tpuprof lint --json``) carries the ``tpuprof-lint-v1`` schema id so
CI consumers can gate on a format, not on stdout prose.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

LINT_SCHEMA = "tpuprof-lint-v1"

#: findings -> CLI exit 2 (errors.LintFindingsError, an InputError: "the
#: tree the user asked us to bless is not blessable")
SEVERITIES = ("error", "warn")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violated invariant.

    ``checker``   the checker id that produced it (ANALYSIS.md catalogue)
    ``path``      root-relative file the violation lives in (a doc or a
                  module)
    ``line``      1-based line (0 = whole-file / cross-file finding)
    ``ident``     stable suppression identity, e.g.
                  ``serve_workers:doc`` or ``metric:tpuprof_x:undocumented``
    ``message``   the human sentence: what drifted and what the fix is
    """

    checker: str
    path: str
    line: int
    ident: str
    message: str
    severity: str = "error"

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.checker}] {self.message}"


@dataclasses.dataclass
class LintReport:
    """Everything one lint run produced, suppressions applied.

    ``findings`` is every finding in checker order; ``suppressed``
    maps a finding's ident to the suppression reason that absorbed it.
    """

    root: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    suppressed: Dict[Finding, str] = dataclasses.field(default_factory=dict)
    checkers_run: List[str] = dataclasses.field(default_factory=list)
    wall_s: float = 0.0

    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if f not in self.suppressed]

    def counts_by_checker(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.unsuppressed():
            out[f.checker] = out.get(f.checker, 0) + 1
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": LINT_SCHEMA,
            "root": self.root,
            "checkers": list(self.checkers_run),
            "wall_s": round(self.wall_s, 4),
            "findings": [
                {
                    "checker": f.checker,
                    "file": f.path,
                    "line": f.line,
                    "ident": f.ident,
                    "severity": f.severity,
                    "message": f.message,
                    "suppressed": f in self.suppressed,
                    **({"reason": self.suppressed[f]}
                       if f in self.suppressed else {}),
                }
                for f in self.findings
            ],
            "counts_by_checker": self.counts_by_checker(),
            "suppressed_count": len(self.suppressed),
            "clean": not self.unsuppressed(),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=1, sort_keys=False)
