"""Checker modules — importing this package registers every checker
(the ``@checker`` decorators run at import).  Import order IS report
order (the registry is insertion-ordered); keep it the ANALYSIS.md
catalogue order."""

from tpuprof.analysis.checkers import durability      # noqa: F401
from tpuprof.analysis.checkers import config_surface  # noqa: F401
from tpuprof.analysis.checkers import obs_contract    # noqa: F401
from tpuprof.analysis.checkers import taxonomy        # noqa: F401
from tpuprof.analysis.checkers import discipline      # noqa: F401
