"""Checker ``error-taxonomy`` — errors.py ⇄ exit codes ⇄ ROBUSTNESS.md.

The typed-error contract (ROBUSTNESS.md "degradation ladder"): every
exception class defined in ``tpuprof/errors.py`` has a row in the
ROBUSTNESS.md taxonomy table, the row's documented exit code equals
what ``errors.exit_code`` would compute (via the ``_EXIT_CODES``
ordered mapping, inheritance included — subclasses like
``CorruptResultError`` legitimately share their parent's code), every
``_EXIT_CODES`` entry names a live class listed in ``TYPED_ERRORS``,
distinct ``_EXIT_CODES`` entries never collide on a code, and the doc
table names no dead classes.  This checker REPLACED the hand-rolled
parsing in ``TestTaxonomyDocSync`` (ISSUE 12 satellite) — the test now
asserts through here, one parser for one contract.

Everything is read from the AST, not by importing ``errors`` — so the
checker renders the same verdict on a synthetic (deliberately broken)
tree as on the real one.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tpuprof.analysis.context import AnalysisContext
from tpuprof.analysis.model import Finding
from tpuprof.analysis.registry import checker

_ROB = "ROBUSTNESS.md"
# the taxonomy table's shape: | `Class` | `Base` | meaning | code |
_ROW_RE = re.compile(
    r"^\|\s*`([A-Za-z_][A-Za-z0-9_]*)`\s*\|.*\|\s*([^|]+?)\s*\|$")


def _classes(tree: ast.Module) -> Dict[str, Tuple[List[str], int]]:
    """class name -> (base names, line) for every top-level class."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
            out[node.name] = (bases, node.lineno)
    return out


def _exit_pairs(tree: ast.Module) -> List[Tuple[str, int, int]]:
    """(class name, code, line) in declaration order from the
    ``_EXIT_CODES`` tuple-of-pairs assignment."""
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "_EXIT_CODES"
                        for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            pairs = []
            for elt in node.value.elts:
                if isinstance(elt, (ast.Tuple, ast.List)) \
                        and len(elt.elts) == 2 \
                        and isinstance(elt.elts[0], ast.Name) \
                        and isinstance(elt.elts[1], ast.Constant):
                    pairs.append((elt.elts[0].id,
                                  int(elt.elts[1].value), elt.lineno))
            return pairs
    return []


def _typed_errors(tree: ast.Module) -> Set[str]:
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "TYPED_ERRORS"
                        for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return {e.id for e in node.value.elts
                    if isinstance(e, ast.Name)}
    return set()


def _ancestors(name: str, classes: Dict[str, Tuple[List[str], int]]
               ) -> Set[str]:
    seen: Set[str] = set()
    todo = [name]
    while todo:
        cur = todo.pop()
        for base in classes.get(cur, ([], 0))[0]:
            if base not in seen:
                seen.add(base)
                todo.append(base)
    return seen


def _computed_code(name: str, classes, pairs) -> int:
    """What ``errors.exit_code`` returns for an instance of ``name``:
    the FIRST _EXIT_CODES entry the class is-a (order matters — the
    mapping's own comment), 1 when nothing matches."""
    lineage = {name} | _ancestors(name, classes)
    for cls, code, _line in pairs:
        if cls in lineage:
            return code
    return 1


@checker(
    "error-taxonomy",
    "errors.py classes ⇄ _EXIT_CODES ⇄ ROBUSTNESS.md taxonomy table, "
    "bijective (subclass code-sharing allowed)")
def check_taxonomy(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    sf = ctx.file("/errors.py")
    if sf is None:
        return [Finding(
            checker="error-taxonomy", path="tpuprof/errors.py", line=0,
            ident="errors:missing",
            message="no errors.py module found — the taxonomy cannot "
                    "be checked")]
    classes = _classes(sf.tree)
    pairs = _exit_pairs(sf.tree)
    typed = _typed_errors(sf.tree)

    doc = ctx.doc_text(_ROB)
    doc_rows: Dict[str, Tuple[str, int]] = {}
    if doc is None:
        findings.append(Finding(
            checker="error-taxonomy", path=_ROB, line=0,
            ident="doc:missing",
            message="ROBUSTNESS.md not found — the taxonomy table "
                    "cannot be checked"))
    else:
        for i, line in enumerate(doc.splitlines(), 1):
            m = _ROW_RE.match(line.strip())
            if m and m.group(1) in classes:
                doc_rows[m.group(1)] = (m.group(2), i)
            elif m and m.group(1)[:1].isupper() \
                    and m.group(1) not in doc_rows:
                # CamelCase row with no matching class: dead doc row
                # (snake_case rows belong to the config table —
                # config-surface owns those)
                findings.append(Finding(
                    checker="error-taxonomy", path=_ROB, line=i,
                    ident=f"{m.group(1)}:doc-dead",
                    message=f"ROBUSTNESS.md taxonomy table documents "
                            f"'{m.group(1)}' but errors.py defines no "
                            "such class — stale row"))

    for name, (_bases, lineno) in classes.items():
        code = _computed_code(name, classes, pairs)
        if doc is not None and name not in doc_rows:
            findings.append(Finding(
                checker="error-taxonomy", path=sf.relpath, line=lineno,
                ident=f"{name}:undocumented",
                message=f"error class '{name}' has no ROBUSTNESS.md "
                        "taxonomy-table row — every typed failure "
                        "shape must be documented with its exit code"))
        elif doc is not None:
            documented, doc_line = doc_rows[name]
            digits = re.findall(r"\d+", documented)
            if digits:
                if int(digits[-1]) != code:
                    findings.append(Finding(
                        checker="error-taxonomy", path=_ROB,
                        line=doc_line, ident=f"{name}:code-mismatch",
                        message=f"ROBUSTNESS.md documents exit "
                                f"{digits[-1]} for '{name}' but "
                                f"errors.exit_code computes {code}"))
            elif code != 1:
                findings.append(Finding(
                    checker="error-taxonomy", path=_ROB, line=doc_line,
                    ident=f"{name}:code-mismatch",
                    message=f"ROBUSTNESS.md marks '{name}' as having "
                            f"no exit code but errors.exit_code "
                            f"computes {code}"))

    for cls, _code, lineno in pairs:
        if cls not in classes:
            findings.append(Finding(
                checker="error-taxonomy", path=sf.relpath, line=lineno,
                ident=f"{cls}:orphan-exit-code",
                message=f"_EXIT_CODES maps '{cls}' which errors.py "
                        "does not define — orphan exit-code entry"))
        elif typed and cls not in typed:
            findings.append(Finding(
                checker="error-taxonomy", path=sf.relpath, line=lineno,
                ident=f"{cls}:not-typed",
                message=f"_EXIT_CODES maps '{cls}' but TYPED_ERRORS "
                        "does not list it — the CLI would print a "
                        "traceback for an error with a documented "
                        "exit code"))
    seen_codes: Dict[int, str] = {}
    for cls, code, lineno in pairs:
        if code in seen_codes:
            findings.append(Finding(
                checker="error-taxonomy", path=sf.relpath, line=lineno,
                ident=f"{cls}:code-collision",
                message=f"_EXIT_CODES gives '{cls}' exit {code}, "
                        f"already claimed by '{seen_codes[code]}' — "
                        "codes must be distinct (subclasses share via "
                        "inheritance, not duplicate entries)"))
        else:
            seen_codes[code] = cls
    return findings
