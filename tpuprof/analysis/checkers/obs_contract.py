"""Checker ``obs-contract`` — metric and event names synced to docs.

Two registries, two docs, four drift directions:

* every metric registered on the process registry
  (``metrics.counter/gauge/histogram("tpuprof_...")`` at module
  import) must appear in OBSERVABILITY.md — an undocumented series is
  invisible to the operators the telemetry exists for;
* every ``tpuprof_*`` name OBSERVABILITY.md mentions must be a live
  registration — docs describing dead metrics send people grepping
  for series that never fire;
* every ``events.emit("<kind>", ...)`` call site must have an
  EVENT_SCHEMA entry (tests/test_obs_smoke.py — the runtime JSONL
  validator and this checker read the same dict, one contract);
* every EVENT_SCHEMA kind must have a live emit site — a dead schema
  entry validates events nobody produces.

Dynamic names (non-literal first args) are skipped: the contract is
about the declared names, and every registration/emit in the tree
today is a literal.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from tpuprof.analysis.context import (AnalysisContext, call_name,
                                      const_str)
from tpuprof.analysis.model import Finding
from tpuprof.analysis.registry import checker

_METRIC_METHODS = ("counter", "gauge", "histogram")
_METRIC_TOKEN = re.compile(r"\btpuprof_[a-z0-9_]+\b")
_OBS_DOC = "OBSERVABILITY.md"
_SCHEMA_PATH = "tests/test_obs_smoke.py"


def _registrations(ctx: AnalysisContext) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    for sf, node in ctx.iter_calls():
        if call_name(node).split(".")[-1] not in _METRIC_METHODS:
            continue
        name = const_str(node.args[0]) if node.args else None
        if name and name.startswith("tpuprof_"):
            out.setdefault(name, (sf.relpath, node.lineno))
    return out


def _emits(ctx: AnalysisContext) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    for sf, node in ctx.iter_calls():
        if call_name(node).split(".")[-1] != "emit":
            continue
        kind = const_str(node.args[0]) if node.args else None
        if kind:
            out.setdefault(kind, (sf.relpath, node.lineno))
    return out


@checker(
    "obs-contract",
    "registered metric names ⇄ OBSERVABILITY.md and emitted event "
    "kinds ⇄ EVENT_SCHEMA, both directions")
def check_obs_contract(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []

    registered = _registrations(ctx)
    doc = ctx.doc_text(_OBS_DOC)
    if doc is None:
        findings.append(Finding(
            checker="obs-contract", path=_OBS_DOC, line=0,
            ident="doc:missing",
            message="OBSERVABILITY.md not found — the metric catalogue "
                    "cannot be checked"))
        documented = set()
    else:
        documented = set(_METRIC_TOKEN.findall(doc))

    for name, (path, line) in sorted(registered.items()):
        if doc is not None and name not in documented:
            findings.append(Finding(
                checker="obs-contract", path=path, line=line,
                ident=f"metric:{name}:undocumented",
                message=f"metric '{name}' is registered here but "
                        "OBSERVABILITY.md never names it — add a "
                        "catalogue row"))
    for name in sorted(documented - set(registered)):
        findings.append(Finding(
            checker="obs-contract", path=_OBS_DOC,
            line=ctx.doc_line(_OBS_DOC, name),
            ident=f"metric:{name}:dead-doc",
            message=f"OBSERVABILITY.md names '{name}' but no "
                    "registration exists in the package — stale doc "
                    "(or the registration lost its literal name)"))

    emitted = _emits(ctx)
    schema = ctx.event_schema_keys()
    if schema is None:
        findings.append(Finding(
            checker="obs-contract", path=_SCHEMA_PATH, line=0,
            ident="event-schema:missing",
            message="EVENT_SCHEMA dict not found in "
                    "tests/test_obs_smoke.py — the JSONL event "
                    "contract cannot be checked"))
        return findings

    for kind, (path, line) in sorted(emitted.items()):
        if kind not in schema:
            findings.append(Finding(
                checker="obs-contract", path=path, line=line,
                ident=f"event:{kind}:unregistered",
                message=f"events.emit({kind!r}) has no EVENT_SCHEMA "
                        "entry — the JSONL validator would reject a "
                        "sink that recorded it; add the schema row"))
    for kind, line in sorted(schema.items()):
        # "metric" records are synthesized inside emit_snapshot (one
        # per live series) rather than through emit(kind, ...) — the
        # schema key is load-bearing for the validator even with no
        # emit literal
        if kind not in emitted and kind != "metric":
            findings.append(Finding(
                checker="obs-contract", path=_SCHEMA_PATH, line=line,
                ident=f"event:{kind}:dead-schema",
                message=f"EVENT_SCHEMA declares kind '{kind}' but no "
                        "events.emit site produces it — dead contract "
                        "entry"))
    return findings
