"""Checker ``durability`` — atomic publication of every durable file.

Scope: the modules that write into directories other processes scan or
re-read across crashes (spool, fleet dir, artifact chains, checkpoint
paths) — :data:`DURABLE_MODULES`.  Three rules, each a shipped-race
postmortem turned invariant:

* **bare-write** — a function that opens a file for writing must be an
  atomic-write seam: the same function fsyncs the handle AND publishes
  via ``os.replace``/``os.link`` (rename-after-fsync).  A bare
  ``open(path, "w")`` (or ``Path.write_bytes``/``write_text``) into a
  durable directory can be observed torn by a concurrent reader or
  survive a crash half-written.
* **tmp-name** — the seam's temp file must be DOT-PREFIXED in its
  basename.  Suffix-style ``path + ".tmp"`` names share the real
  file's prefix, so every ``startswith("part.")``-style scan matches
  the in-flight write — the exact PR-7 race
  (``part.<phase>.<host>.<seq>.tmp.<pid>`` read torn by a concurrent
  finish barrier).
* **scan-unfiltered** — a directory scan (``os.listdir``/``scandir``)
  over a durable directory must filter names: a prefix/suffix/regex
  test (which a dot-prefixed tmp can never pass) or an explicit
  dot/``.tmp.`` exclusion.  An unfiltered iteration reads whatever is
  mid-flight.

Emptiness probes (``if not os.listdir(d)``) are exempt — they touch no
names.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tpuprof.analysis.context import (AnalysisContext, SourceFile,
                                      call_name, const_str, literal_head)
from tpuprof.analysis.model import Finding
from tpuprof.analysis.registry import checker

#: root-relative suffixes of the modules under the durability contract
#: (ANALYSIS.md lists them; extend when a new module starts publishing
#: durable files)
DURABLE_MODULES = (
    "runtime/checkpoint.py",
    "runtime/fleet.py",
    "runtime/aot.py",
    "artifact/store.py",
    "serve/server.py",
    "serve/scheduler.py",
    "serve/watch.py",
    "serve/http.py",
    "obs/fleet.py",
    "warehouse/columnar.py",
    "warehouse/store.py",
)

_WRITE_CHARS = set("wax+")
_FILTER_ATTRS = ("startswith", "endswith", "match", "fullmatch")


def _walk_shallow(fn: ast.AST):
    """Walk a function's OWN body, not descending into nested defs —
    a closure's writes are the closure's findings, once."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        node = todo.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            todo.extend(ast.iter_child_nodes(node))


def _embedded_literals(node: ast.AST) -> List[str]:
    """Every constant string inside a name-building expression."""
    out = []
    for n in ast.walk(node):
        v = const_str(n)
        if v is not None:
            out.append(v)
    return out


def _is_write_open(node: ast.Call) -> bool:
    if not call_name(node).endswith("open"):
        return False
    mode: Optional[ast.AST] = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    m = const_str(mode)
    return bool(m) and bool(set(m) & _WRITE_CHARS)


def _resolve_in_function(fn: ast.AST, expr: ast.AST) -> ast.AST:
    """If ``expr`` is a Name assigned once in ``fn``, the assigned
    value; else ``expr`` itself."""
    if not isinstance(expr, ast.Name):
        return expr
    assigned = [n.value for n in ast.walk(fn)
                if isinstance(n, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == expr.id
                        for t in n.targets)]
    return assigned[0] if len(assigned) == 1 else expr


def _listdir_is_probe(sf: SourceFile, node: ast.Call) -> bool:
    """True when the scan result is only truth-tested (emptiness),
    never iterated: ``if not os.listdir(d)`` / ``len(os.listdir(d))``."""
    parent = sf.parent(node)
    if isinstance(parent, ast.UnaryOp) and isinstance(parent.op, ast.Not):
        return True
    if isinstance(parent, ast.Call) and call_name(parent) == "len":
        return True
    if isinstance(parent, (ast.If, ast.While, ast.BoolOp, ast.Compare)):
        return True
    return False


@checker(
    "durability",
    "durable writes go tmp→fsync→rename through dot-prefixed temp "
    "names, and durable-directory scans filter in-flight files out")
def check_durability(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.files:
        norm = sf.relpath.replace("\\", "/")
        if not any(norm.endswith(m) for m in DURABLE_MODULES):
            continue
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            write_opens = []
            has_fsync = has_publish = False
            for node in _walk_shallow(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if _is_write_open(node):
                    write_opens.append(node)
                elif name.endswith(".fsync"):
                    has_fsync = True
                elif name.endswith((".replace", ".link", ".rename")):
                    has_publish = True
                elif name.endswith((".write_bytes", ".write_text")):
                    findings.append(Finding(
                        checker="durability", path=sf.relpath,
                        line=node.lineno,
                        ident=f"{norm}:{fn.name}:path-write",
                        message=f"{fn.name}() publishes via "
                                "Path.write_bytes/write_text — durable "
                                "files must go through an atomic "
                                "tmp+fsync+rename seam"))
            if not write_opens:
                continue
            if not (has_fsync and has_publish):
                missing = []
                if not has_fsync:
                    missing.append("os.fsync before publication")
                if not has_publish:
                    missing.append("os.replace/os.link publication")
                for node in write_opens:
                    findings.append(Finding(
                        checker="durability", path=sf.relpath,
                        line=node.lineno,
                        ident=f"{norm}:{fn.name}:bare-write",
                        message=f"{fn.name}() opens a file for writing "
                                "in a durable module but is not an "
                                "atomic-write seam — missing "
                                + " and ".join(missing)))
                continue
            # the function IS a seam: its temp name must be dot-prefixed
            for node in write_opens:
                target = _resolve_in_function(fn, node.args[0]) \
                    if node.args else None
                if target is None:
                    continue
                head = literal_head(target)
                if head is None:
                    # the name STARTS with runtime data.  If a later
                    # literal chunk says "tmp", this is suffix-style
                    # naming (`path + ".tmp"`, `f"{path}.tmp.{pid}"`)
                    # — the temp shares the real file's prefix, the
                    # exact shape of the PR-7 race — flag it.  A bare
                    # parameter with no tmp evidence is unprovable
                    # here; its construction site is in scope instead.
                    if any("tmp" in s for s in _embedded_literals(target)):
                        findings.append(Finding(
                            checker="durability", path=sf.relpath,
                            line=node.lineno,
                            ident=f"{norm}:{fn.name}:tmp-name",
                            message=f"{fn.name}() builds its temp name "
                                    "as a SUFFIX of the real path — "
                                    "the temp shares the published "
                                    "file's prefix, so prefix scans "
                                    "match the in-flight write; use a "
                                    "dot-prefixed basename "
                                    "(.<name>.tmp.<pid>) instead"))
                    continue
                if not head.startswith("."):
                    findings.append(Finding(
                        checker="durability", path=sf.relpath,
                        line=node.lineno,
                        ident=f"{norm}:{fn.name}:tmp-name",
                        message=f"{fn.name}() writes its temp file "
                                f"under a name starting {head!r} — tmp "
                                "basenames must be dot-prefixed so no "
                                "prefix scan can ever match an "
                                "in-flight write (the PR-7 "
                                "'part.*.tmp.<pid>' race)"))
        # directory scans
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scans = []
            has_filter = False
            for node in _walk_shallow(fn):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if name.endswith((".listdir", ".scandir")):
                        scans.append(node)
                    elif name.split(".")[-1] in _FILTER_ATTRS:
                        has_filter = True
                elif isinstance(node, ast.Compare) \
                        and any(isinstance(op, (ast.In, ast.NotIn))
                                for op in node.ops):
                    # explicit '".tmp." in name' style exclusion
                    if const_str(node.left) is not None or any(
                            const_str(c) is not None
                            for c in node.comparators):
                        has_filter = True
            for node in scans:
                if _listdir_is_probe(sf, node):
                    continue
                if not has_filter:
                    findings.append(Finding(
                        checker="durability", path=sf.relpath,
                        line=node.lineno,
                        ident=f"{norm}:{fn.name}:scan-unfiltered",
                        message=f"{fn.name}() iterates a durable "
                                "directory listing with no name filter "
                                "— in-flight (dot-prefixed) temp files "
                                "would be read; add a prefix/suffix/"
                                "regex test or an explicit dot "
                                "exclusion"))
    return findings
