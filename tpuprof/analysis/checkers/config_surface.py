"""Checker ``config-surface`` — config ⇄ env ⇄ CLI ⇄ doc completeness.

Every runtime knob must be reachable four ways (the ROBUSTNESS.md
config-table contract grown from PR 1's ``resolve_*`` convention): the
``ProfilerConfig`` field, a ``resolve_*`` resolver, a ``TPUPROF_*``
env twin, a CLI flag, and a documentation row.  A knob missing a leg
is un-deployable somewhere: no env twin means wrappers cannot tune it,
no CLI flag means operators cannot, no doc row means nobody knows it
exists.

Scope rule (ANALYSIS.md): a field enters the contract when ANY leg
beyond the dataclass field exists — a matching ``TPUPROF_<FIELD>`` env
literal anywhere in the package, a name-matching ``resolve_*``
function, or a config-table row.  Once in scope, ALL legs are
required.  Pure constructor parity knobs (``bins``, ``corr_reject``
...) that never grew an env/resolver/doc surface stay out of scope —
they are the reference facade, not runtime knobs.

Leg matching is by name (``field`` ⇄ ``TPUPROF_FIELD`` ⇄ ``--field``,
each modulo a trailing ``_s`` unit suffix) plus the declared alias
tables below for historical flag names (``--every``, ``--keep``,
``--http``, ``--metrics-json``); a resolver also links when it reads —
or is called with — the field's env var.  Docs count from any of
README.md / ROBUSTNESS.md / OBSERVABILITY.md: a config-table row
naming the field, or the env var appearing in prose.

The reverse direction is drift too: a ROBUSTNESS config-table row
naming a field that no longer exists on ``ProfilerConfig`` reports as
``doc-dead``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from tpuprof.analysis.context import (AnalysisContext, call_name,
                                      const_str)
from tpuprof.analysis.model import Finding
from tpuprof.analysis.registry import checker

#: fields whose CLI flag predates the field-name convention — the flag
#: is the public contract, the alias records it (ANALYSIS.md)
CLI_ALIASES: Dict[str, str] = {
    "watch_every_s": "--every",
    "artifact_keep": "--keep",
    "serve_http_port": "--http",
    "metrics_path": "--metrics-json",
    "metrics_enabled": "--progress",
    "checkpoint_path": "--checkpoint",
    "checkpoint_every_batches": "--checkpoint-every",
    "unique_track_total_rows": "--unique-track-total-rows",
    "artifact_path": "--artifact",
}

#: env twins that are not the mechanical TPUPROF_<FIELD> name
ENV_ALIASES: Dict[str, str] = {
    "metrics_enabled": "TPUPROF_METRICS",
}

_DOCS = ("README.md", "ROBUSTNESS.md", "OBSERVABILITY.md")


def _strip_unit(name: str) -> str:
    return name[:-2] if name.endswith("_s") else name


def _config_fields(ctx: AnalysisContext) -> Dict[str, int]:
    sf = ctx.file("/config.py")
    if sf is None:
        return {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) \
                and node.name == "ProfilerConfig":
            out = {}
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name) \
                        and not stmt.target.id.startswith("_"):
                    out[stmt.target.id] = stmt.lineno
            return out
    return {}


def _resolvers(ctx: AnalysisContext) -> Dict[str, Set[str]]:
    """resolver name -> env-var literals its body reads.  Resolvers
    live in config.py by convention, but a few legitimately sit next
    to their consumer (``obs.resolve_metrics_path``) — scan every
    package module."""
    out: Dict[str, Set[str]] = {}
    for sf in ctx.files:
        for node in sf.tree.body:
            if isinstance(node, ast.FunctionDef) \
                    and node.name.startswith("resolve_"):
                envs = {c for n in ast.walk(node)
                        if (c := const_str(n))
                        and c.startswith("TPUPROF_")}
                out.setdefault(node.name, set()).update(envs)
    return out


def _resolve_call_envs(ctx: AnalysisContext) -> Set[str]:
    """Env literals handed to any ``resolve_*`` call anywhere in the
    package — the generic-resolver link (``resolve_watchdog_timeout
    (value, "TPUPROF_DRAIN_TIMEOUT_S")``)."""
    out: Set[str] = set()
    for _sf, node in ctx.iter_calls():
        if call_name(node).split(".")[-1].startswith("resolve_"):
            for arg in list(node.args) + [k.value for k in node.keywords]:
                v = const_str(arg)
                if v and v.startswith("TPUPROF_"):
                    out.add(v)
    return out


def _cli_flags(ctx: AnalysisContext) -> Set[str]:
    sf = ctx.file("/cli.py")
    if sf is None:
        return set()
    flags: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) \
                and call_name(node).endswith("add_argument"):
            for arg in node.args:
                v = const_str(arg)
                if v and v.startswith("--"):
                    flags.add(v)
    return flags


_ROW_RE = re.compile(r"^\|\s*`([A-Za-z_][A-Za-z0-9_]*)`\s*\|")


def _table_fields(ctx: AnalysisContext, fields: Dict[str, int]
                  ) -> Dict[str, List[str]]:
    """doc name -> field names its config-table rows claim.  A table
    row is any markdown row whose first cell is a backticked
    snake_case name; rows naming error classes (the taxonomy table)
    are filtered by the caller against the field set."""
    out: Dict[str, List[str]] = {}
    for doc in _DOCS:
        text = ctx.doc_text(doc)
        if not text:
            continue
        rows = []
        for line in text.splitlines():
            m = _ROW_RE.match(line.strip())
            if m:
                rows.append(m.group(1))
        out[doc] = rows
    return out


@checker(
    "config-surface",
    "every runtime config knob has its resolve_*, TPUPROF_* env twin, "
    "CLI flag, and doc-table row; doc rows name only live fields")
def check_config_surface(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    fields = _config_fields(ctx)
    if not fields:
        return [Finding(
            checker="config-surface", path="tpuprof/config.py", line=0,
            ident="config:missing",
            message="no ProfilerConfig dataclass found — the config "
                    "surface cannot be checked")]
    resolvers = _resolvers(ctx)
    call_envs = _resolve_call_envs(ctx)
    flags = _cli_flags(ctx)
    pkg_literals = {v for _sf, v in ctx.string_literals()
                    if v.startswith("TPUPROF_")}
    tables = _table_fields(ctx, fields)
    config_sf = ctx.file("/config.py")
    config_rel = config_sf.relpath if config_sf else "tpuprof/config.py"

    for field, lineno in fields.items():
        env = ENV_ALIASES.get(field, "TPUPROF_" + field.upper())
        has_env = env in pkg_literals
        resolver = None
        for rname, renvs in resolvers.items():
            stem = rname[len("resolve_"):]
            if stem in (field, _strip_unit(field)) or env in renvs:
                resolver = rname
                break
        has_resolver = resolver is not None or env in call_envs
        doc_rows = [doc for doc, rows in tables.items()
                    if field in rows]
        doc_prose = [doc for doc in _DOCS
                     if env in (ctx.doc_text(doc) or "")]
        has_doc = bool(doc_rows or doc_prose)

        in_scope = has_env or resolver is not None or bool(doc_rows)
        if not in_scope:
            continue

        flag = CLI_ALIASES.get(field)
        candidates = [flag] if flag else [
            "--" + field.replace("_", "-"),
            "--" + _strip_unit(field).replace("_", "-")]
        has_cli = any(c in flags for c in candidates)

        if not has_env:
            findings.append(Finding(
                checker="config-surface", path=config_rel, line=lineno,
                ident=f"{field}:env",
                message=f"config field '{field}' has no {env} env twin "
                        "anywhere in the package — wrappers/CI cannot "
                        "set it without code"))
        if not has_resolver:
            findings.append(Finding(
                checker="config-surface", path=config_rel, line=lineno,
                ident=f"{field}:resolver",
                message=f"config field '{field}' has no resolve_* "
                        "resolver (none name-matches and none reads "
                        f"{env}) — the explicit-wins/env/default "
                        "resolution order is unimplemented"))
        if not has_cli:
            findings.append(Finding(
                checker="config-surface", path=config_rel, line=lineno,
                ident=f"{field}:cli",
                message=f"config field '{field}' has no CLI flag "
                        f"(looked for {', '.join(candidates)}; declare "
                        "an alias in CLI_ALIASES if the flag predates "
                        "the naming convention)"))
        if not has_doc:
            findings.append(Finding(
                checker="config-surface", path=config_rel, line=lineno,
                ident=f"{field}:doc",
                message=f"config field '{field}' has no doc leg — add "
                        "a ROBUSTNESS.md/README config-table row or "
                        f"document {env} in README/OBSERVABILITY"))

    # reverse: ROBUSTNESS config-table rows naming dead fields (the
    # taxonomy table's rows are CamelCase error classes — the
    # snake_case row regex already excludes them; anything else
    # snake_case in a ROBUSTNESS table must be a live field)
    for row in tables.get("ROBUSTNESS.md", []):
        if row not in fields and row == row.lower():
            findings.append(Finding(
                checker="config-surface", path="ROBUSTNESS.md",
                line=ctx.doc_line("ROBUSTNESS.md", f"`{row}`"),
                ident=f"doc-dead:{row}",
                message=f"ROBUSTNESS.md config table names '{row}' "
                        "but ProfilerConfig has no such field — stale "
                        "row"))
    return findings
