"""Checker ``runtime-discipline`` — locked seams stay locked.

Two disciplines, both PR-9/PR-4 postmortems turned invariants:

* **runner seam** — ``MeshRunner`` construction is a cache lookup
  (``serve/cache.acquire_runner``), never a direct call: a bypass
  rebuilds compiled programs (the 20-40 s cold start the cache
  amortizes) and — worse — escapes the process-wide dispatch lock's
  assumptions about who owns the mesh.  Direct construction is legal
  only inside the cache itself and inside ``runtime/mesh.py``.
* **fault sites** — every site-string literal handed to
  ``faults.hit``/``faults.mangle`` or passed as a ``site=`` keyword
  must be declared in :data:`tpuprof.testing.faults.SITES`, and every
  declared site must still have a live use.  An undeclared site is
  invisible to the ``TPUPROF_FAULTS`` grammar's users (nothing
  documents it can be injected); a dead declaration documents an
  injection point that no longer exists.

Dynamic site expressions (``faults.hit(site, ...)`` inside the guard,
where the caller supplies the literal) are skipped — the caller's
literal is collected at ITS call site instead.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tpuprof.analysis.context import (AnalysisContext, call_name,
                                      const_str)
from tpuprof.analysis.model import Finding
from tpuprof.analysis.registry import checker

#: modules allowed to construct MeshRunner directly: the cache (the
#: one blessed seam) and the definition module itself
RUNNER_SEAM_MODULES = ("serve/cache.py", "runtime/mesh.py")

_FAULTS_MODULE = "testing/faults.py"


def _declared_sites(ctx: AnalysisContext
                    ) -> Tuple[Optional[Set[str]], str, int]:
    """(SITES members, faults.py relpath, assignment line) — None set
    when the registry is missing."""
    sf = ctx.file("/" + _FAULTS_MODULE)
    if sf is None:
        return None, "tpuprof/" + _FAULTS_MODULE, 0
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets):
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]   # frozenset({...})
            if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
                return ({v for e in value.elts
                         if (v := const_str(e)) is not None},
                        sf.relpath, node.lineno)
    return None, sf.relpath, 0


def _used_sites(ctx: AnalysisContext) -> Dict[str, Tuple[str, int]]:
    """site literal -> first (file, line) using it: faults.hit/mangle
    first args plus any ``site="..."`` keyword anywhere in the
    package (guards, watchdogs, deadline constructors)."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf, node in ctx.iter_calls():
        if sf.relpath.replace("\\", "/").endswith(_FAULTS_MODULE):
            continue            # the registry module itself
        tail = call_name(node).split(".")[-1]
        if tail in ("hit", "mangle") and node.args:
            v = const_str(node.args[0])
            if v is not None:
                out.setdefault(v, (sf.relpath, node.lineno))
        for kw in node.keywords:
            if kw.arg == "site":
                v = const_str(kw.value)
                if v is not None:
                    out.setdefault(v, (sf.relpath, node.lineno))
    return out


@checker(
    "runtime-discipline",
    "MeshRunner construction only through serve/cache; every faults "
    "site literal declared in the central SITES registry, no dead "
    "declarations")
def check_discipline(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []

    for sf, node in ctx.iter_calls():
        name = call_name(node)
        if name == "MeshRunner" or name.endswith(".MeshRunner"):
            norm = sf.relpath.replace("\\", "/")
            if not any(norm.endswith(m) for m in RUNNER_SEAM_MODULES):
                findings.append(Finding(
                    checker="runtime-discipline", path=sf.relpath,
                    line=node.lineno, ident=f"mesh-runner:{norm}",
                    message="direct MeshRunner construction bypasses "
                            "the serve/cache.acquire_runner seam — "
                            "every profile path must draw runners "
                            "from the keyed compiled-program cache "
                            "(PR 9)"))

    declared, faults_path, faults_line = _declared_sites(ctx)
    used = _used_sites(ctx)
    if declared is None:
        findings.append(Finding(
            checker="runtime-discipline", path=faults_path, line=0,
            ident="sites:missing-registry",
            message="tpuprof/testing/faults.py declares no SITES "
                    "registry — fault-site literals have no central "
                    "source of truth"))
        return findings
    for site, (path, line) in sorted(used.items()):
        if site not in declared:
            findings.append(Finding(
                checker="runtime-discipline", path=path, line=line,
                ident=f"site:{site}:undeclared",
                message=f"fault/guard site {site!r} is not declared "
                        "in faults.SITES — add it to the central "
                        "registry (and the faults.py site table) so "
                        "TPUPROF_FAULTS users can discover it"))
    for site in sorted(declared - set(used)):
        findings.append(Finding(
            checker="runtime-discipline", path=faults_path,
            line=faults_line, ident=f"site:{site}:dead",
            message=f"faults.SITES declares {site!r} but no call site "
                    "uses it — dead registry entry"))
    return findings
