"""The committed suppression file (ANALYSIS.md "Suppressions").

One entry per line::

    <checker-id>  <ident-glob>  <reason — mandatory prose>

``#`` comments and blank lines are ignored.  The ident-glob is an
fnmatch pattern against :attr:`Finding.ident` (NEVER file:line — line
numbers churn; idents are stable names like ``serve_workers:doc``).
``*`` as the checker id matches any checker.

Two rules keep the file honest:

* **no silent allowlisting** — an entry with no reason text is itself
  a finding (checker id ``suppressions``), so nothing gets waved
  through without a recorded why;
* **no rot** — an entry that matched nothing this run is a STALE
  finding: the violation it excused is gone, delete the line (or the
  glob quietly widened past its purpose).
"""

from __future__ import annotations

import fnmatch
import os
from typing import Dict, List, Optional, Tuple

from tpuprof.analysis.model import Finding

#: root-relative default location of the committed suppression file
DEFAULT_FILE = "LINT_SUPPRESSIONS"


class Suppression:
    def __init__(self, checker: str, pattern: str, reason: str,
                 line: int):
        self.checker = checker
        self.pattern = pattern
        self.reason = reason
        self.line = line
        self.hits = 0

    def matches(self, finding: Finding) -> bool:
        if self.checker not in ("*", finding.checker):
            return False
        return fnmatch.fnmatchcase(finding.ident, self.pattern)


def load(root: str, path: Optional[str] = None
         ) -> Tuple[List[Suppression], List[Finding]]:
    """(entries, file-format findings).  A missing file is an empty —
    perfectly clean — suppression set, not an error."""
    relpath = path or DEFAULT_FILE
    abspath = relpath if os.path.isabs(relpath) \
        else os.path.join(root, relpath)
    try:
        with open(abspath, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return [], []
    entries: List[Suppression] = []
    bad: List[Finding] = []
    shown = os.path.relpath(abspath, root) \
        if abspath.startswith(os.path.abspath(root) + os.sep) else relpath
    for i, raw in enumerate(lines, 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split(None, 2)
        if len(parts) < 3 or not parts[2].strip():
            bad.append(Finding(
                checker="suppressions", path=shown, line=i,
                ident=f"malformed:{i}",
                message="suppression entries are '<checker> "
                        "<ident-glob> <reason>' — the reason prose is "
                        "mandatory (no silent allowlisting): "
                        f"{line!r}"))
            continue
        entries.append(Suppression(parts[0], parts[1], parts[2].strip(),
                                   i))
    return entries, bad


def apply(findings: List[Finding], entries: List[Suppression],
          suppression_path: str) -> Tuple[Dict[Finding, str],
                                          List[Finding]]:
    """(suppressed finding -> reason, stale-entry findings)."""
    suppressed: Dict[Finding, str] = {}
    for f in findings:
        for s in entries:
            if s.matches(f):
                s.hits += 1
                suppressed[f] = s.reason
                break
    stale = [
        Finding(
            checker="suppressions", path=suppression_path, line=s.line,
            ident=f"stale:{s.checker}:{s.pattern}",
            message=f"suppression '{s.checker} {s.pattern}' matched no "
                    "finding this run — the violation it excused is "
                    "gone; delete the entry")
        for s in entries if s.hits == 0
    ]
    return suppressed, stale
