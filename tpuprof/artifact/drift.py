"""Per-column drift detection over two stats artifacts (``tpuprof diff``).

Every metric here is computed from what the artifacts already store —
no source data is re-read:

* **PSI / KS** from the persisted histograms.  Each histogram becomes a
  piecewise-linear empirical CDF over its own edges; KS is the max
  |CDF_A − CDF_B| over the union of both edge sets (the difference of
  two piecewise-linear functions attains its max at a breakpoint), and
  PSI re-bins both CDFs onto a common equal-width grid spanning the
  union range (the standard 10-bucket formulation, probabilities
  floored at ε so empty buckets stay finite).
* **Distinct-count churn** from the exported distinct counts (HLL /
  exact-tier — whatever the profile used; ``distinct_approx`` rides
  along so a consumer can weigh the estimate).
* **Top-k churn** from the ranked top-k sketch rows (Misra-Gries
  survivors): Jaccard distance of the two value sets, plus which values
  entered/exited.
* **Schema changes**: added / dropped columns and refined-kind changes
  (NUM→CAT is drift even when every number still parses).
* **Moment/missing shift**: |Δmean|/σ_A and Δp_missing as cheap
  always-available signals (they catch drift in columns whose
  histograms are degenerate).

Severity: each column gets ``ok``/``warn``/``drift`` by comparing its
metrics against :class:`DriftThresholds` (PSI 0.1/0.25 is the classic
banding); schema changes are always ``drift``.  The output dict is the
machine-readable ``tpuprof-drift-v1`` contract; the HTML twin renders
it through the report template environment (artifact/render.py).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Dict, List, Optional, Tuple

from tpuprof.artifact.store import Artifact
from tpuprof.obs import metrics as _obs_metrics

DRIFT_SCHEMA_ID = "tpuprof-drift-v1"

PSI_BUCKETS = 10
_EPS = 1e-6

_REPORTS = _obs_metrics.counter(
    "tpuprof_drift_reports_total", "drift reports computed")
_SECONDS = _obs_metrics.histogram(
    "tpuprof_drift_seconds", "wall seconds per drift computation")
_FLAGGED = _obs_metrics.gauge(
    "tpuprof_drift_columns_flagged",
    "columns at drift severity in the newest report")


@dataclasses.dataclass(frozen=True)
class DriftThresholds:
    """warn/drift bands per metric; ``from_cli`` scales the warn band
    to half the configured drift threshold so one flag moves both."""

    psi_warn: float = 0.1
    psi_drift: float = 0.25
    ks_warn: float = 0.1
    ks_drift: float = 0.2
    missing_warn: float = 0.02
    missing_drift: float = 0.10
    mean_shift_warn: float = 0.5
    mean_shift_drift: float = 2.0
    distinct_ratio_warn: float = 1.5
    distinct_ratio_drift: float = 3.0
    topk_churn_warn: float = 0.34
    topk_churn_drift: float = 0.67

    @classmethod
    def from_cli(cls, psi: Optional[float] = None,
                 ks: Optional[float] = None) -> "DriftThresholds":
        kw = {}
        if psi is not None:
            kw.update(psi_drift=psi, psi_warn=psi / 2.0)
        if ks is not None:
            kw.update(ks_drift=ks, ks_warn=ks / 2.0)
        return cls(**kw)

    def as_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# histogram -> CDF machinery
# ---------------------------------------------------------------------------

def _hist_cdf(hist: Dict[str, Any]):
    """(counts, edges) -> a callable empirical CDF, or None for an
    empty/degenerate histogram.  Point-mass histograms (every edge
    equal — constant columns) step from 0 to 1 at the value."""
    counts = [float(c) for c in hist.get("counts") or []]
    edges = [float(e) for e in hist.get("edges") or []]
    total = sum(counts)
    if total <= 0 or len(edges) != len(counts) + 1:
        return None
    if edges[-1] <= edges[0]:
        point = edges[0]

        def cdf_point(x: float) -> float:
            return 1.0 if x >= point else 0.0
        cdf_point.edges = [point]            # type: ignore[attr-defined]
        return cdf_point
    cum = [0.0]
    for c in counts:
        cum.append(cum[-1] + c)

    def cdf(x: float) -> float:
        if x <= edges[0]:
            return 0.0
        if x >= edges[-1]:
            return 1.0
        # bins are few (config.bins, default 10): linear scan is fine
        for i in range(len(counts)):
            if x < edges[i + 1]:
                lo, hi = edges[i], edges[i + 1]
                frac = (x - lo) / (hi - lo) if hi > lo else 1.0
                return (cum[i] + counts[i] * frac) / total
        return 1.0
    cdf.edges = edges                        # type: ignore[attr-defined]
    return cdf


def ks_statistic(hist_a: Dict[str, Any], hist_b: Dict[str, Any]
                 ) -> Optional[float]:
    """Two-sample KS distance between the histogram-implied CDFs (None
    when either side has no mass)."""
    ca, cb = _hist_cdf(hist_a), _hist_cdf(hist_b)
    if ca is None or cb is None:
        return None
    points = sorted(set(ca.edges) | set(cb.edges))
    return max(abs(ca(x) - cb(x)) for x in points)


def psi_statistic(hist_a: Dict[str, Any], hist_b: Dict[str, Any],
                  buckets: int = PSI_BUCKETS) -> Optional[float]:
    """Population stability index over a common equal-width grid
    spanning both ranges (None when either side has no mass)."""
    ca, cb = _hist_cdf(hist_a), _hist_cdf(hist_b)
    if ca is None or cb is None:
        return None
    lo = min(ca.edges[0], cb.edges[0])
    hi = max(ca.edges[-1], cb.edges[-1])
    if hi <= lo:                              # both point masses
        same = ca.edges[0] == cb.edges[0]
        return 0.0 if same else None
    psi = 0.0
    for i in range(buckets):
        b0 = lo + (hi - lo) * i / buckets
        b1 = lo + (hi - lo) * (i + 1) / buckets
        # closed top bucket so the max lands in-grid
        pa = max(ca(b1) - ca(b0), 0.0) if i < buckets - 1 \
            else max(1.0 - ca(b0), 0.0)
        pb = max(cb(b1) - cb(b0), 0.0) if i < buckets - 1 \
            else max(1.0 - cb(b0), 0.0)
        pa, pb = max(pa, _EPS), max(pb, _EPS)
        psi += (pa - pb) * math.log(pa / pb)
    return psi


# ---------------------------------------------------------------------------
# per-column metrics
# ---------------------------------------------------------------------------

def _topk_sets(rows: Optional[List[Dict[str, Any]]]):
    if not rows:
        return None
    # values arrive json_scalar'd; repr-keying keeps 1 and "1" distinct
    return {repr(r.get("value")) for r in rows}


def _topk_churn(rows_a, rows_b) -> Tuple[Optional[float], List, List]:
    sa, sb = _topk_sets(rows_a), _topk_sets(rows_b)
    if sa is None or sb is None:
        return None, [], []
    union = sa | sb
    if not union:
        return None, [], []
    churn = 1.0 - len(sa & sb) / len(union)
    by_val_b = {repr(r.get("value")): r.get("value") for r in rows_b}
    by_val_a = {repr(r.get("value")): r.get("value") for r in rows_a}
    entered = [by_val_b[k] for k in sorted(sb - sa)][:5]
    exited = [by_val_a[k] for k in sorted(sa - sb)][:5]
    return churn, entered, exited


def _num(var: Dict[str, Any], key: str) -> Optional[float]:
    v = var.get(key)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def _severity(metrics: Dict[str, Optional[float]],
              th: DriftThresholds) -> str:
    def band(value, warn, drift):
        if value is None:
            return "ok"
        if value >= drift:
            return "drift"
        return "warn" if value >= warn else "ok"

    ratio = metrics.get("distinct_ratio")
    ratio_dev = max(ratio, 1.0 / ratio) if ratio else None
    missing = metrics.get("missing_delta")
    levels = [
        band(metrics.get("psi"), th.psi_warn, th.psi_drift),
        band(metrics.get("ks"), th.ks_warn, th.ks_drift),
        band(abs(missing) if missing is not None else None,
             th.missing_warn, th.missing_drift),
        band(metrics.get("mean_shift"),
             th.mean_shift_warn, th.mean_shift_drift),
        band(ratio_dev, th.distinct_ratio_warn, th.distinct_ratio_drift),
        band(metrics.get("topk_churn"),
             th.topk_churn_warn, th.topk_churn_drift),
    ]
    if "drift" in levels:
        return "drift"
    return "warn" if "warn" in levels else "ok"


def _column_drift(name: str, var_a: Dict[str, Any], var_b: Dict[str, Any],
                  sk_a: Dict[str, Any], sk_b: Dict[str, Any],
                  th: DriftThresholds) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": var_b.get("type"),
                           "type_base": var_a.get("type")}
    hist_a = (sk_a.get("histograms") or {}).get(name)
    hist_b = (sk_b.get("histograms") or {}).get(name)
    out["psi"] = psi_statistic(hist_a, hist_b) \
        if hist_a and hist_b else None
    out["ks"] = ks_statistic(hist_a, hist_b) \
        if hist_a and hist_b else None
    if out["psi"] is not None:
        out["psi"] = round(out["psi"], 6)
    if out["ks"] is not None:
        out["ks"] = round(out["ks"], 6)

    mean_a, mean_b = _num(var_a, "mean"), _num(var_b, "mean")
    std_a = _num(var_a, "std")
    out["mean_shift"] = round(abs(mean_b - mean_a) / std_a, 6) \
        if None not in (mean_a, mean_b, std_a) and std_a > 0 else None

    pm_a, pm_b = _num(var_a, "p_missing"), _num(var_b, "p_missing")
    out["missing_delta"] = round(pm_b - pm_a, 6) \
        if None not in (pm_a, pm_b) else None

    d_a, d_b = _num(var_a, "distinct_count"), _num(var_b, "distinct_count")
    out["distinct_base"] = int(d_a) if d_a is not None else None
    out["distinct_current"] = int(d_b) if d_b is not None else None
    out["distinct_ratio"] = round(d_b / d_a, 6) \
        if d_a and d_b is not None else None
    out["distinct_approx"] = bool(var_a.get("distinct_approx")
                                  or var_b.get("distinct_approx"))

    churn, entered, exited = _topk_churn(
        (sk_a.get("topk") or {}).get(name),
        (sk_b.get("topk") or {}).get(name))
    out["topk_churn"] = round(churn, 6) if churn is not None else None
    out["topk_entered"] = entered
    out["topk_exited"] = exited

    if var_a.get("type") != var_b.get("type"):
        out["status"] = "drift"
        out["reason"] = "type_changed"
    else:
        out["status"] = _severity(out, th)
        out["reason"] = None
    return out


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------

def _endpoint(art: Artifact) -> Dict[str, Any]:
    return {
        "path": art.path,
        "rows": art.rows,
        "columns": len(art.columns),
        "degraded": bool(art.meta.get("degraded")),
        "tpuprof_version": art.meta.get("tpuprof_version"),
    }


def compute_drift(base: Artifact, current: Artifact,
                  thresholds: Optional[DriftThresholds] = None
                  ) -> Dict[str, Any]:
    """The full drift report (``tpuprof-drift-v1``) comparing ``base``
    (A) to ``current`` (B)."""
    t0 = time.perf_counter()
    th = thresholds or DriftThresholds()
    cols_a, cols_b = base.columns, current.columns
    vars_a = base.stats.get("variables") or {}
    vars_b = current.stats.get("variables") or {}

    added = [c for c in cols_b if c not in cols_a]
    dropped = [c for c in cols_a if c not in cols_b]

    def _schema_entry(reason: str, type_base, type_cur) -> Dict[str, Any]:
        # added/dropped columns carry the FULL metric key set (all
        # null) so every column entry has one shape — consumers and
        # the HTML template never branch on key presence
        return {
            "status": "drift", "reason": reason,
            "type": type_cur, "type_base": type_base,
            "psi": None, "ks": None, "mean_shift": None,
            "missing_delta": None, "distinct_base": None,
            "distinct_current": None, "distinct_ratio": None,
            "distinct_approx": False, "topk_churn": None,
            "topk_entered": [], "topk_exited": [],
        }

    columns: Dict[str, Any] = {}
    for name in cols_b:
        if name in added:
            columns[name] = _schema_entry("added", None, cols_b[name])
            continue
        columns[name] = _column_drift(
            name, vars_a.get(name) or {}, vars_b.get(name) or {},
            base.sketches, current.sketches, th)
    for name in dropped:
        columns[name] = _schema_entry("dropped", cols_a[name], None)

    type_changed = [c for c, e in columns.items()
                    if e.get("reason") == "type_changed"]
    n_drift = sum(1 for e in columns.values() if e["status"] == "drift")
    n_warn = sum(1 for e in columns.values() if e["status"] == "warn")
    report = {
        "schema": DRIFT_SCHEMA_ID,
        "baseline": _endpoint(base),
        "current": _endpoint(current),
        "summary": {
            "rows_base": base.rows,
            "rows_current": current.rows,
            "row_delta": current.rows - base.rows,
            "columns_compared": len(columns),
            "columns_added": added,
            "columns_dropped": dropped,
            "types_changed": type_changed,
            "n_drift": n_drift,
            "n_warn": n_warn,
            "n_ok": len(columns) - n_drift - n_warn,
            "verdict": ("drift" if n_drift else
                        "warn" if n_warn else "ok"),
        },
        "thresholds": th.as_dict(),
        "columns": columns,
    }
    if _obs_metrics.enabled():
        _REPORTS.inc()
        _SECONDS.observe(time.perf_counter() - t0)
        _FLAGGED.set(n_drift)
        from tpuprof.obs import events
        events.emit("drift_report", verdict=report["summary"]["verdict"],
                    n_drift=n_drift, n_warn=n_warn,
                    columns=len(columns))
    return report
