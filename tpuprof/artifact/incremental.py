"""Incremental profiling on the merge laws (ROADMAP item 4).

A fold-able artifact stores the profile's complete mergeable state —
every per-column sketch is a commutative monoid (tests/test_merge_laws),
so ``profile(A ∪ Δ) == stored_state(A) ⊕ profile(Δ)`` holds exactly.
:func:`resume_profiler` realizes the ⊕ through the existing streaming
fold: it rebuilds a :class:`~tpuprof.runtime.stream.StreamingProfiler`
whose state IS the artifact's, so feeding only the newly-arrived
fragments and snapshotting produces the same stats dict (byte-for-byte,
including the RNG-positioned row sample) a full re-scan of A ∪ Δ would
— the nightly 1B-row re-profile becomes ``read + profile(delta)``.

The restore path is the checkpoint's (stream.from_payload): native-hash
provenance, sketch-shape and sampler-k mismatches are all rejected with
the same messages, and a degraded prefix (quarantine manifest in the
stored state) stays degraded in the incremental result.

Single-pass interplay (ISSUE 14): an artifact written by a
``profile_passes=fused`` profiler carries its provisional bin edges
and histogram fold inside the state payload, so the resumed profiler
keeps binning the delta onto the SAME bins — resume is byte-stable,
and the artifact itself seals every lane's exact pass-B bounds as
``sketches["bin_seeds"]`` for the next fused profile to seed from
(runtime/singlepass.py).
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional, Sequence, Union

from tpuprof.artifact.store import Artifact, read_artifact
from tpuprof.obs import metrics as _obs_metrics

_RESUMES = _obs_metrics.counter(
    "tpuprof_artifact_resumes_total",
    "incremental profilers rebuilt from fold-able artifacts")
_RESUME_SECONDS = _obs_metrics.histogram(
    "tpuprof_artifact_resume_seconds",
    "wall seconds per incremental resume (decode + state placement)")
_RESUMED_ROWS = _obs_metrics.gauge(
    "tpuprof_artifact_resumed_rows",
    "rows the newest incremental resume skipped re-scanning")


def resume_profiler(artifact: Union[str, os.PathLike, Artifact],
                    config=None, devices: Optional[Sequence] = None
                    ) -> Any:
    """Rebuild a :class:`StreamingProfiler` from a fold-able artifact
    (path or an already-read :class:`Artifact`).

    The returned profiler continues exactly where the artifact's writer
    stopped: ``update(delta)`` then ``stats()`` equals a full re-scan
    of the whole stream.  Raises :class:`CorruptArtifactError` for a
    stats-only or torn artifact, and the checkpoint-restore
    ``ValueError`` family for config/state mismatches (sampler size,
    HLL width, hash provenance)."""
    t0 = time.perf_counter()
    art = artifact if isinstance(artifact, Artifact) \
        else read_artifact(os.fspath(artifact))
    payload = art.state_payload()
    from tpuprof.runtime.stream import StreamingProfiler
    prof = StreamingProfiler.from_payload(payload, config=config,
                                          devices=devices)
    if _obs_metrics.enabled():
        _RESUMES.inc()
        _RESUME_SECONDS.observe(time.perf_counter() - t0)
        _RESUMED_ROWS.set(art.rows)
        from tpuprof.obs import events
        events.emit("artifact_resume", path=art.path, rows=art.rows,
                    cursor=int(payload.get("cursor", -1)))
    return prof
