"""Versioned stats-artifact store (ROADMAP item 4, ISSUE 6 tentpole).

An *artifact* is one profile persisted as a single JSON document with
schema id ``tpuprof-stats-v1``:

* ``stats`` — the full machine-readable export (report/export.py): raw
  JSON numbers everywhere, human formatting demoted to ``display``.
* ``sketches`` — the drift inputs the export deliberately excludes as
  render-layer detail: per-column histogram (counts, edges) and the
  ranked top-k table, JSON-readable so ``tpuprof diff`` needs no
  unpickling to compare two artifacts.
* ``state`` (optional) — the fold-state payload: the SAME
  ``(device pytree, host aggregators, cursor, meta)`` a streaming
  checkpoint persists (runtime/stream.export_payload), npz+pickled and
  base64-embedded with its own CRC.  An artifact carrying it is
  *fold-able*: ``resume_profiler`` rebuilds the profiler and new
  fragments merge state-for-state — ``stored_state ⊕ profile(delta)``
  equals a full re-scan (tests/test_artifact.py merge-law extension).
  One-shot ``tpuprof profile --artifact`` writes stats-only artifacts
  (diffable, not fold-able); the fold state, like a checkpoint, is a
  same-machine-class payload, not a wire-portable format.

Integrity (the PR-4 durability ladder, applied to a NEW artifact
class): the document carries a CRC32 over its own canonical
serialization, the write is tmp+fsync+rename atomic, and EVERY read
failure — truncation at any byte offset, bit rot, junk rewrite, a
missing or foreign schema id, a torn state payload — surfaces as the
typed :class:`~tpuprof.errors.CorruptArtifactError` (CLI exit code 6),
never a raw ``JSONDecodeError``/``UnpicklingError``.  A torn artifact
can therefore never silently feed a drift report.
"""

from __future__ import annotations

import base64
import binascii
import dataclasses
import io
import json
import os
import pickle
import time
import zlib
from typing import Any, Dict, Optional

from tpuprof.errors import CorruptArtifactError
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.report.export import SCHEMA_ID, json_scalar, stats_to_json
from tpuprof.testing import faults as _faults

_WRITES = _obs_metrics.counter(
    "tpuprof_artifact_writes_total", "stats artifacts written")
_READS = _obs_metrics.counter(
    "tpuprof_artifact_reads_total", "stats artifacts read back")
_CORRUPT = _obs_metrics.counter(
    "tpuprof_artifact_corrupt_total",
    "artifact reads rejected by the integrity checks")
_WRITE_SECONDS = _obs_metrics.histogram(
    "tpuprof_artifact_write_seconds",
    "wall seconds per atomic artifact write (serialize + fsync + rename)")
_READ_SECONDS = _obs_metrics.histogram(
    "tpuprof_artifact_read_seconds",
    "wall seconds per artifact read (disk + CRC + decode)")
_BYTES = _obs_metrics.gauge(
    "tpuprof_artifact_bytes", "size of the newest artifact written")

# how many ranked top-k rows ride the sketches section per CAT column —
# the churn metric's working set (the stats dict's freq tables are
# already capped at config.top_freq upstream)
TOPK_SKETCH_ROWS = 50

# canonical serialization the CRC covers: key-sorted, no whitespace —
# any parsed-value change (even a flipped char inside a string) changes
# these bytes, so crc32(canonical(parse(file))) detects every mutation
# the JSON layer itself does not reject
_CANON = {"sort_keys": True, "separators": (",", ":")}


@dataclasses.dataclass
class Artifact:
    """One artifact, read back: the JSON sections plus the (already
    integrity-checked) raw fold-state bytes when present."""

    schema: str
    meta: Dict[str, Any]
    stats: Dict[str, Any]
    sketches: Dict[str, Any]
    state_bytes: Optional[bytes] = None
    path: Optional[str] = None
    crc32: Optional[int] = None     # the verified integrity envelope's
                                    # CRC — the provenance token the
                                    # columnar warehouse stamps into
                                    # its Parquet metadata

    @property
    def foldable(self) -> bool:
        return self.state_bytes is not None

    @property
    def rows(self) -> int:
        return int(self.meta.get("rows") or 0)

    @property
    def columns(self) -> Dict[str, str]:
        """Column name -> refined kind (NUM/CAT/DATE/...), in profile
        order."""
        return dict(self.meta.get("columns") or {})

    def state_payload(self) -> Dict[str, Any]:
        """Decode the fold-state payload (checkpoint-shaped dict).  Any
        unpickle failure is typed: the CRC already passed, so a failure
        here means an incompatible writer, which to a caller is the
        same 'cannot trust this artifact'."""
        if self.state_bytes is None:
            raise CorruptArtifactError(
                f"artifact {self.path!r} carries no fold state — written "
                "by a one-shot profile (stats-only); incremental resume "
                "needs an artifact written from a StreamingProfiler")
        try:
            payload = pickle.loads(self.state_bytes)
        except Exception as exc:
            raise CorruptArtifactError(
                f"artifact {self.path!r} fold-state payload does not "
                f"decode ({type(exc).__name__}: {exc})") from exc
        if not isinstance(payload, dict) or "host_blob" not in payload:
            raise CorruptArtifactError(
                f"artifact {self.path!r} fold-state payload decodes to "
                "an unexpected layout")
        return payload


def _config_meta(config) -> Dict[str, Any]:
    """The config knobs two artifacts must agree on for their states
    (and sketches) to be comparable/mergeable."""
    if config is None:
        return {}
    keys = ("bins", "hll_precision", "topk_capacity",
            "quantile_sketch_size", "seed", "batch_rows", "nested",
            "exact_distinct", "top_freq")
    out = {k: getattr(config, k, None) for k in keys}
    out["fingerprint"] = config.fingerprint()
    return out


def build_sketches(stats: Dict[str, Any]) -> Dict[str, Any]:
    """The JSON-readable drift inputs, extracted from a stats dict
    BEFORE the export drops them: per-column histograms (the PSI/KS
    substrate) and ranked top-k rows (the churn substrate)."""
    hists: Dict[str, Any] = {}
    for name, var in stats["variables"].items():
        h = var.get("histogram")
        if h is None:
            continue
        counts, edges = h
        hists[str(name)] = {"counts": [int(c) for c in counts],
                            "edges": [float(e) for e in edges]}
    topk: Dict[str, Any] = {}
    for col, vc in (stats.get("freq") or {}).items():
        topk[str(col)] = [
            {"value": json_scalar(idx), "count": int(cnt)}
            for idx, cnt in list(vc.items())[:TOPK_SKETCH_ROWS]]
    out = {"histograms": hists, "topk": topk}
    # pass-B bound seeds (runtime/singlepass.py): every numeric lane's
    # exact f32 (lo, hi, mean) — the next fused profile of this source
    # seeds its provisional bins from here, so an undrifted source
    # skips its second scan entirely.  Absent from pre-singlepass
    # artifacts (the seeder falls back to the histogram endpoints).
    seeds = stats.get("_bin_seeds")
    if seeds:
        out["bin_seeds"] = {str(k): [float(x) for x in v]
                            for k, v in seeds.items()}
    return out


def _encode_state(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Fold-state payload dict -> the embedded JSON entry.  The device
    pytree is flattened to one npz archive exactly as a checkpoint's is
    (runtime/checkpoint), so :func:`resume` feeds the SAME restore path
    a checkpoint does."""
    import jax
    import numpy as np

    from tpuprof.runtime import checkpoint as ckpt

    flat = ckpt._flatten(jax.device_get(payload["state"])) \
        if payload.get("state") is not None else {}
    buf = io.BytesIO()
    np.savez(buf, **flat)
    wire = {
        "arrays_npz": buf.getvalue(),
        "host_blob": payload["host_blob"],
        # the writer's ProfilerConfig rides along so resume_profiler
        # rebuilds the same batch/sketch geometry with no out-of-band
        # config copy (stream.from_payload defaults to it)
        "config": payload.get("config"),
        "cursor": int(payload["cursor"]),
        "meta": payload["meta"],
    }
    raw = pickle.dumps(wire, protocol=pickle.HIGHEST_PROTOCOL)
    return {
        "encoding": "npz+pickle/base64",
        "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
        "length": len(raw),
        "payload": base64.b64encode(raw).decode("ascii"),
    }


def write_artifact(path: str, stats: Optional[Dict[str, Any]] = None,
                   config=None, profiler=None,
                   source: Optional[str] = None) -> Dict[str, Any]:
    """Write one ``tpuprof-stats-v1`` artifact atomically.

    Two entry points:

    * ``write_artifact(path, profiler=stream_prof)`` — snapshot the
      profiler (force-drains buffered rows) AND embed its fold state:
      the artifact is incremental-resumable.
    * ``write_artifact(path, stats=stats_dict, config=cfg)`` — persist
      an already-computed stats dict (the one-shot ``--artifact`` CLI
      path): diffable, stats-only.

    Returns the document's ``meta`` section (handy for logging)."""
    if (profiler is None) == (stats is None):
        raise ValueError("pass exactly one of profiler= or stats=")
    t0 = time.perf_counter()
    state_entry = None
    if profiler is not None:
        config = profiler.config
        state_entry = _encode_state(profiler.export_payload())
        stats = profiler.stats()
    meta = {
        "format": SCHEMA_ID,
        "tpuprof_version": _version(),
        "created_unix": round(time.time(), 3),
        "rows": int(stats["table"]["n"]),
        "columns": {str(name): var["type"]
                    for name, var in stats["variables"].items()},
        "config": _config_meta(config),
        "foldable": state_entry is not None,
        "degraded": bool(stats.get("_quarantine")),
        "source": source,
    }
    core = {
        "schema": SCHEMA_ID,
        "meta": meta,
        "stats": stats_to_json(stats),
        "sketches": build_sketches(stats),
        "state": state_entry,
    }
    doc = dict(core)
    doc["integrity"] = {
        "algorithm": "crc32/canonical-json",
        "crc32": zlib.crc32(json.dumps(core, **_CANON).encode()) & 0xFFFFFFFF,
    }
    data = json.dumps(doc, indent=1).encode()
    # dot-prefixed temp (ISSUE 12 durability invariant): artifact
    # chains are directory-scanned (watch retention), so the in-flight
    # write must be invisible to every name filter; single writer per
    # path, so no pid — a crashed write's litter is reclaimed next time
    tmp = os.path.join(os.path.dirname(path) or ".",
                       f".{os.path.basename(path)}.tmp")
    try:
        with open(tmp, "wb") as fh:
            _faults.hit("artifact_write", key=meta["rows"])
            fh.write(_faults.mangle("artifact_write", data))
            # fsync BEFORE the rename (the checkpoint store's rationale:
            # os.replace is atomic in the namespace, not for data pages)
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)
    if _obs_metrics.enabled():
        _WRITES.inc()
        _WRITE_SECONDS.observe(time.perf_counter() - t0)
        _BYTES.set(len(data))
        from tpuprof.obs import events
        events.emit("artifact_write", path=path, rows=meta["rows"],
                    bytes=len(data), foldable=meta["foldable"])
    # a COPY carrying the sealed document's CRC (the warehouse
    # provenance token) — the doc's own meta section must stay exactly
    # what the CRC covered
    out = dict(meta)
    out["crc32"] = doc["integrity"]["crc32"]
    return out


def read_artifact(path: str) -> Artifact:
    """Read + integrity-check one artifact.  Every failure mode is the
    typed :class:`CorruptArtifactError` except a genuinely missing file
    (``FileNotFoundError`` — "never written" and "rotted" are different
    operator problems)."""
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        raise
    except OSError as exc:
        _mark_corrupt()
        raise CorruptArtifactError(
            f"artifact {path!r} is unreadable "
            f"({type(exc).__name__}: {exc})") from exc
    try:
        doc = json.loads(data)
    except Exception as exc:
        _mark_corrupt()
        raise CorruptArtifactError(
            f"artifact {path!r} is not valid JSON — truncated or "
            f"corrupt ({type(exc).__name__}: {exc})") from exc
    if not isinstance(doc, dict):
        _mark_corrupt()
        raise CorruptArtifactError(
            f"artifact {path!r} decodes to {type(doc).__name__}, not an "
            "artifact document")
    if doc.get("schema") != SCHEMA_ID:
        _mark_corrupt()
        raise CorruptArtifactError(
            f"artifact {path!r} has schema {doc.get('schema')!r}; this "
            f"build reads {SCHEMA_ID!r}")
    integrity = doc.pop("integrity", None)
    if not isinstance(integrity, dict) or "crc32" not in integrity:
        _mark_corrupt()
        raise CorruptArtifactError(
            f"artifact {path!r} lacks its integrity envelope — torn or "
            "hand-edited")
    canon = json.dumps(doc, **_CANON).encode()
    if zlib.crc32(canon) & 0xFFFFFFFF != integrity["crc32"]:
        _mark_corrupt()
        raise CorruptArtifactError(
            f"artifact {path!r} CRC mismatch — corrupt artifact")
    state_bytes = None
    state = doc.get("state")
    if state is not None:
        try:
            state_bytes = base64.b64decode(
                state["payload"].encode("ascii"), validate=True)
        except (KeyError, TypeError, AttributeError,
                binascii.Error) as exc:
            _mark_corrupt()
            raise CorruptArtifactError(
                f"artifact {path!r} fold-state payload does not decode "
                f"({type(exc).__name__}: {exc})") from exc
        if len(state_bytes) != state.get("length") or \
                zlib.crc32(state_bytes) & 0xFFFFFFFF != state.get("crc32"):
            _mark_corrupt()
            raise CorruptArtifactError(
                f"artifact {path!r} fold-state payload fails its CRC — "
                "torn write")
    art = Artifact(schema=doc["schema"], meta=doc.get("meta") or {},
                   stats=doc.get("stats") or {},
                   sketches=doc.get("sketches") or {},
                   state_bytes=state_bytes, path=path,
                   crc32=int(integrity["crc32"]))
    if _obs_metrics.enabled():
        _READS.inc()
        _READ_SECONDS.observe(time.perf_counter() - t0)
    return art


def _mark_corrupt() -> None:
    _CORRUPT.inc()
    from tpuprof.obs import blackbox
    blackbox.record("artifact_corrupt")


def _version() -> str:
    from tpuprof import __version__
    return __version__
