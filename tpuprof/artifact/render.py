"""Drift dict → HTML, through the report template environment.

The drift page reuses the profile report's shell, CSS and formatter
filters (report/render.py) so the two products look like one tool; the
fragment itself is a NEW template (``drift.html``), so profile-report
HTML stays byte-identical.
"""

from __future__ import annotations

from typing import Any, Dict

from markupsafe import Markup


def drift_to_html(drift: Dict[str, Any],
                  title: str = "tpuprof drift report") -> str:
    """Standalone drift page for one ``tpuprof-drift-v1`` dict."""
    from tpuprof import __version__
    from tpuprof.report.render import _get_env
    env = _get_env()
    fragment = env.get_template("drift.html").render(
        drift=drift, version=__version__)
    return env.get_template("base.html").render(
        title=title, version=__version__,
        content=Markup(fragment)).lstrip()
