"""tpuprof/artifact — persisted stats artifacts, incremental profiling
and drift detection (ROADMAP item 4; ISSUE 6 tentpole).

The subsystem turns every profile into a durable, comparable, fold-able
product:

* :func:`write_artifact` / :func:`read_artifact` — the versioned
  ``tpuprof-stats-v1`` store (store.py): raw-number stats + drift
  sketches + (optionally) the complete mergeable fold state, CRC-sealed
  so a torn file is a typed :class:`~tpuprof.errors.CorruptArtifactError`,
  never a silent wrong drift report.
* :func:`resume_profiler` — incremental profiling (incremental.py):
  rebuild a StreamingProfiler from a fold-able artifact and profile
  only the delta; ``stored_state ⊕ profile(delta)`` equals a full
  re-scan byte-for-byte.
* :func:`compute_drift` / :func:`drift_to_html` — ``tpuprof diff A B``
  (drift.py, render.py): per-column PSI/KS from the stored histograms,
  distinct/top-k churn, schema changes, as machine-readable
  ``tpuprof-drift-v1`` JSON plus an HTML page on the report templates.

See ARTIFACTS.md for the schema, compatibility policy and metric
definitions, and OBSERVABILITY.md for the ``tpuprof_artifact_*`` /
``tpuprof_drift_*`` metrics.
"""

from tpuprof.artifact.drift import (DRIFT_SCHEMA_ID, DriftThresholds,
                                    compute_drift, ks_statistic,
                                    psi_statistic)
from tpuprof.artifact.incremental import resume_profiler
from tpuprof.artifact.render import drift_to_html
from tpuprof.artifact.store import (SCHEMA_ID, Artifact, read_artifact,
                                    write_artifact)

__all__ = [
    "Artifact", "DRIFT_SCHEMA_ID", "DriftThresholds", "SCHEMA_ID",
    "compute_drift", "drift_to_html", "ks_statistic", "psi_statistic",
    "read_artifact", "resume_profiler", "write_artifact",
]
