"""ProfileReport — the user-facing facade (parity surface).

Reference: spark_df_profiling/__init__.py [U] (SURVEY.md §1, §3):

    ProfileReport(df, bins=10, corr_reject=0.9, **kwargs)
    report.to_file(outputfile)       # standalone HTML page
    report.html                      # rendered fragment/page
    report.get_rejected_variables(threshold)
    report._repr_html_()             # Jupyter auto-display

As in the reference, statistics are computed eagerly at construction
(SURVEY §3.3: notebook display returns the cached string, no
recomputation).  Rendering is deferred to first ``.html`` access — an
observable no-op since the stats dict is already frozen.
"""

from __future__ import annotations

import io
from typing import Any, Dict, List, Optional

from tpuprof.backends.base import get_backend
from tpuprof.config import ProfilerConfig
from tpuprof.schema import (VariablesView, rejected_variables,
                            validate_stats)


def describe(source: Any, config: Optional[ProfilerConfig] = None,
             **kwargs) -> Dict[str, Any]:
    """Reference: base.describe(df, bins, corr_reject) — returns the stats
    dict (SURVEY §1 L2→L3 seam) without rendering."""
    if config is not None and kwargs:
        raise ValueError(
            f"pass either an explicit ProfilerConfig or kwargs, not both "
            f"(got config and {sorted(kwargs)})")
    config = config or ProfilerConfig.from_kwargs(**kwargs)
    backend = get_backend(config.backend)
    if backend.name == "cpu":
        from tpuprof.config import resolve_elastic
        if resolve_elastic(config.elastic):
            # the oracle ignores runtime knobs silently (checkpoints,
            # watchdogs — perf-only), but elastic changes WHO does the
            # work: N oracle members would each profile everything and
            # race on the output believing it was split
            from tpuprof.errors import InputError
            raise InputError(
                "elastic fleet mode needs the streaming engine — the "
                f"selected backend is the CPU oracle (backend="
                f"{config.backend!r}); pass backend='tpu' (it runs on "
                "CPU hosts too)")
    stats = backend.collect(source, config)
    problems = validate_stats(stats)
    if problems:
        raise AssertionError(
            f"backend {backend.name!r} violated the stats contract: {problems}")
    # serve the reference's DataFrame idioms (.loc[col, 'mean']) and the
    # native dict contract from the same object (SURVEY §1 L2→L3 seam)
    stats["variables"] = VariablesView(stats["variables"])
    return stats


class ProfileReport:
    """Profile a tabular source and render an HTML report.

    ``source`` may be a pandas DataFrame, a pyarrow Table, or a path to a
    Parquet file/directory (the TPU backend streams the latter two without
    materializing them in host memory).
    """

    def __init__(self, source: Any, config: Optional[ProfilerConfig] = None,
                 **kwargs):
        if config is not None and kwargs:
            raise ValueError(
                f"pass either an explicit ProfilerConfig or kwargs, not both "
                f"(got config and {sorted(kwargs)})")
        self.config = config or ProfilerConfig.from_kwargs(**kwargs)
        self.description = describe(source, self.config)
        self._html: Optional[str] = None

    # -- reference API ------------------------------------------------------

    @property
    def html(self) -> str:
        if self._html is None:
            from tpuprof.report.render import to_html
            self._html = to_html(self.description, self.config)
        return self._html

    def to_file(self, outputfile: str) -> None:
        """Reference: ProfileReport.to_file — wraps the fragment with the
        standalone page shell and writes it; purely host-local, no compute
        (SURVEY §3.2)."""
        from tpuprof.report.render import to_standalone_html
        page = to_standalone_html(self.description, self.config)
        with io.open(outputfile, "w", encoding="utf-8") as fh:
            fh.write(page)

    def to_json_dict(self) -> Dict[str, Any]:
        """The complete stats dict (every top-level key of the SURVEY §1
        contract — table, variables, freq, correlations, messages,
        sample) as a ``json.dump``-ready structure.  ``--stats-json``
        writes exactly this."""
        from tpuprof.report.export import stats_to_json
        return stats_to_json(self.description)

    def get_rejected_variables(self, threshold: Optional[float] = None
                               ) -> List[str]:
        """Columns rejected for high correlation (SURVEY §3.4) — reads the
        cached dict, no recomputation."""
        return rejected_variables(self.description, threshold)

    def _repr_html_(self) -> str:
        return self.html

    def __repr__(self) -> str:
        table = self.description["table"]
        return (f"<tpuprof.ProfileReport n={table['n']} "
                f"nvar={table['nvar']}>")
