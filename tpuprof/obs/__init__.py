"""Pipeline observability (OBSERVABILITY.md): metrics registry, span
tracing, JSONL event export, live progress.

The subsystem is OFF by default and costs one branch per instrumentation
site when off.  Three switches turn it on, strongest first:

* ``ProfilerConfig(metrics_enabled=True, metrics_path=...)`` — per-run
* ``--metrics-json PATH`` / ``--progress`` on the CLI
* ``TPUPROF_METRICS=1`` (and ``TPUPROF_METRICS_PATH``) in the env

All three land on :func:`configure`, which flips the process-wide
default registry and points the JSONL sink.  Everything here is
host-side and import-light: no jax, no pandas — safe to import from
the hot ingest modules.
"""

from __future__ import annotations

from typing import Optional

from tpuprof.obs import blackbox, events, fleet, memory, metrics
from tpuprof.obs.events import emit, emit_snapshot
from tpuprof.obs.metrics import (MetricsRegistry, counter, enabled, gauge,
                                 histogram, registry, set_enabled)
from tpuprof.obs.progress import RateEMA, Ticker, registry_progress_line
from tpuprof.obs.spans import current_path, get_phase_report, span

__all__ = [
    "MetricsRegistry", "RateEMA", "Ticker", "blackbox", "block_sample",
    "configure", "configure_from_config", "counter", "current_path",
    "emit", "emit_snapshot", "enabled", "finalize", "fleet", "gauge",
    "get_phase_report", "histogram", "memory", "registry",
    "registry_progress_line", "set_enabled", "snapshot_if_enabled",
    "span",
]

# every Nth device dispatch is block_until_ready-timed when > 0
# (kernels/fused.observe_dispatch); 0 = never synchronize for telemetry
_block_sample = 0


def block_sample() -> int:
    return _block_sample


def configure(enabled: Optional[bool] = None,
              jsonl_path: Optional[str] = None,
              block_sample: Optional[int] = None,
              max_bytes: Optional[int] = None) -> None:
    """Flip the process-wide observability state.  ``None`` leaves a
    knob as it is, so CLI and backend can each set their half without
    clobbering the other."""
    global _block_sample
    if jsonl_path is not None:
        events.set_sink(jsonl_path, max_bytes=max_bytes)
        if enabled is None:     # a sink with recording off would be empty
            enabled = True
    if enabled is not None:
        metrics.set_enabled(enabled)
    if block_sample is not None:
        _block_sample = max(int(block_sample), 0)


def configure_from_config(config) -> None:
    """Apply a ProfilerConfig's metrics knobs (backends call this at the
    top of collect / StreamingProfiler.__init__)."""
    from tpuprof.config import (resolve_metrics_enabled,
                                resolve_metrics_max_bytes)
    on = resolve_metrics_enabled(config.metrics_enabled,
                                 config.metrics_path)
    path = resolve_metrics_path(config)
    configure(enabled=on, jsonl_path=path,
              block_sample=config.metrics_block_sample,
              max_bytes=resolve_metrics_max_bytes(
                  getattr(config, "metrics_max_bytes", None)))
    # the flight recorder's context card: enough to read a postmortem
    # without the process that wrote it
    blackbox.set_context(config_fingerprint=config.fingerprint())


def resolve_metrics_path(config) -> Optional[str]:
    """The JSONL sink path this config lands on (config field, else
    ``TPUPROF_METRICS_PATH``) — also the base the fleet exposition
    (``<path>.fleet.prom``) derives from."""
    path = config.metrics_path
    if path is None:
        import os
        path = os.environ.get("TPUPROF_METRICS_PATH") or None
    return path


def snapshot_if_enabled() -> Optional[dict]:
    """Registry snapshot when recording is on, else None — what rides
    the stats dict (``stats['_obs']``) into the report footer."""
    if not metrics.enabled():
        return None
    return metrics.registry().snapshot()


def finalize(reason: str = "final") -> None:
    """Flush a final metrics snapshot into the JSONL sink (if any).  The
    sink stays open — a process may profile again and append."""
    if events.get_sink() is not None:
        emit_snapshot(reason=reason)
