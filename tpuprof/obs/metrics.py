"""Thread-safe metrics registry: counters, gauges, fixed-bucket
histograms (OBSERVABILITY.md).

Design constraints, in order:

* **Near-zero overhead when disabled.**  Every instrument method's first
  action is one attribute read of the registry's ``enabled`` flag; the
  instrumentation sites in the hot paths (ingest, stream drain, device
  dispatch) are per-batch or per-task, never per-row, so the disabled
  cost is a handful of predictable branches per 64k rows.
* **Process-wide default registry.**  Instruments are declared at module
  import (``metrics.counter(...)`` at the top of ingest/arrow.py, etc.)
  and exist whether or not recording is on — ``render_text()`` then
  shows an honest zero rather than omitting a series that simply never
  fired.
* **Prometheus-style exposition** via :meth:`MetricsRegistry.render_text`
  and a plain-dict :meth:`MetricsRegistry.snapshot` for JSON/JSONL
  export (obs/events.py writes the event stream).

Labels are keyword arguments at record time (``c.inc(program="scan_a")``)
and must stay low-cardinality — worker names, program names, path kinds;
never column names or row values (a 10k-column table must not mint 10k
series).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# default histogram buckets: wall-clock seconds from 100us to 60s —
# covers a prep task (~ms), a device dispatch (~15ms tunneled), a
# checkpoint save (~100ms) and a full drain (~s) on one shared scale
TIME_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    # sorted so inc(a=1, b=2) and inc(b=2, a=1) hit one series
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    # Prometheus exposition escaping for label values: backslash first
    # (the escape character itself), then quote and line feed — a value
    # containing `"` or a newline would otherwise tear the sample line
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Instrument:
    """Shared series storage: one value (or bucket vector) per label set."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help: str = ""):
        self._registry = registry
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, Any] = {}

    # NOTE: instrument methods check ``self._registry.enabled`` inline
    # (a plain attribute read) rather than via a property — a property
    # is a Python-level call, and the disabled path is budgeted at one
    # branch per site (PERF.md round 6)


class Counter(_Instrument):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if not self._registry.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def total(self) -> float:
        """Sum across every label set (the unlabeled view)."""
        with self._lock:
            return float(sum(self._series.values()))

    def items(self) -> List[Tuple[LabelKey, float]]:
        """(label_key, value) pairs — a stable copy."""
        with self._lock:
            return list(self._series.items())


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels) -> None:
        if not self._registry.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Instrument):
    """Fixed-bucket histogram (cumulative buckets at render time, like
    Prometheus): per label set it keeps per-bucket counts plus sum and
    count — no per-observation storage, O(buckets) memory forever."""

    kind = "histogram"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help: str = "",
                 buckets: Sequence[float] = TIME_BUCKETS):
        super().__init__(registry, name, help)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name!r} needs >= 1 bucket bound")
        self.buckets = bs

    def observe(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        key = _label_key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = self._series[key] = {
                    "buckets": [0] * len(self.buckets),
                    "sum": 0.0, "count": 0}
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["buckets"][i] += 1
                    break
            st["sum"] += float(value)
            st["count"] += 1

    def summary(self, **labels) -> Dict[str, float]:
        with self._lock:
            st = self._series.get(_label_key(labels))
            if st is None:
                return {"count": 0, "sum": 0.0, "mean": 0.0}
            n = st["count"]
            return {"count": n, "sum": st["sum"],
                    "mean": st["sum"] / n if n else 0.0}


class MetricsRegistry:
    """Instrument factory + exporter.  ``get_or_create`` semantics: a
    second declaration of the same name returns the existing instrument
    (modules re-imported under different names must not fork a series),
    but a kind mismatch is a programming error and raises."""

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    # -- declaration -------------------------------------------------------

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Any:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{inst.kind}, not {cls.kind}")
                return inst
            inst = cls(self, name, help, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = TIME_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    # -- export ------------------------------------------------------------

    def _items(self) -> List[_Instrument]:
        with self._lock:
            return sorted(self._instruments.values(),
                          key=lambda i: i.name)

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view of every series (JSON-serializable).

        ``{"counters": {name: {label_str: value}}, "gauges": {...},
        "histograms": {name: {label_str: {count, sum, mean}}}}`` —
        label_str "" is the unlabeled series."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "histograms": {}}
        for inst in self._items():
            with inst._lock:
                series = dict(inst._series)
            if isinstance(inst, Histogram):
                out["histograms"][inst.name] = {
                    _fmt_labels(k): {
                        "count": st["count"], "sum": round(st["sum"], 6),
                        "mean": round(st["sum"] / st["count"], 6)
                        if st["count"] else 0.0}
                    for k, st in series.items()}
            else:
                bucket = "counters" if isinstance(inst, Counter) \
                    else "gauges"
                out[bucket][inst.name] = {
                    _fmt_labels(k): v for k, v in series.items()}
        return out

    def render_text(self) -> str:
        """Prometheus text exposition (the ``/metrics`` format): HELP and
        TYPE comments, one sample line per series, histograms expanded
        into cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``."""
        lines: List[str] = []
        for inst in self._items():
            with inst._lock:
                series = dict(inst._series)
            if inst.help:
                lines.append(f"# HELP {inst.name} {inst.help}")
            lines.append(f"# TYPE {inst.name} {inst.kind}")
            if isinstance(inst, Histogram):
                for key, st in sorted(series.items()):
                    cum = 0
                    for b, c in zip(inst.buckets, st["buckets"]):
                        cum += c
                        lk = _fmt_labels(key + (("le", _fmt_value(b)),))
                        lines.append(f"{inst.name}_bucket{lk} {cum}")
                    lk = _fmt_labels(key + (("le", "+Inf"),))
                    lines.append(f"{inst.name}_bucket{lk} {st['count']}")
                    lines.append(f"{inst.name}_sum{_fmt_labels(key)} "
                                 f"{st['sum']:.6g}")
                    lines.append(f"{inst.name}_count{_fmt_labels(key)} "
                                 f"{st['count']}")
            else:
                if not series:
                    # an instrument that never fired still exposes its
                    # unlabeled zero — absence would read as "not wired"
                    lines.append(f"{inst.name} 0")
                for key, v in sorted(series.items()):
                    lines.append(
                        f"{inst.name}{_fmt_labels(key)} {_fmt_value(v)}")
        return "\n".join(lines) + "\n"

    # -- fleet wire form (obs/fleet.py) ------------------------------------

    def to_wire(self) -> Dict[str, Any]:
        """Mergeable, picklable view of every series — what a host ships
        over the DCN allgather for fleet aggregation (obs/fleet.py).

        Label keys stay structured (lists of ``[name, value]`` pairs,
        not the rendered ``{a="b"}`` strings) so :meth:`merge_wire` can
        relabel and sum without parsing."""
        wire: Dict[str, Any] = {"counters": {}, "gauges": {},
                                "histograms": {}}
        for inst in self._items():
            with inst._lock:
                series = dict(inst._series)
            if isinstance(inst, Histogram):
                wire["histograms"][inst.name] = {
                    "help": inst.help,
                    "buckets": list(inst.buckets),
                    "series": [[list(map(list, k)),
                                {"buckets": list(st["buckets"]),
                                 "sum": float(st["sum"]),
                                 "count": int(st["count"])}]
                               for k, st in series.items()],
                }
            else:
                kind = "counters" if isinstance(inst, Counter) else "gauges"
                wire[kind][inst.name] = {
                    "help": inst.help,
                    "series": [[list(map(list, k)), float(v)]
                               for k, v in series.items()],
                }
        return wire

    def merge_wire(self, wire: Dict[str, Any],
                   host: Optional[str] = None) -> None:
        """Fold one host's :meth:`to_wire` payload into this registry.

        Merge laws (the fleet view's contract, tests/test_fleet.py):

        * **counters sum** across hosts per label set — fleet totals;
        * **gauges keep per-host values** under an added ``host=`` label
          (a gauge is a point-in-time reading; summing two hosts' queue
          depths or RSS would fabricate a number nobody measured);
        * **histograms sum** bucket ladders + sum + count when the
          ladders match; a mismatched ladder (version skew across the
          fleet) degrades to per-host series under ``host=`` rather
          than silently mis-summing buckets.
        """
        host_pair = [] if host is None else [["host", str(host)]]
        for name, ent in wire.get("counters", {}).items():
            c = self.counter(name, ent.get("help", ""))
            with c._lock:
                for key, value in ent.get("series", []):
                    k = tuple(tuple(p) for p in key)
                    c._series[k] = c._series.get(k, 0.0) + float(value)
        for name, ent in wire.get("gauges", {}).items():
            g = self.gauge(name, ent.get("help", ""))
            with g._lock:
                for key, value in ent.get("series", []):
                    k = _label_key(dict(list(map(tuple, key))
                                        + host_pair))
                    g._series[k] = float(value)
        for name, ent in wire.get("histograms", {}).items():
            buckets = tuple(float(b) for b in ent.get("buckets", ()))
            h = self.histogram(name, ent.get("help", ""),
                               buckets=buckets or TIME_BUCKETS)
            same_ladder = h.buckets == buckets
            with h._lock:
                for key, st in ent.get("series", []):
                    pairs = list(map(tuple, key))
                    if not same_ladder:
                        # version-skewed ladder: keep the host's series
                        # intact (relabelled) instead of mis-summing
                        pairs += [("host", str(host))] \
                            if host is not None else []
                        k = _label_key(dict(pairs))
                        h._series[k] = {
                            "buckets": [0] * len(h.buckets),
                            "sum": float(st["sum"]),
                            "count": int(st["count"])}
                        continue
                    k = tuple(pairs)
                    mine = h._series.get(k)
                    if mine is None:
                        mine = h._series[k] = {
                            "buckets": [0] * len(h.buckets),
                            "sum": 0.0, "count": 0}
                    for i, c in enumerate(st["buckets"]):
                        mine["buckets"][i] += int(c)
                    mine["sum"] += float(st["sum"])
                    mine["count"] += int(st["count"])

    def reset(self) -> None:
        """Zero every series (instrument declarations survive) — test
        isolation and the per-profile snapshot boundary."""
        for inst in self._items():
            with inst._lock:
                inst._series.clear()


# ---------------------------------------------------------------------------
# process-wide default registry
# ---------------------------------------------------------------------------

_default = MetricsRegistry(enabled=False)


def registry() -> MetricsRegistry:
    return _default


def enabled() -> bool:
    return _default.enabled


def set_enabled(value: bool) -> None:
    _default.enabled = bool(value)


def counter(name: str, help: str = "") -> Counter:
    return _default.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _default.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = TIME_BUCKETS) -> Histogram:
    return _default.histogram(name, help, buckets=buckets)
