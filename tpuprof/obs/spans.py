"""Span-based tracing: nested wall-clock timing with metadata.

``span("prep", cols=23)`` times its body, records the wall seconds into

* the per-phase totals (``get_phase_report()`` — the report footer's
  contract, kept from the original ``phase_timer``),
* the ``tpuprof_span_seconds{name=...}`` histogram (when metrics are
  enabled),
* one ``{"kind": "span"}`` JSONL event (when a sink is configured),
  carrying the full dotted path (``"profile.scan_a"``) and nesting depth
  so a trace viewer can rebuild the tree,
* a debug log line (the original ``phase_timer`` behavior).

Nesting is per-thread (a ``threading.local`` stack): spans opened by
prep-pool workers do not see — or corrupt — the main thread's stack.
Phase totals accumulate under the span's LEAF name, exactly like
``phase_timer`` did, so ``get_phase_report()`` keys are stable across
the refactor.
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
from typing import Any, Dict, Iterator

from tpuprof.obs import events, metrics

logger = logging.getLogger("tpuprof")

_lock = threading.Lock()
_phase_totals: Dict[str, float] = {}
_tls = threading.local()

_SPAN_SECONDS = metrics.histogram(
    "tpuprof_span_seconds",
    "wall-clock seconds per pipeline span, by leaf name")


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_path() -> str:
    """Dotted path of the innermost open span on THIS thread ('' at
    top level)."""
    return ".".join(_stack())


@contextlib.contextmanager
def span(name: str, **meta: Any) -> Iterator[None]:
    """Time a pipeline stage.  Exceptions propagate; the timing is
    recorded either way (a failed stage's cost is still cost)."""
    stack = _stack()
    stack.append(name)
    depth = len(stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        with _lock:
            _phase_totals[name] = _phase_totals.get(name, 0.0) + dt
        _SPAN_SECONDS.observe(dt, name=name)
        # unconditional: emit records into the crash flight recorder
        # even with no sink configured (obs/blackbox.py), so span
        # closes are visible in a postmortem of a metrics-off run
        events.emit("span", name=name, seconds=round(dt, 6),
                    path=".".join(stack + [name]), depth=depth,
                    **meta)
        logger.debug("%s", json.dumps(
            {"event": "phase", "name": name, "seconds": round(dt, 4),
             **meta}, default=str))


def get_phase_report(reset: bool = False) -> Dict[str, float]:
    """Per-leaf-name accumulated wall-clock seconds (the report footer
    and bench stage breakdowns read this)."""
    with _lock:
        out = dict(_phase_totals)
        if reset:
            _phase_totals.clear()
    return out
