"""Fleet-wide metric aggregation (OBSERVABILITY.md "fleet view").

The per-process obs layer leaves an N-host mesh with N separate
``.prom``/JSONL files and no single place to read the fleet.  This
module is the missing rung: every process ships its registry's wire
form (``MetricsRegistry.to_wire()``) over the existing DCN allgather
(runtime/distributed.publish_fleet calls :func:`merge_wires`), and
host 0 writes ONE ``<metrics_path>.fleet.prom`` plus a
``fleet_snapshot`` JSONL event covering every process.

Merge laws (tests/test_fleet.py):

* counters **sum** across hosts (fleet totals — rows, dispatches,
  quarantines, watchdog timeouts);
* gauges keep **per-host values** under an added ``host=`` label;
* histograms **sum** their bucket ladders (same declared buckets).

Everything here is host-side and import-light: no jax — the collective
leg lives in runtime/distributed.py, which hands this module plain
wire dicts.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from tpuprof.obs import events, metrics


def _atomic_text_write(path: str, text: str) -> None:
    """tmp + os.replace so concurrent writers last-writer-win on a
    COMPLETE file: elastic leader election (min live host on each
    member's own liveness snapshot) can transiently elect two leaders,
    and two plain open(path, 'w') writers would interleave into a torn
    prom dump."""
    # dot-prefixed basename + pid (ISSUE 12 durability invariant): two
    # transiently-elected leaders need distinct temps, and no directory
    # scan may ever see the in-flight write.  fsync before the rename —
    # os.replace is atomic in the namespace, not for data pages
    tmp = os.path.join(os.path.dirname(path) or ".",
                       f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "w") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def fleet_prom_path(metrics_path: str) -> str:
    """Where the fleet exposition lands, next to the per-process
    ``<metrics_path>.prom`` twin."""
    return metrics_path + ".fleet.prom"


def merge_wires(wires: List[Dict[str, Any]]) -> metrics.MetricsRegistry:
    """Fold every host's wire into one registry (host i gets gauge
    label ``host="i"`` — list order is the allgather's rank order)."""
    merged = metrics.MetricsRegistry(enabled=True)
    for i, wire in enumerate(wires):
        merged.merge_wire(wire, host=str(i))
    return merged


def write_fleet_labeled(metrics_path: Optional[str],
                        wires_by_host: Dict[str, Dict[str, Any]],
                        reason: str = "collect") -> Optional[str]:
    """The elastic-fleet twin of :func:`write_fleet`: wires arrive
    keyed by STABLE host id (runtime/fleet.py contribution wires), not
    allgather rank, so the ``host=`` gauge labels survive membership
    churn — a report written by the surviving leader still names the
    dead member's series by its id."""
    merged = metrics.MetricsRegistry(enabled=True)
    for host in sorted(wires_by_host):
        merged.merge_wire(wires_by_host[host], host=host)
    snap = merged.snapshot()
    events.emit("fleet_snapshot", reason=reason,
                hosts=len(wires_by_host), snapshot=snap)
    if not metrics_path:
        return None
    path = fleet_prom_path(metrics_path)
    try:
        _atomic_text_write(path, merged.render_text())
    except OSError:
        return None         # the fleet dump must never fail the profile
    return path


def write_fleet(metrics_path: Optional[str],
                wires: List[Dict[str, Any]],
                reason: str = "collect",
                quarantined_by_host: Optional[List[int]] = None) -> \
        Optional[str]:
    """Render + persist the fleet view (the HOST-0 half of a publish).

    Writes ``<metrics_path>.fleet.prom`` when a metrics path is
    configured, and emits one ``fleet_snapshot`` JSONL event (ring +
    sink) either way.  Returns the path written, or None."""
    merged = merge_wires(wires)
    snap = merged.snapshot()
    events.emit("fleet_snapshot", reason=reason, hosts=len(wires),
                quarantined_by_host=list(quarantined_by_host or []),
                snapshot=snap)
    if not metrics_path:
        return None
    path = fleet_prom_path(metrics_path)
    try:
        # same atomic seam as write_fleet_labeled: a reader (scraper,
        # test) racing the collect-finish dump must see the previous
        # complete file or the new one, never interleaved text
        _atomic_text_write(path, merged.render_text())
    except OSError:
        return None         # the fleet dump must never fail the profile
    return path
