"""Device + host memory telemetry (OBSERVABILITY.md "memory gauges").

A long profile's failure mode on real hardware is headroom, not speed:
HBM creeping toward the limit as staged batches pile up, or host RSS
growing under a leaky prep cache.  This module samples both at drain
boundaries (stream drains, pass flushes — never per batch):

* ``tpuprof_device_memory_bytes{kind="in_use"|"limit", device=...}``
  from ``device.memory_stats()`` — guarded: CPU/older backends return
  None or lack the method entirely, and the gauges simply stay silent;
* ``tpuprof_host_rss_bytes`` from ``/proc/self/statm`` (fallback:
  ``resource.getrusage`` peak RSS — better than nothing on non-Linux).

``sample()`` is also the plain-dict read the bench block and report
footer consume; it records into the registry only when metrics are on.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence

from tpuprof.obs import metrics as _obs_metrics

_DEVICE_MEM = _obs_metrics.gauge(
    "tpuprof_device_memory_bytes",
    "accelerator memory bytes by device and kind (in_use/limit); "
    "silent on backends without memory_stats()")
_HOST_RSS = _obs_metrics.gauge(
    "tpuprof_host_rss_bytes",
    "resident set size of this process at the last drain boundary")


def host_rss_bytes() -> Optional[int]:
    """Current RSS in bytes (None when unreadable)."""
    try:
        with open("/proc/self/statm") as fh:
            fields = fh.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, IndexError, ValueError):
        pass
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB, macOS bytes — normalize heuristically
        return int(ru) * (1 if ru > 1 << 32 else 1024)
    except Exception:
        return None


def device_memory(devices: Optional[Sequence] = None) -> Dict[str, Dict[str, int]]:
    """``{device_label: {"in_use": ..., "limit": ...}}`` for every local
    device that reports memory stats ({} on CPU backends)."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax
        devs = devices if devices is not None else jax.local_devices()
    except Exception:
        return out
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue            # CPU backends return None
        label = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', '?')}"
        ent: Dict[str, int] = {}
        in_use = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit")
        if in_use is not None:
            ent["in_use"] = int(in_use)
        if limit is not None:
            ent["limit"] = int(limit)
        if ent:
            out[label] = ent
    return out


def sample(devices: Optional[Sequence] = None) -> Dict[str, Any]:
    """One telemetry sample: reads both sides, sets the gauges when
    metrics are enabled, and returns the plain dict either way (bench
    block / report assembly).  Cheap enough for drain boundaries; never
    raises."""
    devmem = device_memory(devices)
    rss = host_rss_bytes()
    if _obs_metrics.enabled():
        for label, ent in devmem.items():
            for kind, value in ent.items():
                _DEVICE_MEM.set(value, device=label, kind=kind)
        if rss is not None:
            _HOST_RSS.set(rss)
    return {"devices": devmem, "host_rss_bytes": rss}
