"""Live progress / heartbeat layer.

Two consumers:

* ``StreamingProfiler.heartbeat()`` / ``.progress()`` — an in-process
  pull API: rows folded, batches, buffered rows, and a rows/s EMA that
  tracks the recent rate rather than the lifetime average (a stalled
  stream reads ~0, not its historical glory).
* the CLI ticker (``--progress`` / ``--metrics-interval``) — a daemon
  thread that periodically prints a one-line status to stderr and/or
  emits a metrics snapshot into the JSONL sink while a (possibly
  hour-long) profile runs, reading the process-wide registry the
  pipeline is already updating.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Dict, Optional

from tpuprof.obs import events, metrics


class RateEMA:
    """Exponentially-decayed rate estimator (rows/s).

    ``update(n)`` adds n units at *now*; the rate halves its memory
    every ``halflife`` seconds of silence, so bursts decay and a stall
    converges to 0 instead of freezing the last burst's figure."""

    def __init__(self, halflife: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.halflife = float(halflife)
        self._clock = clock
        self._lock = threading.Lock()
        self._rate = 0.0
        self._acc = 0.0                     # units since the last blend
        self._t_last: Optional[float] = None

    def update(self, n: float) -> None:
        now = self._clock()
        with self._lock:
            if self._t_last is None:        # first sample starts the clock
                self._t_last = now
                self._acc = float(n)
                return
            self._acc += float(n)
            dt = now - self._t_last
            if dt <= 0:                     # same-instant bursts coalesce
                return
            inst = self._acc / dt
            alpha = 1.0 - 0.5 ** (dt / self.halflife)
            self._rate += alpha * (inst - self._rate)
            self._acc = 0.0
            self._t_last = now

    def rate(self) -> float:
        now = self._clock()
        with self._lock:
            if self._t_last is None:
                return 0.0
            # silence decays the estimate toward 0 — read-only (the next
            # update blends from the undecayed state, which is fine: its
            # alpha covers the same silent window)
            dt = max(now - self._t_last, 0.0)
            return self._rate * 0.5 ** (dt / self.halflife)


def fmt_rate(rows_per_sec: float) -> str:
    if rows_per_sec >= 1e6:
        return f"{rows_per_sec / 1e6:.2f}M rows/s"
    if rows_per_sec >= 1e3:
        return f"{rows_per_sec / 1e3:.1f}k rows/s"
    return f"{rows_per_sec:,.0f} rows/s"


def registry_progress_line(reg: Optional[metrics.MetricsRegistry] = None
                           ) -> str:
    """One-line pipeline status assembled from the standard counters
    (OBSERVABILITY.md names) — what ``--progress`` prints."""
    r = reg if reg is not None else metrics.registry()
    rows = r.counter("tpuprof_ingest_rows_total").total()
    batches = r.counter("tpuprof_ingest_batches_total").total()
    # the <program>_batches series are batches-per-staged-dispatch
    # bookkeeping, not dispatches — same exclusion as the report footer
    disp = sum(v for k, v in
               r.counter("tpuprof_device_dispatch_total").items()
               if not any(lv.endswith("_batches") for _, lv in k))
    ckpt = r.counter("tpuprof_checkpoint_saves_total").total()
    parts = [f"{int(rows):,} rows", f"{int(batches)} batches",
             f"{int(disp)} dispatches"]
    if ckpt:
        parts.append(f"{int(ckpt)} checkpoints")
    return " · ".join(parts)


class Ticker:
    """Daemon thread driving the periodic jobs: a stderr progress line,
    a JSONL metrics snapshot, or both.  ``stop()`` is idempotent and
    joins the thread so tests never leak tickers."""

    def __init__(self, interval: float, progress: bool = False,
                 snapshots: bool = False, stream=None):
        self.interval = max(float(interval), 0.1)
        self.progress = progress
        self.snapshots = snapshots
        self.stream = stream if stream is not None else sys.stderr
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.monotonic()
        self._last_rows = 0.0
        self.stop_timed_out = False     # a stop() join that expired
        # with the tick thread still alive (e.g. a tick blocked on a
        # wedged stream) — flagged, and _tick's stop guard keeps the
        # orphan from ever printing/emitting into a closed profiler

    def _tick(self) -> None:
        if self._stop.is_set():
            # stop() may expire its join while a tick is queued behind
            # a slow write; the guard makes the orphan tick a no-op
            # instead of emitting into a finished (or closed) run
            return
        if self.snapshots:
            events.emit_snapshot(reason="interval")
        if self.progress:
            rows = metrics.registry().counter(
                "tpuprof_ingest_rows_total").total()
            dt = time.monotonic() - self._t0
            rate = (rows - self._last_rows) / self.interval
            self._last_rows = rows
            print(f"tpuprof: [{dt:7.1f}s] "
                  f"{registry_progress_line()} · {fmt_rate(rate)}",
                  file=self.stream)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception:   # a broken pipe must not kill the ticker
                return

    def start(self) -> "Ticker":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tpuprof-obs-ticker")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            if thread.is_alive():
                # the join can expire with the thread still inside a
                # blocked tick; before this flag existed the orphan
                # kept ticking into whatever came next.  The daemon
                # thread dies with the process; the _tick stop guard
                # silences it until then.
                self.stop_timed_out = True
                events.emit("ticker_stop_timeout",
                            interval=self.interval)
            self._thread = None

    def __enter__(self) -> "Ticker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
