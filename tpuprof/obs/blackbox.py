"""Always-on crash flight recorder (OBSERVABILITY.md "flight recorder").

The metrics/JSONL layer is opt-in, which means the runs that crash with
telemetry OFF — most of them — leave nothing to debug.  This module is
the black box: a small, lock-cheap ring buffer that records every obs
event (span closes, heartbeats, retries, quarantines, checkpoint
fallbacks, dispatch milestones) whether or not metrics are enabled, plus
a context card (config fingerprint, process index, last checkpoint
cursor, last heartbeat).  When a run dies — a typed error escaping the
CLI, or SIGTERM/SIGUSR1 via the handlers the CLI installs — the ring is
dumped to ``tpuprof-postmortem-<pid>.json`` so every crash leaves a
debuggable artifact.

Cost model: one deque append + dict build per event, at batch/stage
granularity (never per row).  ``TPUPROF_BLACKBOX=0`` disables recording
entirely (one attribute read per site); any other integer sets the ring
capacity (default 512 entries).

Import-light by design: no jax, no pandas — safe from every hot module.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 512
_ENV = "TPUPROF_BLACKBOX"
_ENV_DIR = "TPUPROF_POSTMORTEM_DIR"


def _env_capacity() -> int:
    """``TPUPROF_BLACKBOX``: unset/empty -> default ring; ``0`` ->
    disabled; any other integer -> that capacity."""
    raw = os.environ.get(_ENV)
    if raw in (None, ""):
        return DEFAULT_CAPACITY
    try:
        n = int(raw)
    except ValueError:
        return DEFAULT_CAPACITY
    return max(n, 0)


class BlackBox:
    """Bounded in-memory event ring + context card.  Thread-safe; every
    operation is O(1) under one lock (appends never allocate past the
    ring capacity)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(int(capacity), 0)
        self.enabled = self.capacity > 0
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity or 1)
        self._seq = 0
        self._context: Dict[str, Any] = {}

    def record(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        entry = {"seq": 0, "ts": round(time.time(), 3), "kind": kind}
        entry.update(fields)
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)

    def set_context(self, **kv: Any) -> None:
        """Merge facts into the context card dumped with the ring (config
        fingerprint, process index, last checkpoint cursor, ...)."""
        if not self.enabled:
            return
        with self._lock:
            self._context.update(kv)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> Dict[str, Any]:
        # dump-time context providers run OUTSIDE the lock (a provider
        # may take its own locks — the serve queue snapshot does) and
        # inside try/except: a postmortem must never crash on the
        # context it is trying to attach
        provided: Dict[str, Any] = {}
        for provider in list(_providers):
            try:
                extra = provider()
                if isinstance(extra, dict):
                    provided.update(extra)
            except Exception:
                provided["context_provider_error"] = repr(provider)
        with self._lock:
            entries = list(self._ring)
            context = dict(self._context)
        context.update(provided)
        return {
            "capacity": self.capacity,
            "recorded": self._seq,
            "dropped": max(self._seq - len(entries), 0),
            "context": context,
            "entries": entries,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._context.clear()
            self._seq = 0

    def dump(self, path: Optional[str] = None,
             error: Optional[BaseException] = None,
             signal_name: Optional[str] = None,
             reason: str = "crash") -> Optional[str]:
        """Write the postmortem bundle; returns the path written (None
        when disabled or unwritable — a dump must never mask the crash
        it describes)."""
        if not self.enabled:
            return None
        if path is None:
            path = os.path.join(os.environ.get(_ENV_DIR) or os.getcwd(),
                                f"tpuprof-postmortem-{os.getpid()}.json")
        bundle = self.snapshot()
        bundle.update({
            "schema": "tpuprof-postmortem-v1",
            "pid": os.getpid(),
            "ts": round(time.time(), 3),
            "reason": reason,
        })
        if error is not None:
            bundle["error"] = {"type": type(error).__name__,
                               "message": str(error)}
        if signal_name is not None:
            bundle["signal"] = signal_name
        try:
            with open(path, "w") as fh:
                # default=str: numpy scalars / paths / exceptions in ring
                # fields must never make the crash dump itself crash
                json.dump(bundle, fh, default=str, indent=1)
        except OSError:
            return None
        return path


# ---------------------------------------------------------------------------
# process-wide recorder
# ---------------------------------------------------------------------------

_box = BlackBox(_env_capacity())


def box() -> BlackBox:
    return _box


def enabled() -> bool:
    return _box.enabled


def record(kind: str, **fields: Any) -> None:
    _box.record(kind, **fields)


def set_context(**kv: Any) -> None:
    _box.set_context(**kv)


def dump_postmortem(error: Optional[BaseException] = None,
                    signal_name: Optional[str] = None,
                    reason: str = "crash",
                    path: Optional[str] = None) -> Optional[str]:
    return _box.dump(path=path, error=error, signal_name=signal_name,
                     reason=reason)


_providers: List[Any] = []      # dump-time context callables


def register_context_provider(fn) -> None:
    """Attach a callable returning a dict merged into every postmortem's
    context card AT DUMP TIME (so the snapshot is current, not a stale
    periodic copy) — e.g. the serve scheduler's live job-queue view.
    Providers must be quick and are exception-isolated."""
    if fn not in _providers:
        _providers.append(fn)


def unregister_context_provider(fn) -> None:
    try:
        _providers.remove(fn)
    except ValueError:
        pass


_installed = {"term": None, "usr1": None}   # our live handler objects


def install_signal_handlers() -> bool:
    """CLI entry hook: dump the ring on SIGTERM (then die with the
    default disposition, so wrappers still see a signal death) and on
    SIGUSR1 (dump and keep running — live inspection of a wedged
    process).  Returns False when disabled or not installable (non-main
    thread, platform without the signals).

    Idempotent and daemon-safe: a long-lived `tpuprof serve` process
    (or a wrapper calling per request) installs the handlers exactly
    ONCE — a repeat call that finds OUR handler still registered
    returns True without re-wrapping, so closures never stack and
    ``signal.getsignal`` stays stable for embedders.  If an embedding
    host replaced the dispositions since, the call installs afresh
    (the check is against the live registration, not a sticky flag)."""
    if not _box.enabled:
        return False
    import signal as _signal
    if _installed["term"] is not None \
            and _signal.getsignal(_signal.SIGTERM) is _installed["term"]:
        return True

    def _usr1(signum, frame):
        _box.record("signal", name="SIGUSR1")
        dump_postmortem(signal_name="SIGUSR1", reason="signal")

    def _term(signum, frame):
        _box.record("signal", name="SIGTERM")
        dump_postmortem(signal_name="SIGTERM", reason="signal")
        # restore the default disposition and re-raise so the exit
        # status stays "killed by SIGTERM", not a swallowed signal
        _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
        os.kill(os.getpid(), _signal.SIGTERM)

    try:
        _signal.signal(_signal.SIGTERM, _term)
        if hasattr(_signal, "SIGUSR1"):
            _signal.signal(_signal.SIGUSR1, _usr1)
    except (ValueError, OSError):
        # not the main thread, or an embedding host owns the handlers
        return False
    _installed["term"] = _term
    _installed["usr1"] = _usr1
    return True
