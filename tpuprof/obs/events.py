"""JSONL event sink (OBSERVABILITY.md "JSONL events").

One line per event, append-only, written as it happens so a crashed run
leaves a readable trace up to the crash.  Events are flat dicts with a
``kind`` discriminator:

* ``{"kind": "span", "name", "seconds", "path", "depth", ...meta}`` —
  emitted by obs/spans.py at every span exit
* ``{"kind": "metric", "name", "type", "labels", "value"|"count"/"sum"}``
  — one event per live series, emitted by :func:`emit_snapshot`
  (finalize and the ``--metrics-interval`` ticker)
* ``{"kind": "heartbeat", ...}`` — StreamingProfiler.heartbeat() /
  the CLI ``--progress`` ticker

Every event carries ``ts`` (epoch seconds).  Field values are coerced
via ``default=str`` — numpy scalars, paths and timestamps must never
crash the pipeline they observe.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional

from tpuprof.obs import metrics


class JsonlSink:
    """Thread-safe append-only JSONL writer (line-buffered)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)

    def write(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()


_lock = threading.Lock()
_sink: Optional[JsonlSink] = None


def set_sink(path: Optional[str]) -> Optional[JsonlSink]:
    """Point the process-wide sink at ``path`` (None closes it).  A
    repeated call with the sink's current path keeps it (appending),
    so configure() is idempotent across CLI + backend."""
    global _sink
    with _lock:
        if _sink is not None and (path is None or _sink.path != path):
            _sink.close()
            _sink = None
        if path is not None and _sink is None:
            _sink = JsonlSink(path)
        return _sink


def get_sink() -> Optional[JsonlSink]:
    return _sink


def emit(kind: str, **fields) -> None:
    """Write one event to the sink, if any.  Cheap no-op otherwise."""
    sink = _sink
    if sink is None:
        return
    sink.write({"ts": round(time.time(), 3), "kind": kind, **fields})


def emit_snapshot(registry: Optional[metrics.MetricsRegistry] = None,
                  reason: str = "snapshot") -> None:
    """One ``metric`` event per live series — the JSONL twin of
    ``render_text()`` (same names, same label strings)."""
    sink = _sink
    if sink is None:
        return
    reg = registry if registry is not None else metrics.registry()
    snap = reg.snapshot()
    ts = round(time.time(), 3)
    for mtype, byname in (("counter", snap["counters"]),
                          ("gauge", snap["gauges"])):
        for name, series in byname.items():
            for labels, value in series.items():
                sink.write({"ts": ts, "kind": "metric", "reason": reason,
                            "name": name, "type": mtype,
                            "labels": labels, "value": value})
    for name, series in snap["histograms"].items():
        for labels, st in series.items():
            sink.write({"ts": ts, "kind": "metric", "reason": reason,
                        "name": name, "type": "histogram",
                        "labels": labels, "count": st["count"],
                        "sum": st["sum"], "mean": st["mean"]})
