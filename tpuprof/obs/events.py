"""JSONL event sink (OBSERVABILITY.md "JSONL events").

One line per event, append-only, written as it happens so a crashed run
leaves a readable trace up to the crash.  Events are flat dicts with a
``kind`` discriminator:

* ``{"kind": "span", "name", "seconds", "path", "depth", ...meta}`` —
  emitted by obs/spans.py at every span exit
* ``{"kind": "metric", "name", "type", "labels", "value"|"count"/"sum"}``
  — one event per live series, emitted by :func:`emit_snapshot`
  (finalize and the ``--metrics-interval`` ticker)
* ``{"kind": "heartbeat", ...}`` — StreamingProfiler.heartbeat() /
  the CLI ``--progress`` ticker

Every event carries ``ts`` (epoch seconds).  Field values are coerced
via ``default=str`` — numpy scalars, paths and timestamps must never
crash the pipeline they observe.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from tpuprof.obs import blackbox, metrics


class JsonlSink:
    """Thread-safe append-only JSONL writer (line-buffered).

    ``max_bytes`` (config ``metrics_max_bytes`` /
    ``TPUPROF_METRICS_MAX_BYTES``; None/0 = unlimited) caps on-disk
    growth: when the file would exceed the cap it rotates once to
    ``path.1`` (replacing any previous rotation) and keeps appending to
    a fresh ``path`` — a week-long stream's sink is then bounded at
    ~2x max_bytes instead of filling the disk."""

    def __init__(self, path: str, max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = int(max_bytes) if max_bytes else 0
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0

    def _rotate_locked(self) -> None:
        self._fh.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass            # rotation is best-effort; appending resumes
        self._fh = open(self.path, "a", buffering=1)
        self._bytes = 0

    def write(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, default=str) + "\n"
        with self._lock:
            if self._fh.closed:
                return
            if self.max_bytes and self._bytes \
                    and self._bytes + len(line) > self.max_bytes:
                self._rotate_locked()
            self._fh.write(line)
            self._bytes += len(line)

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()


_lock = threading.Lock()
_sink: Optional[JsonlSink] = None


def set_sink(path: Optional[str],
             max_bytes: Optional[int] = None) -> Optional[JsonlSink]:
    """Point the process-wide sink at ``path`` (None closes it).  A
    repeated call with the sink's current path keeps it (appending,
    updating the growth cap), so configure() is idempotent across
    CLI + backend."""
    global _sink
    with _lock:
        if _sink is not None and (path is None or _sink.path != path):
            _sink.close()
            _sink = None
        if path is not None and _sink is None:
            _sink = JsonlSink(path, max_bytes=max_bytes)
        elif _sink is not None and max_bytes is not None:
            _sink.max_bytes = int(max_bytes) if max_bytes else 0
        return _sink


def get_sink() -> Optional[JsonlSink]:
    return _sink


def emit(kind: str, **fields) -> None:
    """Write one event to the sink, if any — and ALWAYS into the crash
    flight recorder (obs/blackbox.py), so a run with metrics off still
    leaves a ring of recent events behind a crash."""
    blackbox.record(kind, **fields)
    sink = _sink
    if sink is None:
        return
    sink.write({"ts": round(time.time(), 3), "kind": kind, **fields})


def emit_snapshot(registry: Optional[metrics.MetricsRegistry] = None,
                  reason: str = "snapshot") -> None:
    """One ``metric`` event per live series — the JSONL twin of
    ``render_text()`` (same names, same label strings)."""
    sink = _sink
    if sink is None:
        return
    reg = registry if registry is not None else metrics.registry()
    snap = reg.snapshot()
    ts = round(time.time(), 3)
    for mtype, byname in (("counter", snap["counters"]),
                          ("gauge", snap["gauges"])):
        for name, series in byname.items():
            for labels, value in series.items():
                sink.write({"ts": ts, "kind": "metric", "reason": reason,
                            "name": name, "type": mtype,
                            "labels": labels, "value": value})
    for name, series in snap["histograms"].items():
        for labels, st in series.items():
            sink.write({"ts": ts, "kind": "metric", "reason": reason,
                        "name": name, "type": "histogram",
                        "labels": labels, "count": st["count"],
                        "sum": st["sum"], "mean": st["mean"]})
