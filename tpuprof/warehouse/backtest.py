"""Alert backtesting — replay a threshold change against a retained
artifact chain (ISSUE 13 (d)).

``tpuprof backtest SOURCE --psi-threshold X`` answers the question
threshold tuning actually asks: *had the watch run with THESE bands,
which cycles would have alerted?* — without re-profiling anything.
The replay walks the retained JSON chain (``watch/<key>/
cycle_*.artifact.json``) oldest-first and re-runs exactly the live
loop's decision chain per cycle:

* the drift report from the SAME engine (``compute_drift``) against
  the SAME baseline semantics (the last readable artifact — a corrupt
  retained generation is walked past, exactly like the live baseline
  walk);
* the alert shape from the SAME definition the live loop uses
  (serve/watch.drift_alert_shape — verdict + capped flagged set);
* the SAME episode dedup (serve/watch.drift_episode_key — an ongoing
  drift with an unchanged shape alerts once, an ``ok`` cycle re-arms).

Because every rule is imported from the watch module rather than
re-derived, a backtest at the live thresholds reproduces the live
alert set exactly (tests/test_warehouse.py pins this against a real
DriftWatcher run), and a backtest at changed thresholds is exactly
what the live watch WOULD have raised.

Depth note: the replay sees what retention kept — ``artifact_keep``
generations (ARTIFACTS.md "Profile warehouse" documents the
interaction; raise ``--keep`` on sources whose thresholds you expect
to tune).
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from tpuprof.errors import CorruptArtifactError, InputError
from tpuprof.obs import blackbox
from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics

BACKTEST_SCHEMA = "tpuprof-backtest-v1"

_CYCLE_RE = re.compile(r"cycle_(\d{8})\.artifact\.json$")

_BACKTESTS = _obs_metrics.counter(
    "tpuprof_backtests_total", "alert backtests replayed")
_BACKTEST_SECONDS = _obs_metrics.histogram(
    "tpuprof_backtest_seconds",
    "wall seconds per alert backtest (chain read + drift replays)")


def chain_dir(spool: Optional[str], source: Any) -> str:
    """Resolve the retained-chain directory for ``source``: a directory
    that itself holds ``cycle_*.artifact.json`` is used as-is, else the
    watch layout under the spool (``SPOOL/watch/<source-key>``)."""
    from tpuprof.serve.watch import source_key
    text = str(source)
    if os.path.isdir(text) and _has_cycles(text):
        return text
    if not spool:
        raise InputError(
            f"{text!r} is not a retained-chain directory and no --spool "
            "was given — pass the watch daemon's spool so the chain "
            "resolves to SPOOL/watch/<source-key>/")
    return os.path.join(spool, "watch", source_key(source))


def _has_cycles(path: str) -> bool:
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return any(_CYCLE_RE.match(n) for n in names)


def chain(dirpath: str) -> List[Tuple[int, str]]:
    """Retained ``(cycle, path)`` artifacts, OLDEST first (a replay is
    a time series)."""
    out = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    for name in names:
        m = _CYCLE_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(dirpath, name)))
    return sorted(out)


def backtest(dirpath: str, thresholds) -> Dict[str, Any]:
    """Replay ``thresholds`` over the retained chain at ``dirpath``.
    Returns the ``tpuprof-backtest-v1`` document: one record per
    retained cycle plus the alert set the live watch would have
    raised under these bands."""
    from tpuprof.artifact import compute_drift, read_artifact
    from tpuprof.serve.watch import drift_alert_shape, drift_episode_key

    t0 = time.perf_counter()
    retained = chain(dirpath)
    if not retained:
        raise InputError(
            f"no retained cycle artifacts under {dirpath!r} — the "
            "watch loop has not fed this chain (or retention rotated "
            "everything away; raise --keep)")
    cycles: List[Dict[str, Any]] = []
    alerts: List[Dict[str, Any]] = []
    baseline = None                 # the last READABLE artifact
    last_key: Optional[List[Any]] = None
    for cyc, path in retained:
        try:
            current = read_artifact(path)
        except (CorruptArtifactError, OSError) as exc:
            # the live loop would have walked past this generation at
            # baseline time; at replay time it is simply unknowable
            blackbox.record("backtest_skip", path=path,
                            error=f"{type(exc).__name__}: {exc}")
            cycles.append({"cycle": cyc, "status": "unreadable",
                           "alerted": False})
            continue
        if baseline is None:
            cycles.append({"cycle": cyc, "status": "baseline",
                           "alerted": False})
            baseline = current
            continue
        drift = compute_drift(baseline, current, thresholds)
        s = drift["summary"]
        status, flagged = drift_alert_shape(drift)
        record = {"cycle": cyc, "status": status,
                  "n_drift": s["n_drift"], "n_warn": s["n_warn"],
                  "alerted": False}
        if status == "ok":
            last_key = None
        else:
            key = drift_episode_key(status, flagged)
            if key != last_key:
                record["alerted"] = True
                alerts.append({"cycle": cyc, "severity": status,
                               "columns": flagged,
                               "n_drift": s["n_drift"],
                               "n_warn": s["n_warn"]})
                last_key = key
        cycles.append(record)
        baseline = current
    seconds = time.perf_counter() - t0
    doc = {
        "schema": BACKTEST_SCHEMA,
        "chain": dirpath,
        "thresholds": thresholds.as_dict(),
        "cycles": cycles,
        "alerts": alerts,
        "summary": {
            "cycles": len(cycles),
            "alerts": len(alerts),
            "drift_cycles": sum(1 for c in cycles
                                if c.get("status") == "drift"),
            "warn_cycles": sum(1 for c in cycles
                               if c.get("status") == "warn"),
            "unreadable": sum(1 for c in cycles
                              if c.get("status") == "unreadable"),
        },
    }
    if _obs_metrics.enabled():
        _BACKTESTS.inc()
        _BACKTEST_SECONDS.observe(seconds)
        _obs_events.emit("backtest", chain=dirpath,
                         cycles=len(cycles), alerts=len(alerts),
                         seconds=round(seconds, 4))
    return doc
