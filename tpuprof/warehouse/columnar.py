"""Columnar stats files — ``tpuprof-stats-parquet-v1`` (ISSUE 13 (a)).

The JSON artifact (tpuprof/artifact/store.py) is ONE document: reading
the mean of one column out of a 10k-column profile costs parsing the
whole thing.  The warehouse twin stores the same ``variables`` numbers
as a Parquet table — one row per profiled column, one typed Parquet
column per stat — so warehouse-scale consumers column-prune: a
``["column", "mean"]`` read touches two column chunks, not the
document (the ``warehouse`` bench leg tracks the speedup).

Layout of one file:

* rows: the profile's columns, in profile order, keyed by the
  ``column`` string column; ``type`` carries the refined kind.
* stat columns: every numeric stat the export produced, as int64 when
  every present value is an integer, else float64 — the VALUES are the
  raw ``variables`` numbers bit-for-bit (the round-trip golden test
  asserts ulp-identity against the JSON artifact).
* ``hist_counts`` (list<int64>) / ``hist_edges`` (list<float64>): the
  per-column histogram sketch, so PSI/KS trend extraction
  (warehouse/history.py) never needs the JSON chain.
* file metadata: schema id, source, generation, created/rows/config
  provenance, and the CRC32 of the JSON artifact this file was derived
  from (``artifact_crc32``) — a consumer can tie any Parquet row back
  to the exact sealed document it came from.

Durability is the artifact store's contract: the Parquet bytes are
built in memory and published through ONE atomic tmp+fsync+rename seam
with a dot-prefixed temp name (ISSUE 12 durability invariant — the
warehouse directory is chain-scanned).  Every read failure — truncation
at any byte offset, a bit flip in the footer, junk, a foreign schema —
is the typed :class:`~tpuprof.errors.CorruptWarehouseError`, never a
raw pyarrow traceback.  pyarrow itself is imported lazily: an
environment without it raises the typed
:class:`~tpuprof.errors.WarehouseUnavailableError` (CLI exit code 10)
and the JSON artifact path is unaffected.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from tpuprof.errors import (CorruptWarehouseError,
                            WarehouseUnavailableError)
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.testing import faults as _faults

STATS_PARQUET_SCHEMA = "tpuprof-stats-parquet-v1"

#: metadata keys (all UTF-8 strings in the Parquet file footer)
_META_PREFIX = "tpuprof."

_WRITES = _obs_metrics.counter(
    "tpuprof_warehouse_writes_total", "columnar stats files written")
_READS = _obs_metrics.counter(
    "tpuprof_warehouse_reads_total",
    "columnar stats files read back (full or column-pruned)")
_CORRUPT = _obs_metrics.counter(
    "tpuprof_warehouse_corrupt_total",
    "columnar reads rejected by the integrity checks")
_WRITE_SECONDS = _obs_metrics.histogram(
    "tpuprof_warehouse_write_seconds",
    "wall seconds per atomic columnar write (encode + fsync + rename)")
_BYTES = _obs_metrics.gauge(
    "tpuprof_warehouse_bytes", "size of the newest columnar file written")


def import_pyarrow():
    """The lazy pyarrow gate (ISSUE 13 satellite): every warehouse
    entry point draws pyarrow through here, so a box without it gets
    ONE typed, actionable error instead of an ImportError traceback —
    and the JSON artifact path, which never calls this, is unaffected."""
    try:
        import pyarrow
        import pyarrow.parquet  # noqa: F401 — the submodule the IO uses
    except Exception as exc:
        raise WarehouseUnavailableError(
            "the columnar profile warehouse needs pyarrow, which this "
            f"environment cannot import ({type(exc).__name__}: {exc}) "
            "— install pyarrow>=16 or set warehouse_format=off "
            "(TPUPROF_WAREHOUSE_FORMAT=off); JSON artifacts are "
            "unaffected") from exc
    return pyarrow


@dataclasses.dataclass
class Generation:
    """One columnar stats file read back: provenance metadata plus the
    requested columns as plain Python dicts."""

    schema: str
    meta: Dict[str, Any]
    columns: List[str]              # profiled column names, file order
    stats: Dict[str, Dict[str, Any]]  # column -> {stat: raw value}
    path: Optional[str] = None

    @property
    def generation(self) -> int:
        return int(self.meta.get("generation") or 0)

    @property
    def created_unix(self) -> float:
        return float(self.meta.get("created_unix") or 0.0)


def _is_int(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def stat_columns(variables: Dict[str, Dict[str, Any]]) -> List[str]:
    """The union of numeric stat keys across every column, in first-
    appearance order — the file's stat column set.  Non-numeric stats
    (mode strings, the histogram tuple, nested display blobs) stay out;
    the histogram rides the dedicated ``hist_*`` list columns."""
    out: List[str] = []
    seen = set()
    for var in variables.values():
        for key, val in var.items():
            if key in seen or key.startswith("_"):
                continue
            if _is_num(val) or val is None:
                # a key that is None everywhere is undecidable; admit
                # it only once some column gives it a number
                if val is None and not any(
                        _is_num(v.get(key)) for v in variables.values()):
                    continue
                seen.add(key)
                out.append(key)
    return out


def write_stats_parquet(path: str, stats_json: Dict[str, Any],
                        sketches: Optional[Dict[str, Any]] = None, *,
                        source: Optional[str] = None,
                        generation: int = 0,
                        rows: Optional[int] = None,
                        config_fingerprint: Optional[str] = None,
                        artifact_crc32: Optional[int] = None,
                        created_unix: Optional[float] = None) -> Dict[str, Any]:
    """Write one ``tpuprof-stats-parquet-v1`` file atomically.

    ``stats_json`` is the artifact's ``stats`` section (the
    ``stats_to_json`` export — raw JSON numbers); ``sketches`` the
    artifact's ``sketches`` section (histograms feed the ``hist_*``
    columns).  Returns the metadata dict stamped into the file."""
    pa = import_pyarrow()
    import pyarrow.parquet as pq

    t0 = time.perf_counter()
    variables: Dict[str, Dict[str, Any]] = stats_json.get("variables") or {}
    names = [str(n) for n in variables]
    stats_keys = stat_columns(variables)
    hists = (sketches or {}).get("histograms") or {}

    arrays: Dict[str, Any] = {
        "column": pa.array(names, type=pa.string()),
        "type": pa.array([variables[n].get("type") for n in names],
                         type=pa.string()),
    }
    for key in stats_keys:
        vals = [variables[n].get(key) for n in names]
        vals = [v if _is_num(v) else None for v in vals]
        # int64 only when every present value is an int — a mixed
        # int/float stat must not silently truncate, and float64 holds
        # every json float bit-for-bit
        typ = pa.int64() if all(v is None or _is_int(v) for v in vals) \
            else pa.float64()
        arrays[key] = pa.array(
            [v if v is None or typ == pa.int64() else float(v)
             for v in vals], type=typ)
    arrays["hist_counts"] = pa.array(
        [[int(c) for c in (hists.get(n) or {}).get("counts") or []] or None
         for n in names], type=pa.list_(pa.int64()))
    arrays["hist_edges"] = pa.array(
        [[float(e) for e in (hists.get(n) or {}).get("edges") or []] or None
         for n in names], type=pa.list_(pa.float64()))

    meta = {
        "schema": STATS_PARQUET_SCHEMA,
        "tpuprof_version": _version(),
        "source": source,
        "generation": int(generation),
        "created_unix": round(created_unix if created_unix is not None
                              else time.time(), 3),
        "rows": int(rows) if rows is not None else None,
        "config_fingerprint": config_fingerprint,
        "artifact_crc32": artifact_crc32,
        "stat_columns": stats_keys,
    }
    table = pa.table(arrays, metadata={
        (_META_PREFIX + k).encode(): json.dumps(v).encode()
        for k, v in meta.items()})
    buf = io.BytesIO()
    pq.write_table(table, buf)
    data = _faults.mangle("warehouse_write", buf.getvalue())
    _faults.hit("warehouse_write", key=int(generation))
    _atomic_write(path, data)
    if _obs_metrics.enabled():
        _WRITES.inc()
        _WRITE_SECONDS.observe(time.perf_counter() - t0)
        _BYTES.set(len(data))
        from tpuprof.obs import events
        events.emit("warehouse_write", path=path, source=source,
                    generation=int(generation), columns=len(names),
                    bytes=len(data),
                    seconds=round(time.perf_counter() - t0, 4))
    return meta


def _atomic_write(path: str, data: bytes) -> None:
    # dot-prefixed temp (ISSUE 12 durability invariant): the warehouse
    # directory is chain-scanned (store.py GEN_RE walk), so the
    # in-flight write must be invisible to every name filter
    tmp = os.path.join(os.path.dirname(path) or ".",
                       f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)


def read_stats_parquet(path: str,
                       columns: Optional[Sequence[str]] = None,
                       stats: Optional[Sequence[str]] = None
                       ) -> Generation:
    """Read one columnar stats file, optionally column-pruned.

    ``columns`` filters the profiled-column ROWS; ``stats`` prunes
    which stat columns are materialized (the 10k-column win: a
    ``stats=["mean"]`` read touches the ``column`` and ``mean`` chunks
    only).  A genuinely missing file raises ``FileNotFoundError``
    ("never written" and "rotted" are different operator problems);
    EVERY other failure is the typed :class:`CorruptWarehouseError`."""
    import_pyarrow()
    import pyarrow.parquet as pq

    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        pf = pq.ParquetFile(path)
        raw_meta = pf.schema_arrow.metadata or {}
        meta = _decode_meta(path, raw_meta)
        read_cols = None
        if stats is not None:
            available = set(pf.schema_arrow.names)
            read_cols = ["column"] + [
                s for s in stats if s in available and s != "column"]
        table = pf.read(columns=read_cols)
    except (FileNotFoundError, CorruptWarehouseError):
        raise
    except Exception as exc:
        # pyarrow raises a zoo (ArrowInvalid, ArrowIOError, OSError,
        # ValueError) depending on WHERE the bytes are torn — one typed
        # shape for all of it, like every other store in the tree
        _mark_corrupt()
        raise CorruptWarehouseError(
            f"columnar stats file {path!r} is unreadable — truncated "
            f"or corrupt ({type(exc).__name__}: {exc})") from exc
    data = table.to_pydict()
    names = [str(n) for n in data.get("column") or []]
    keep = None if columns is None else {str(c) for c in columns}
    per_col: Dict[str, Dict[str, Any]] = {}
    for i, name in enumerate(names):
        if keep is not None and name not in keep:
            continue
        per_col[name] = {k: v[i] for k, v in data.items()
                        if k != "column"}
    if _obs_metrics.enabled():
        _READS.inc()
    return Generation(schema=STATS_PARQUET_SCHEMA, meta=meta,
                      columns=[n for n in names
                               if keep is None or n in keep],
                      stats=per_col, path=path)


def _decode_meta(path: str, raw: Dict[bytes, bytes]) -> Dict[str, Any]:
    meta: Dict[str, Any] = {}
    for k, v in raw.items():
        key = k.decode("utf-8", "replace")
        if not key.startswith(_META_PREFIX):
            continue
        try:
            meta[key[len(_META_PREFIX):]] = json.loads(v.decode())
        except ValueError:
            meta[key[len(_META_PREFIX):]] = v.decode("utf-8", "replace")
    if meta.get("schema") != STATS_PARQUET_SCHEMA:
        _mark_corrupt()
        raise CorruptWarehouseError(
            f"columnar stats file {path!r} has schema "
            f"{meta.get('schema')!r}; this build reads "
            f"{STATS_PARQUET_SCHEMA!r}")
    return meta


def _mark_corrupt() -> None:
    _CORRUPT.inc()
    from tpuprof.obs import blackbox
    blackbox.record("warehouse_corrupt")


def _version() -> str:
    from tpuprof import __version__
    return __version__
