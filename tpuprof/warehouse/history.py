"""History queries over a per-source warehouse directory (ISSUE 13 (b)+(c)).

``tpuprof history SOURCE --stat mean --col price`` answers "how has
this column's mean moved across every profiled generation" from the
append-only columnar chain the watch loop feeds — column-pruned reads
(only the ``column`` + requested stat chunks materialize), corrupt
generations walked past the way checkpoint restore walks its chain
(counted on ``tpuprof_warehouse_fallbacks_total``, never a raw
traceback, never a silently shortened series without the skip being
reported).

``--trend`` extracts drift-over-time: PSI/KS between every consecutive
pair of readable generations, computed by the existing
``tpuprof-drift-v1`` engine's statistics (artifact/drift.py
``psi_statistic``/``ks_statistic``) from the histogram sketches each
generation carries as ``hist_counts``/``hist_edges`` list columns —
the warehouse needs no JSON artifact to answer, so the trend reaches
past the rotated ``artifact_keep`` window.

Both answer shapes are one JSON document, schema
``tpuprof-history-v1`` — the same document ``GET /v1/history/<key>``
serves off the HTTP edge (serve/http.py).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from tpuprof.errors import CorruptWarehouseError, InputError
from tpuprof.obs import blackbox
from tpuprof.obs import events as _obs_events
from tpuprof.obs import metrics as _obs_metrics
from tpuprof.warehouse import columnar, store

HISTORY_SCHEMA = "tpuprof-history-v1"

_QUERIES = _obs_metrics.counter(
    "tpuprof_history_queries_total",
    "warehouse history queries by kind (stat|trend|columns)")
_QUERY_SECONDS = _obs_metrics.histogram(
    "tpuprof_history_query_seconds",
    "wall seconds per history query (chain scan + pruned reads)")
_FALLBACKS = _obs_metrics.counter(
    "tpuprof_warehouse_fallbacks_total",
    "history scans that walked past a corrupt warehouse generation")


def _walk(dirpath: str, columns: Optional[List[str]],
          stats: Optional[List[str]]):
    """Yield ``(generation, Generation|None)`` oldest-first, replacing
    each unreadable file with ``None`` after counting the fallback —
    the caller decides whether a hole is a skip (stat series) or a
    broken pair (trend)."""
    for gen, path in store.chain(dirpath):
        try:
            yield gen, columnar.read_stats_parquet(
                path, columns=columns, stats=stats)
        except (CorruptWarehouseError, OSError) as exc:
            _FALLBACKS.inc()
            blackbox.record("warehouse_fallback", path=path,
                            error=f"{type(exc).__name__}: {exc}")
            yield gen, None


def query_stat(dirpath: str, col: str, stat: str) -> Dict[str, Any]:
    """One column's one stat across every readable generation."""
    t0 = time.perf_counter()
    series: List[Dict[str, Any]] = []
    skipped: List[int] = []
    total = 0
    for gen, g in _walk(dirpath, [col], ["column", stat]):
        total += 1
        if g is None:
            skipped.append(gen)
            continue
        var = g.stats.get(col)
        series.append({
            "generation": gen,
            "created_unix": g.created_unix,
            "rows": g.meta.get("rows"),
            "value": None if var is None else var.get(stat),
        })
    if total == 0:
        raise InputError(
            f"no warehouse generations under {dirpath!r} — nothing "
            "profiled into this warehouse yet (the watch loop feeds "
            "it; one-shot writes need --warehouse-dir)")
    doc = _doc(dirpath, kind="stat", col=col, stat=stat, series=series,
               skipped=skipped)
    _observe("stat", dirpath, len(series), time.perf_counter() - t0)
    return doc


def query_trend(dirpath: str, col: Optional[str] = None
                ) -> Dict[str, Any]:
    """PSI/KS between every consecutive pair of readable generations —
    per column, or for one named column.  A corrupt generation breaks
    its pairs exactly as a corrupt watch artifact would: the next
    readable generation compares against the last readable one (the
    baseline-walk semantics)."""
    t0 = time.perf_counter()
    from tpuprof.artifact.drift import ks_statistic, psi_statistic
    cols = [col] if col else None
    series: List[Dict[str, Any]] = []
    skipped: List[int] = []
    prev = None             # (generation, Generation) — last readable
    total = 0
    for gen, g in _walk(dirpath, cols, ["column", "hist_counts",
                                        "hist_edges"]):
        total += 1
        if g is None:
            skipped.append(gen)
            continue
        if prev is not None:
            pgen, pg = prev
            entry: Dict[str, Any] = {
                "generation": gen, "baseline_generation": pgen,
                "created_unix": g.created_unix, "columns": {}}
            for name in g.columns:
                pvar = pg.stats.get(name)
                var = g.stats.get(name)
                if pvar is None or var is None:
                    continue
                ha = _hist(pvar)
                hb = _hist(var)
                if ha is None or hb is None:
                    continue
                psi = psi_statistic(ha, hb)
                ks = ks_statistic(ha, hb)
                entry["columns"][name] = {
                    "psi": round(psi, 6) if psi is not None else None,
                    "ks": round(ks, 6) if ks is not None else None,
                }
            series.append(entry)
        prev = (gen, g)
    if total == 0:
        raise InputError(
            f"no warehouse generations under {dirpath!r} — nothing "
            "profiled into this warehouse yet")
    doc = _doc(dirpath, kind="trend", col=col, stat=None, series=series,
               skipped=skipped)
    _observe("trend", dirpath, len(series), time.perf_counter() - t0)
    return doc


def query_columns(dirpath: str, cols: List[str],
                  stats: List[str],
                  on_corrupt=None) -> Optional[Dict[str, Any]]:
    """The NEWEST readable generation's values for a column/stat subset
    — the warehouse leg of ``POST /v1/query`` pushdown (ISSUE 16 (c)).

    Walks the chain newest-first so the freshest answer wins; a corrupt
    head generation demotes to the next readable one exactly like the
    stat-series walk (counted, blackboxed, never a raw traceback).
    Column-pruned: only the ``column`` chunk plus the requested stat
    chunks materialize, so a two-stat probe of a wide profile reads
    kilobytes, not the whole Parquet file.

    Returns ``None`` when no generation is readable (the caller falls
    through to the computed tier); otherwise a dict with
    ``generation``/``created_unix``/``rows``/``columns``/``missing``,
    where ``missing`` lists requested columns this generation never
    profiled — a non-empty list also sends the caller to the computed
    tier, since the warehouse cannot answer the whole question.

    ``on_corrupt(path, exc)`` is invoked for every corrupt/unreadable
    generation the walk skips — the HTTP edge's circuit breaker
    (ISSUE 19) counts these to decide when this source's warehouse
    reads stop being worth the disk tax."""
    t0 = time.perf_counter()
    for gen, path in reversed(store.chain(dirpath)):
        try:
            g = columnar.read_stats_parquet(
                path, columns=list(cols),
                stats=["column"] + [s for s in stats if s != "column"])
        except (CorruptWarehouseError, OSError) as exc:
            _FALLBACKS.inc()
            blackbox.record("warehouse_fallback", path=path,
                            error=f"{type(exc).__name__}: {exc}")
            if on_corrupt is not None:
                on_corrupt(path, exc)
            continue
        columns: Dict[str, Any] = {}
        missing: List[str] = []
        for col in cols:
            var = g.stats.get(col)
            if var is None:
                missing.append(col)
                continue
            columns[col] = {s: var.get(s) for s in stats}
        _observe("columns", dirpath, 1, time.perf_counter() - t0)
        return {
            "generation": gen,
            "created_unix": g.created_unix,
            "rows": g.meta.get("rows"),
            "columns": columns,
            "missing": missing,
        }
    return None


def _hist(var: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    counts, edges = var.get("hist_counts"), var.get("hist_edges")
    if not counts or not edges:
        return None
    return {"counts": counts, "edges": edges}


def _doc(dirpath: str, *, kind: str, col, stat, series, skipped
         ) -> Dict[str, Any]:
    return {
        "schema": HISTORY_SCHEMA,
        "kind": kind,
        "warehouse": dirpath,
        "col": col,
        "stat": stat,
        "generations": len(series),
        "skipped_corrupt": skipped,
        "series": series,
    }


def _observe(kind: str, dirpath: str, generations: int,
             seconds: float) -> None:
    if _obs_metrics.enabled():
        _QUERIES.inc(kind=kind)
        _QUERY_SECONDS.observe(seconds)
        _obs_events.emit("history_query", kind=kind, warehouse=dirpath,
                         generations=generations,
                         seconds=round(seconds, 4))
