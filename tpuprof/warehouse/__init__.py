"""tpuprof/warehouse — the profile warehouse (ISSUE 13; ROADMAP item 2).

Turns profile artifacts from single documents into a queryable
time-series with four pillars:

* warehouse/columnar.py  — ``tpuprof-stats-parquet-v1``: one row per
                           profiled column per generation, stats as
                           typed Parquet columns, histogram sketches as
                           list columns, schema/CRC provenance in the
                           file metadata; column-pruned reads are the
                           10k-column win the JSON document cannot give.
* warehouse/store.py     — the append-only per-source generation
                           directory (``<warehouse_dir>/<source-key>/
                           gen_<n>.stats.parquet``) the watch loop
                           feeds and ``--artifact`` writes alongside.
* warehouse/history.py   — ``tpuprof history SOURCE --stat mean --col
                           price`` / ``--trend``: stat series and
                           PSI/KS-over-time from the columnar chain,
                           corrupt generations walked past; also served
                           as ``GET /v1/history/<key>`` off the HTTP
                           edge.
* warehouse/backtest.py  — ``tpuprof backtest SOURCE --psi-threshold
                           X``: replay changed alert bands against the
                           retained JSON chain with the live watch
                           loop's own decision rules.

pyarrow is imported lazily (columnar.import_pyarrow): an environment
without it gets the typed
:class:`~tpuprof.errors.WarehouseUnavailableError` (CLI exit code 10)
and the JSON artifact path is unaffected.  See ARTIFACTS.md "Profile
warehouse" for the schema and layout, OBSERVABILITY.md for the
``tpuprof_warehouse_*`` / ``tpuprof_history_*`` series.
"""

from tpuprof.warehouse.backtest import (BACKTEST_SCHEMA, backtest,
                                        chain_dir)
from tpuprof.warehouse.columnar import (STATS_PARQUET_SCHEMA, Generation,
                                        import_pyarrow,
                                        read_stats_parquet,
                                        write_stats_parquet)
from tpuprof.warehouse.history import (HISTORY_SCHEMA, query_stat,
                                       query_trend)
from tpuprof.warehouse.store import (append_artifact, append_generation,
                                     chain, generation_path, source_dir)

__all__ = [
    "BACKTEST_SCHEMA", "Generation", "HISTORY_SCHEMA",
    "STATS_PARQUET_SCHEMA", "append_artifact", "append_generation",
    "backtest", "chain", "chain_dir", "generation_path",
    "import_pyarrow", "query_stat", "query_trend", "read_stats_parquet",
    "source_dir", "write_stats_parquet",
]
