"""The append-only per-source warehouse directory (ISSUE 13 (b)).

Layout under a warehouse root::

    <warehouse_dir>/<source-key>/gen_<n>.stats.parquet

where ``<source-key>`` is the watch layer's stable per-source name
(serve/watch.source_key — basename + short path hash), so a watch
spool's warehouse and its retained JSON chains key identically and
``tpuprof history SOURCE`` resolves the same directory the watch loop
fed.

The directory is APPEND-ONLY: the JSON artifact chain rotates at
``artifact_keep`` generations (it carries fold state and full
sketches — heavy), but one columnar row-set per generation is cheap,
so the warehouse keeps the whole history.  That asymmetry is the point:
``tpuprof history`` answers over every generation ever profiled while
the JSON chain stays a small hot window (ARTIFACTS.md "Profile
warehouse").

Generation numbers are assigned by the writer (the watch loop passes
its cycle counter; one-shot ``--artifact`` writes take max+1), padded
to 8 digits so lexical order is numeric order.  Scans filter through
:data:`GEN_RE`, so a dot-prefixed in-flight temp can never be read
(ISSUE 12 durability invariant).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

from tpuprof.warehouse import columnar

GEN_RE = re.compile(r"gen_(\d{8})\.stats\.parquet$")


def source_dir(warehouse_dir: str, source: Any) -> str:
    """The per-source directory for ``source``: an existing directory
    whose basename already IS a warehouse key (or that directly holds
    ``gen_*`` files) is used as-is, else the watch-layer key of the
    source path is appended to the root."""
    from tpuprof.serve.watch import source_key
    text = str(source)
    if os.path.isdir(text) and _has_generations(text):
        return text
    candidate = os.path.join(warehouse_dir, text)
    if os.path.isdir(candidate) and _has_generations(candidate):
        return candidate
    return os.path.join(warehouse_dir, source_key(source))


def _has_generations(path: str) -> bool:
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return any(GEN_RE.match(n) for n in names)


def chain(dirpath: str) -> List[Tuple[int, str]]:
    """Retained ``(generation, path)`` files, OLDEST first (history is
    a time series; the watch chain walks newest-first because it wants
    a baseline, not a series)."""
    out = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    for name in names:
        m = GEN_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(dirpath, name)))
    return sorted(out)


def generation_path(dirpath: str, generation: int) -> str:
    return os.path.join(dirpath, f"gen_{generation:08d}.stats.parquet")


def append_generation(warehouse_dir: str, source: Any,
                      stats_json: Dict[str, Any],
                      sketches: Optional[Dict[str, Any]] = None, *,
                      generation: Optional[int] = None,
                      rows: Optional[int] = None,
                      config_fingerprint: Optional[str] = None,
                      artifact_crc32: Optional[int] = None,
                      created_unix: Optional[float] = None) -> str:
    """Append one generation for ``source`` and return its path.  With
    no explicit ``generation`` the next number after the newest on disk
    is taken (the one-shot ``--artifact`` path); the watch loop passes
    its cycle counter so warehouse generations and watch cycles share a
    number line."""
    columnar.import_pyarrow()       # gate BEFORE any filesystem effect:
                                    # a pyarrow-less box must not even
                                    # litter an empty per-source dir
    d = os.path.join(warehouse_dir,
                     _key(source))
    os.makedirs(d, exist_ok=True)
    if generation is None:
        existing = chain(d)
        generation = (existing[-1][0] + 1) if existing else 1
    path = generation_path(d, int(generation))
    columnar.write_stats_parquet(
        path, stats_json, sketches, source=str(source),
        generation=int(generation), rows=rows,
        config_fingerprint=config_fingerprint,
        artifact_crc32=artifact_crc32, created_unix=created_unix)
    return path


def append_artifact(warehouse_dir: str, artifact, *,
                    source: Any = None,
                    generation: Optional[int] = None) -> str:
    """Append a generation derived from an already-read JSON artifact
    (the watch cycle path: the artifact was just validated + admitted
    to the chain, so its sections are trusted).  ``artifact.crc32`` —
    the verified integrity envelope — becomes the file's provenance
    token."""
    cfg = (artifact.meta.get("config") or {})
    return append_generation(
        warehouse_dir,
        source if source is not None
        else artifact.meta.get("source") or artifact.path,
        artifact.stats, artifact.sketches, generation=generation,
        rows=artifact.rows,
        config_fingerprint=cfg.get("fingerprint"),
        artifact_crc32=artifact.crc32,
        created_unix=artifact.meta.get("created_unix"))


def _key(source: Any) -> str:
    from tpuprof.serve.watch import source_key
    return source_key(source)
