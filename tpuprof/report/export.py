"""Machine-readable export of the full stats dict.

SURVEY §1 calls the stats dict "the single most important compatibility
requirement"; the JSON export must therefore carry EVERY top-level key
of the contract (table, variables, freq, correlations, messages,
sample), not just table+variables (VERDICT r4 #5 — a computed Spearman
matrix appeared in the HTML but was dropped from ``--stats-json``).

Schema ``tpuprof-stats-v1`` (round-5 VERDICT #2): EVERY value in
``table``/``variables`` is its raw machine form — floats stay floats
(non-finite → null, JSON has no NaN), counts stay ints, nulls are
``null``, timestamps become ISO strings.  The human formatter output
those sections carried through v0.5 (``"distinct_count": "24,449"``)
is demoted to a parallel ``display`` section with the same key layout,
so dashboards keep their strings while every downstream consumer parses
numbers.  The ``schema`` key pins the contract; tests/test_artifact.py
golden-tests it.
"""

from __future__ import annotations

import math
from datetime import datetime, timedelta
from typing import Any, Dict

import numpy as np
import pandas as pd

from tpuprof.report.formatters import fmt_value


def json_scalar(value: Any) -> Any:
    """One value → its JSON-safe raw form (no human formatting)."""
    if value is None or value is pd.NaT:
        return None
    if isinstance(value, (tuple, list)):
        # e.g. a CORR message's (partner_column, rho) — keep it structured
        return [json_scalar(v) for v in value]
    if isinstance(value, (bool, np.bool_)):
        return bool(value)
    if isinstance(value, (int, np.integer)):
        return int(value)
    if isinstance(value, (float, np.floating)):
        value = float(value)
        return value if math.isfinite(value) else None
    if isinstance(value, (pd.Timestamp, datetime, np.datetime64)):
        return str(pd.Timestamp(value))
    if isinstance(value, (pd.Timedelta, timedelta, np.timedelta64)):
        return str(pd.Timedelta(value))
    return str(value)


def _corr_entry(matrix: pd.DataFrame) -> Dict[str, Any]:
    cols = [str(c) for c in matrix.columns]
    return {
        "columns": cols,
        "matrix": {str(r): {str(c): json_scalar(matrix.loc[r, c])
                            for c in matrix.columns}
                   for r in matrix.index},
        # sample-estimate Spearman (single-pass/streaming) flags itself;
        # exact matrices carry approx=False so consumers need no default
        "approx": bool(matrix.attrs.get("approx", False)),
    }


# the export contract version: raw-number table/variables with the
# parallel display section.  Bump ONLY on breaking layout changes; the
# stats-artifact store (tpuprof/artifact) embeds this id and refuses
# schemas it does not read.
SCHEMA_ID = "tpuprof-stats-v1"


def stats_to_json(stats: Dict[str, Any]) -> Dict[str, Any]:
    """The complete stats dict as a ``json.dump``-ready structure."""
    # histograms are render-layer artifacts (bin arrays feeding the
    # SVG), not column statistics — same exclusion as since v0.1
    var_items = {
        name: {k: v for k, v in var.items()
               if k not in ("histogram", "mini_histogram")}
        for name, var in stats["variables"].items()}
    out: Dict[str, Any] = {
        "schema": SCHEMA_ID,
        "table": {k: json_scalar(v) for k, v in stats["table"].items()},
        "variables": {
            name: {k: json_scalar(v) for k, v in var.items()}
            for name, var in var_items.items()},
        # the human-formatted twins of table/variables (thousands
        # separators, ∞/NaN glyphs) — what those sections carried
        # before v1 demoted them; key layout mirrors the raw sections
        "display": {
            "table": {k: fmt_value(v) for k, v in stats["table"].items()},
            "variables": {
                name: {k: fmt_value(v) for k, v in var.items()}
                for name, var in var_items.items()},
        },
        "freq": {
            str(col): [{"value": json_scalar(idx), "count": int(cnt)}
                       for idx, cnt in vc.items()]
            for col, vc in stats.get("freq", {}).items()},
        "correlations": {
            str(method): _corr_entry(matrix)
            for method, matrix in stats.get("correlations", {}).items()},
        "messages": [
            {**m.to_dict(), "value": json_scalar(m.value)}
            for m in stats.get("messages", ())],
    }
    if stats.get("_quarantine"):
        # degraded runs only (ROBUSTNESS.md): the skipped-batch manifest
        # rides the JSON export so automation can react without
        # scraping the HTML banner
        out["quarantine"] = [
            {k: json_scalar(v) if not isinstance(v, (list, type(None)))
             else v for k, v in e.items()}
            for e in stats["_quarantine"]]
    sample = stats.get("sample")
    if sample is None:
        out["sample"] = {"columns": [], "rows": []}
    else:
        # an empty source still names its columns — only rows go empty
        out["sample"] = {
            "columns": [str(c) for c in sample.columns],
            "rows": [[json_scalar(v) for v in row]
                     for row in sample.itertuples(index=False, name=None)],
        }
    return out
