"""stats dict → HTML.

Reference: base.to_html() + templates.template() (SURVEY.md §2.1): per-row-
type template dispatch, freq-table and histogram fragment assembly, wrapped
by base.html for ``to_file`` (SURVEY §3.2).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jinja2
from markupsafe import Markup

from tpuprof.config import ProfilerConfig
from tpuprof.report import formatters, svg

_TEMPLATE_DIR = os.path.join(os.path.dirname(__file__), "templates")


def _alert_if(value, threshold) -> str:
    return formatters.alert_class(value, threshold)


def _abs_alert_if(value, threshold) -> str:
    try:
        return formatters.alert_class(abs(float(value)), threshold)
    except (TypeError, ValueError):
        return ""


def _corr_cell(rho) -> str:
    try:
        return svg.corr_cell_style(float(rho))
    except (TypeError, ValueError):
        return ""


def _env() -> jinja2.Environment:
    env = jinja2.Environment(
        loader=jinja2.FileSystemLoader(_TEMPLATE_DIR),
        autoescape=jinja2.select_autoescape(["html"]),
    )
    env.filters.update({
        "fmt": formatters.fmt_value,
        "pct": formatters.fmt_percent,
        "bytesize": formatters.fmt_bytesize,
        "alert_if": _alert_if,
        "abs_alert_if": _abs_alert_if,
        "histogram_svg": lambda h: Markup(svg.histogram_svg(h)),
        "mini_histogram_svg":
            lambda h: Markup(svg.histogram_svg(h, mini=True)),
        "freq_bar": lambda f: Markup(svg.bar_svg(f)),
        "corr_cell": _corr_cell,
    })
    return env


_ENV = None


def _get_env() -> jinja2.Environment:
    global _ENV
    if _ENV is None:
        _ENV = _env()
    return _ENV


def _perf_line(stats: Dict[str, Any]) -> str:
    """Report-footer observability (SURVEY §5): per-phase wall-clock +
    throughput of the scan that produced this stats dict (the backend
    snapshots its phase timings onto ``stats['_phases']``; absent — CPU
    oracle, streaming snapshots — the footer is simply omitted)."""
    phases = stats.get("_phases") or {}
    scan = sum(v for k, v in phases.items() if k.startswith("scan"))
    if not scan:
        return ""
    n = stats["table"]["n"]
    parts = [f"{k} {v:.2f}s" for k, v in sorted(phases.items())]
    return f"{n / scan:,.0f} rows/s · " + " · ".join(parts)


def _pipeline_stats_line(stats: Dict[str, Any]) -> str:
    """Second footer line: pipeline counters from the obs snapshot the
    backend attached as ``stats['_obs']`` (metrics enabled only —
    OBSERVABILITY.md).  Everything here degrades to omission: a missing
    metric simply drops its fragment."""
    snap = stats.get("_obs") or {}
    counters = snap.get("counters") or {}
    hists = snap.get("histograms") or {}

    def _total(name: str) -> float:
        return sum((counters.get(name) or {}).values())

    parts = []
    rows = _total("tpuprof_ingest_rows_total")
    if rows:
        parts.append(f"{int(rows):,} rows ingested")
    batches = _total("tpuprof_ingest_batches_total")
    if batches:
        parts.append(f"{int(batches)} batches prepared")
    disp = counters.get("tpuprof_device_dispatch_total") or {}
    n_disp = sum(v for k, v in disp.items() if "_batches" not in k)
    if n_disp:
        parts.append(f"{int(n_disp)} device dispatches")
    paths = counters.get("tpuprof_prep_numeric_path_total") or {}
    zc = sum(v for k, v in paths.items() if "zero_copy" in k)
    total_paths = sum(paths.values())
    if total_paths:
        parts.append(f"{zc / total_paths:.0%} zero-copy decodes")
    ck = hists.get("tpuprof_checkpoint_save_seconds") or {}
    saves = sum(s["count"] for s in ck.values())
    if saves:
        secs = sum(s["sum"] for s in ck.values())
        parts.append(f"{int(saves)} checkpoints ({secs:.2f}s)")
    gauges = snap.get("gauges") or {}
    devmem = gauges.get("tpuprof_device_memory_bytes") or {}
    in_use = sum(v for k, v in devmem.items() if 'kind="in_use"' in k)
    if in_use:
        frag = f"{formatters.fmt_bytesize(in_use)} device mem in use"
        limit = sum(v for k, v in devmem.items() if 'kind="limit"' in k)
        if limit:
            frag += f" ({in_use / limit:.0%} of limit)"
        parts.append(frag)
    rss = sum((gauges.get("tpuprof_host_rss_bytes") or {}).values())
    if rss:
        parts.append(f"{formatters.fmt_bytesize(rss)} host rss")
    return " · ".join(parts)


def _quarantine_rows(stats: Dict[str, Any]):
    """Degraded-run manifest for the banner (ROBUSTNESS.md): one row
    per skipped batch, pre-formatted so the template stays dumb.  The
    ``_quarantine`` key exists ONLY on degraded runs — clean-run HTML
    is byte-identical to a build without the banner."""
    rows = []
    for e in stats.get("_quarantine") or []:
        pos = e.get("frag_pos")
        rows.append({
            "site": e.get("site", "?"),
            "cursor": "—" if e.get("cursor") is None else e["cursor"],
            "rows": "?" if e.get("rows") is None else f"{e['rows']:,}",
            "pos": f"frag {pos[0]} batch {pos[1]}" if pos else "—",
            "error": str(e.get("error", ""))[:300],
        })
    return rows


def to_html(stats: Dict[str, Any], config: ProfilerConfig) -> str:
    """Render the report fragment (reference: ProfileReport.html)."""
    from tpuprof import __version__
    template = _get_env().get_template("report.html")
    return template.render(
        table=stats["table"],
        variables=stats["variables"],
        freq=stats["freq"],
        correlations=stats["correlations"],
        messages=stats["messages"],
        sample=stats.get("sample"),
        config=config,
        version=__version__,
        perf=_perf_line(stats),
        pipeline_stats=_pipeline_stats_line(stats),
        quarantine=_quarantine_rows(stats),
    )


def to_standalone_html(stats: Dict[str, Any], config: ProfilerConfig,
                       title: str = "tpuprof report") -> str:
    """Wrap the fragment with the standalone page shell (reference:
    to_file's base.html wrapper, SURVEY §3.2)."""
    from tpuprof import __version__
    fragment = to_html(stats, config)
    template = _get_env().get_template("base.html")
    return template.render(
        title=title, version=__version__, content=Markup(fragment)).lstrip()
