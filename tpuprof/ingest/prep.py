"""Shared thread pools for parallel host-side batch prep.

Three tiers, three pools, no nesting:

* the **column pool** runs the intra-batch leaf tasks of
  ``prepare_batch`` — per-column decode/hash/pack, and per-row-chunk
  subtasks for wide numeric planes.  Leaf tasks never submit work, so
  any number of concurrent prepares can share one pool without a
  saturation deadlock.  Sized by :func:`tpuprof.config.resolve_prep_workers`.
* the **batch pool** runs whole-batch prepares for the ordered
  cross-batch pipelines (``prefetch_prepared``, the streaming drain).
  Batch tasks DO fan out — onto the column pool, never onto their own —
  so the two tiers form a DAG and cannot wait on themselves.
* the **io pool** runs background disk writes — today the exact-unique
  tracker's spill-run ``tofile`` (kernels/unique.py), ~800 MB at the
  wide exact-distinct shape — so they hide under the device scan and
  the next batch's prepare instead of stalling the fold thread.  IO
  tasks are leaves (they never submit work) and wait on disk, not the
  GIL, so the tier helps even on a one-core host.  Callers bound their
  own in-flight window and settle futures in order, mirroring the
  ordered batch pipeline.

All pools are process-wide and lazily built: spawning threads per batch
costs more than the work they'd overlap at small shapes, and the hot
paths (Arrow decode, numpy casts/copies, the native xxh64 hash+pack,
``ndarray.tofile``) all release the GIL, so shared pools keep the
host's cores busy without thread thrash.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

from tpuprof.obs import metrics

_LOCK = threading.Lock()

# per-worker leaf-task counts: worker="serial" is the in-caller fallback
# path, pool threads report under their tpuprof-col-N names — a skewed
# spread means the task split is leaving cores idle
_PREP_TASKS = metrics.counter(
    "tpuprof_prep_tasks_total",
    "intra-batch prep leaf tasks executed, by worker thread")
_BATCH_TASKS = metrics.counter(
    "tpuprof_prep_batch_tasks_total",
    "whole-batch prepares run through the ordered cross-batch pipeline")
_IO_TASKS = metrics.counter(
    "tpuprof_prep_io_tasks_total",
    "background disk-write tasks (unique-spill runs) run on the io tier")
_COL_POOL: Optional[ThreadPoolExecutor] = None
_COL_WORKERS = 0
_BATCH_POOL: Optional[ThreadPoolExecutor] = None
_BATCH_WORKERS = 0
_IO_POOL: Optional[ThreadPoolExecutor] = None
_IO_WORKERS = 0


def _shared(kind: str, workers: int) -> ThreadPoolExecutor:
    """The shared pool of one tier, grown (never shrunk) to ``workers``.
    A replaced pool drains its queued tasks before dying — futures from
    it stay valid, so a grow mid-pipeline loses nothing."""
    global _COL_POOL, _COL_WORKERS, _BATCH_POOL, _BATCH_WORKERS, \
        _IO_POOL, _IO_WORKERS
    with _LOCK:
        if kind == "col":
            if _COL_POOL is None or _COL_WORKERS < workers:
                _COL_POOL = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="tpuprof-col")
                _COL_WORKERS = workers
            return _COL_POOL
        if kind == "io":
            if _IO_POOL is None or _IO_WORKERS < workers:
                _IO_POOL = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="tpuprof-io")
                _IO_WORKERS = workers
            return _IO_POOL
        if _BATCH_POOL is None or _BATCH_WORKERS < workers:
            _BATCH_POOL = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="tpuprof-batch")
            _BATCH_WORKERS = workers
        return _BATCH_POOL


def submit_io(fn: Callable[[], object], workers: int):
    """Queue one background disk-write leaf task on the io tier and
    return its Future.  The caller owns completion policy: bound the
    in-flight window, settle futures oldest-first (in-order completion,
    like ``ordered_map``), and translate a raised OSError into its own
    failure semantics — the pool never swallows one."""

    def _counted():
        out = fn()
        _IO_TASKS.inc(worker=threading.current_thread().name)
        return out

    return _shared("io", max(int(workers), 1)).submit(_counted)


def run_tasks(tasks: Sequence[Callable[[], None]], workers: int) -> None:
    """Run intra-batch leaf tasks, on the column pool when it helps.

    Tasks write into disjoint output slices, so completion order is
    irrelevant to the result — the caller's planes are byte-identical
    at any width.  All tasks are awaited even on failure (a late writer
    into a freed plane would corrupt a NEIGHBORING batch); the first
    exception in submission order re-raises, matching what the serial
    loop would have raised first."""
    if workers <= 1 or len(tasks) <= 1:
        for t in tasks:
            t()
        _PREP_TASKS.inc(len(tasks), worker="serial")
        return

    def _counted(t: Callable[[], None]) -> None:
        t()
        # after the task body: a raising task still re-raises below, and
        # the count means "completed work", not "attempts"
        _PREP_TASKS.inc(worker=threading.current_thread().name)

    futs = [_shared("col", workers).submit(_counted, t) for t in tasks]
    first: Optional[BaseException] = None
    for f in futs:
        try:
            f.result()
        except BaseException as exc:    # noqa: BLE001 — re-raised below
            if first is None:
                first = exc
    if first is not None:
        raise first


def ordered_map(items: Iterable, fn: Callable, workers: int,
                depth: int = 2) -> Iterator:
    """Map ``fn`` over ``items`` on the batch pool with IN-ORDER
    delivery: up to ``depth`` results are in flight ahead of the
    consumer, so prep for item N+1 overlaps whatever the consumer does
    with item N (a device fold, typically).  ``workers <= 1`` runs
    serially — the degenerate case is exactly a for loop.

    Unlike ``prefetch_prepared`` this is for a KNOWN worklist (e.g. the
    streaming drain's device-batch slices); the enumeration itself is
    assumed cheap and runs in the caller's thread."""
    if workers <= 1:
        for it in items:
            yield fn(it)
            _BATCH_TASKS.inc(worker="serial")
        return
    pool = _shared("batch", workers)
    pending: List = []
    depth = max(depth, 1)

    def _counted(it):
        out = fn(it)
        _BATCH_TASKS.inc(worker=threading.current_thread().name)
        return out

    try:
        for it in items:
            pending.append(pool.submit(_counted, it))
            while len(pending) > depth:
                yield pending.pop(0).result()
        while pending:
            yield pending.pop(0).result()
    finally:
        for f in pending:       # consumer bailed: don't leak queued work
            f.cancel()
