"""Host-side mergeable uniform row sample (bottom-k priority sampling).

This is the quantile/mode/sample-MAD sketch of the profile.  It began
life as a device sketch (kernels/quantiles.py, removed once this module
superseded it), but the selection is driven ONLY by i.i.d. uniform
priorities, never by the data, so it can run wherever the rows already
are.  During ingestion the rows are in host RAM on their way to the
device; sampling them there costs one vectorized RNG draw + a rare row
gather per batch and removes the single most expensive op (a
(cols, K+rows) top_k) from the device scan entirely.

Semantics and bounds: keeping the global top-K priorities over any
partition of the stream is a uniform random sample without replacement,
so

    merge(sample(A), sample(B)) = top-K(concat)  ≡  sample(A ∪ B)

exactly in distribution, and sample quantiles have rank error
O(1/sqrt(K)).  Priorities are per ROW: the kept rows carry ALL numeric
columns' values (NaN/±inf included); per column the finite subset of a
uniform row sample is a uniform sample of that column's finite values.
A column that is mostly missing therefore keeps ~K·(1-p_missing)
values — its rank error grows accordingly (documented tier; columns
with n ≤ K are still exact because every row is kept).

Multi-host: each process samples its own fragment stripe with an
independent RNG stream (seed ⊕ process ⊕ step); the final merge is one
DCN object gather (runtime/distributed.merge_samplers).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


class RowSampler:
    """Mergeable bottom-k priority row sample, host-resident."""

    def __init__(self, k: int, n_num: int, seed: int = 0,
                 process_index: int = 0):
        self.k = int(k)
        self.n_num = int(n_num)
        self.seed = int(seed)
        self.process_index = int(process_index)
        self.values = np.empty((0, n_num), dtype=np.float32)
        self.prio = np.empty((0,), dtype=np.float64)
        self.step = 0                        # batches folded (RNG position)

    # -- ingestion ---------------------------------------------------------

    def update(self, x: np.ndarray, nrows: int) -> None:
        """Fold one host batch.  ``x``: (>=nrows, n_num) float32 (NaN for
        missing); rows past ``nrows`` are padding and never sampled."""
        rng = np.random.default_rng(
            (self.seed, self.process_index, self.step))
        self.step += 1
        prio = rng.random(nrows)
        if self.prio.size >= self.k:
            # only candidates that beat the current kth priority can enter
            tau = self.prio.min()
            cand = prio > tau
            if not cand.any():
                return
            rows = np.ascontiguousarray(x[:nrows][cand])
            prio = prio[cand]
        else:
            rows = np.ascontiguousarray(x[:nrows])
        self.values = np.concatenate([self.values, rows], axis=0)
        self.prio = np.concatenate([self.prio, prio])
        if self.prio.size > self.k:
            self._compact()

    def _compact(self) -> None:
        idx = np.argpartition(self.prio, -self.k)[-self.k:]
        self.values = np.ascontiguousarray(self.values[idx])
        self.prio = self.prio[idx]

    # -- merge (the commutative-monoid law; tests/test_sample.py) ----------

    def merge(self, other: "RowSampler") -> "RowSampler":
        if other.n_num != self.n_num:
            raise ValueError("cannot merge samplers over different schemas")
        self.values = np.concatenate([self.values, other.values], axis=0)
        self.prio = np.concatenate([self.prio, other.prio])
        if self.prio.size > self.k:
            self._compact()
        return self

    # -- finalize ----------------------------------------------------------

    def columns(self) -> Tuple[np.ndarray, np.ndarray]:
        """Per-column view shaped like the device sketch produced:
        (values (n_num, k) float64, kept (n_num, k) bool) with kept
        marking finite sampled values."""
        out = np.full((self.n_num, self.k), np.nan, dtype=np.float64)
        size = min(self.values.shape[0], self.k)
        if size:
            out[:, :size] = self.values[:size].T
        return out, np.isfinite(out)

    def quantiles(self, probes: Sequence[float]) -> np.ndarray:
        """(n_probes, n_num) float64 linear-interpolated quantiles of each
        column's finite sample; NaN where a column kept nothing."""
        vals, kept = self.columns()
        out = np.full((len(probes), self.n_num), np.nan)
        for c in range(self.n_num):
            v = vals[c, kept[c]]
            if v.size:
                out[:, c] = np.quantile(v, list(probes))
        return out

    def cdf_grid(self, n_grid: int) -> np.ndarray:
        """(n_num, n_grid) float32 per-column sample quantiles at probes
        (j+0.5)/n_grid — the rank grid for the pallas Spearman kernel
        (kernels/fused.spearman_update).  Columns with no finite sample
        are all +inf (their ranks collapse to 0 and the correlation
        finalizes to NaN via the zero-variance guard)."""
        vals, kept = self.columns()
        probes = (np.arange(n_grid) + 0.5) / n_grid
        out = np.full((self.n_num, n_grid), np.inf, dtype=np.float32)
        for c in range(self.n_num):
            v = vals[c, kept[c]]
            if v.size:
                out[c] = np.quantile(v, probes).astype(np.float32)
        return out

    def spearman(self) -> np.ndarray:
        """(n_num, n_num) pairwise-complete Spearman rank correlation of
        the sampled rows.  The sample is a uniform row sample (kept rows
        carry every lane jointly), so this estimates the full-data
        matrix with standard error ~1/sqrt(K) (~0.016 at K=4096); exact
        when the sample holds every row (n <= K).  Average ranks on
        ties — the same convention as scipy/pandas."""
        import pandas as pd
        if self.values.shape[0] < 2:
            return np.full((self.n_num, self.n_num), np.nan)
        df = pd.DataFrame(self.values)
        with np.errstate(invalid="ignore"):
            rho = df.corr(method="spearman").to_numpy()
        return rho

    def sorted_padded(self) -> Tuple[np.ndarray, np.ndarray]:
        """For the Spearman rank-CDF pass: per-column ascending finite
        sample padded with +inf to k, plus kept counts."""
        vals, kept = self.columns()
        padded = np.where(kept, vals, np.inf).astype(np.float32)
        return np.sort(padded, axis=1), kept.sum(axis=1).astype(np.int64)
